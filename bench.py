"""Round benchmark: Ed25519 tx-signature verification throughput per chip.

Mirrors BASELINE.json's headline metric. The CPU baseline (the reference's
libsodium-style per-signature path, threaded) is measured in-process on the
same workload, so vs_baseline = tpu_rate / cpu_rate.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# Apply the on-chip sweep's winning kernel configuration
# (tools/kernel_sweep.py writes KERNEL_TUNING.json) BEFORE any kernel
# module import reads the env. Explicit env settings win — the sweep
# itself sets them per subprocess. (crypto.backend imports no kernel
# module at import time, so this is safe to import here.)
from stellard_tpu.crypto.backend import apply_kernel_tuning  # noqa: E402

_TUNING = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "KERNEL_TUNING.json")
_t = apply_kernel_tuning(_TUNING)
_TUNED_BATCH: str | None = str(int(_t["batch"])) if _t else None


# provenance block attached to EVERY emitted JSON line (VERDICT r5: a
# CPU-fallback artifact must be self-explaining — an offline reader of
# BENCH_rNN.json needs to see WHAT ran, from WHICH tree, whether the
# device probe ever succeeded, and what the last real on-chip kernel
# rate was, without cross-referencing bench logs)
_PROBE_HISTORY: list = []


def _git_sha() -> str:
    import subprocess

    try:
        r = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        return r.stdout.strip() if r.returncode == 0 else "unknown"
    except Exception:  # noqa: BLE001 — provenance is best-effort
        return "unknown"


def _last_onchip() -> dict:
    """Last measured ON-CHIP kernel rate + its recorded date/source, from
    the sweep's KERNEL_TUNING.json (the only artifact that only ever
    carries device-measured rates)."""
    try:
        with open(_TUNING) as f:
            t = json.load(f)
        return {
            "rate_sigs_per_sec": t.get("rate"),
            "batch": t.get("batch"),
            "impl": t.get("impl"),
            "source_file": os.path.basename(_TUNING),
            # the sweep's note records the measurement date + chip
            "note": str(t.get("note", ""))[:200],
        }
    except (OSError, ValueError):
        return {"rate_sigs_per_sec": None, "source_file": None}


_PROVENANCE_BASE = {
    "git_sha": _git_sha(),
    "generated": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    "source_file": "bench.py",
    "last_onchip": _last_onchip(),
}

# storage backend the node legs persist through — storage results are
# meaningless without it, so EVERY emitted line carries the backend +
# durability mode in its provenance block; legs that drive a different
# store (tree_commit, storage_flush) override around their emits.
# Durability defaults to group-commit ("batch") for the node legs: the
# pre-segstore rounds ran cpplog behind an async write-behind thread
# (no per-close fsync), so batch mode is the like-for-like comparison;
# the fsync default's per-close barrier costs ~2x100ms on this box's
# 9p filesystem and is measured by the storage_flush leg explicitly.
_NODE_DB = os.environ.get("BENCH_NODE_DB", "segstore")
_NODE_DB_DURABILITY = os.environ.get("BENCH_NODE_DB_DURABILITY", "batch")
_STORAGE_INFO = {"backend": _NODE_DB, "durability": _NODE_DB_DURABILITY}


def _emit(obj: dict) -> None:
    obj.setdefault(
        "provenance",
        {**_PROVENANCE_BASE, "node_db": dict(_STORAGE_INFO),
         "probe_attempts": list(_PROBE_HISTORY)},
    )
    print(json.dumps(obj), flush=True)


# XLA:CPU logs a ~1.5KB "AOT result ... machine feature mismatch" warning
# EVERY time the persistent compilation cache replays a program compiled
# on a different machine — dozens of repeats per bench run, flooding the
# tail and displacing the JSON result line in combined-output consumers.
# The text is identical each time, so pass the FIRST occurrence through
# and swallow repeats (with a final count), keeping the tail readable and
# stdout's last line the metric JSON.
_NOISY_MARKERS = (
    "Machine type used for XLA:CPU compilation",
    "XLA:CPU AOT result",
)


def _install_stderr_dedupe() -> None:
    """fd-level stderr filter: the warning is written by C++ (absl/TSL)
    directly to fd 2, so a sys.stderr wrapper can't see it. Replace fd 2
    with a pipe drained by a daemon thread that dedupes the known-noisy
    lines and forwards everything else untouched."""
    import threading

    try:
        real_err = os.dup(2)
        r, w = os.pipe()
        os.dup2(w, 2)
        os.close(w)
    except OSError:
        return  # exotic fd setup: run unfiltered rather than break

    def _pump():
        seen = 0
        buf = b""
        try:
            with os.fdopen(r, "rb", buffering=0) as pipe:
                while True:
                    chunk = pipe.read(65536)
                    if not chunk:
                        break
                    buf += chunk
                    *lines, buf = buf.split(b"\n")
                    for line in lines:
                        noisy = any(
                            m.encode() in line for m in _NOISY_MARKERS
                        )
                        if noisy:
                            seen += 1
                            if seen > 1:
                                continue  # swallow repeats
                        os.write(real_err, line + b"\n")
                if buf:
                    os.write(real_err, buf)
                if seen > 1:
                    os.write(
                        real_err,
                        f"bench: suppressed {seen - 1} repeats of the "
                        f"XLA:CPU machine-feature warning\n".encode(),
                    )
        except OSError:
            # the real stderr went away (e.g. `2>&1 | head` consumer
            # exited) or the pipe broke: restore fd 2 so later writers
            # get the normal EPIPE behavior, not a dead filter
            try:
                os.dup2(real_err, 2)
            except OSError:
                pass

    t = threading.Thread(target=_pump, name="stderr-dedupe", daemon=True)
    t.start()

    def _restore():
        # point fd 2 back at the terminal: this drops the last reference
        # to the pipe's write end, the pump sees EOF, drains whatever is
        # buffered (a final traceback must not vanish with the filter),
        # prints its suppression summary, and exits before teardown
        try:
            os.dup2(real_err, 2)
        except OSError:
            return
        t.join(timeout=2.0)

    import atexit

    atexit.register(_restore)


# per-leg routing-model evidence (verify-plane get_json snapshots),
# written to BENCH_DETAIL.json next to this file: when a leg's ratio
# looks wrong, the model state (per-bucket device ms, cpu per-sig ms,
# batch counts, latency histograms) says WHY without a re-run
_DETAIL: dict = {}


def _note_detail(metric: str, backend: str, detail: dict) -> None:
    _DETAIL[f"{metric}:{backend}"] = detail


def _write_detail() -> None:
    if not _DETAIL:
        return
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_DETAIL.json")
        # merge, don't clobber: a partial invocation (BENCH_ONLY, a leg
        # re-run) must not erase the other legs' recorded evidence
        merged: dict = {}
        try:
            with open(path) as f:
                merged = json.load(f)
        except (OSError, ValueError):
            merged = {}
        merged.update(_DETAIL)
        with open(path, "w") as f:
            json.dump(merged, f, indent=1, default=str)
    except OSError:
        pass  # evidence is best-effort; the bench lines already printed


def _probe_device_backend(budget_s: float) -> bool:
    """Check, in throwaway subprocesses, that the pinned JAX backend comes up.

    The env pins JAX_PLATFORMS=axon (a real TPU via a tunnel). Init can fail
    fast (round-1 bench died on one UNAVAILABLE) or hang indefinitely when
    the tunnel is down — so each probe needs a hard wall-clock timeout, which
    an in-process try/except can't give us. The tunnel answers in WINDOWS
    (r4: one 240s attempt missed the window that opened minutes later and
    the round's official bench recorded a CPU fallback), so the probe keeps
    retrying until `budget_s` of wall clock is spent, not just one attempt.
    """
    import subprocess

    # memoized negative result (BENCH_r04: the 240s probe timeout was
    # re-paid by later probes in the same round): once a probe fails,
    # the failure is recorded in the env — inherited by every
    # subprocess leg — and re-probing is skipped for the rest of THIS
    # bench invocation. The provenance block shows the memo hit, so an
    # offline reader sees the fallback was decided once, not retried.
    if os.environ.get("BENCH_PROBE_MEMO") == "failed":
        _PROBE_HISTORY.append({"attempt": 0, "outcome": "memoized_failed"})
        return False

    per_attempt = max(
        30.0, float(os.environ.get("BENCH_PROBE_ATTEMPT_TIMEOUT", "120")))
    deadline = time.monotonic() + budget_s
    attempt = 0
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        # an attempt shorter than jax import + backend init is a
        # guaranteed-timeout fork; stop once the tail can't succeed
        if remaining <= 20.0:
            print(f"bench: backend probe budget ({budget_s:.0f}s) exhausted "
                  f"after {attempt - 1} attempts", file=sys.stderr)
            _PROBE_HISTORY.append(
                {"attempt": attempt, "outcome": "budget_exhausted"}
            )
            return False
        t_att = time.monotonic()
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True,
                timeout=min(per_attempt, remaining),
            )
            if r.returncode == 0:
                _PROBE_HISTORY.append({
                    "attempt": attempt, "outcome": "ok",
                    "elapsed_s": round(time.monotonic() - t_att, 1),
                })
                return True
            err = r.stderr.strip()
            print(f"bench: backend probe rc={r.returncode}: {err[-300:]}",
                  file=sys.stderr)
            _PROBE_HISTORY.append({
                "attempt": attempt, "outcome": f"rc={r.returncode}",
                "elapsed_s": round(time.monotonic() - t_att, 1),
                "stderr_tail": err[-160:],
            })
            # retrying only helps the windowed-tunnel failure mode
            # (hangs / transient UNAVAILABLE); a broken environment
            # fails identically every ~2s for the whole budget
            if ("ModuleNotFoundError" in err or "ImportError" in err
                    or "unknown backend" in err.lower()):
                return False
        except subprocess.TimeoutExpired:
            print(f"bench: backend probe attempt {attempt} timed out",
                  file=sys.stderr)
            _PROBE_HISTORY.append({
                "attempt": attempt, "outcome": "timeout",
                "elapsed_s": round(time.monotonic() - t_att, 1),
            })
        time.sleep(min(15.0, max(0.0, deadline - time.monotonic())))


def _init_device_backend() -> str:
    """Initialise a JAX backend, falling back to cpu so the bench always
    records a number. Returns the platform name actually in use."""
    pinned = os.environ.get("JAX_PLATFORMS", "")
    if pinned and pinned != "cpu":
        probe_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", "900"))
        if not _probe_device_backend(probe_s):
            print("bench: device backend unusable; falling back to cpu",
                  file=sys.stderr)
            os.environ["JAX_PLATFORMS"] = "cpu"
            # memoize the negative result for the round: later probes
            # in this invocation (and subprocess legs inheriting the
            # env) skip straight to the cpu fallback
            os.environ["BENCH_PROBE_MEMO"] = "failed"

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from stellard_tpu.utils.xlacache import enable_compilation_cache

    enable_compilation_cache()
    return jax.devices()[0].platform


# --------------------------------------------------------------------------
# BASELINE.md configs 1-5: each runs the same workload generator under
# signature_backend/hash_backend = cpu then tpu, so the cpu leg IS the
# reference baseline (the reference publishes no numbers, BASELINE.md).


def _payments(master, n, start_seq=1, dests=16):
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    outs = [KeyPair.from_passphrase(f"bench-dest-{i}").account_id
            for i in range(dests)]
    txs = []
    for i in range(n):
        # 250 STR: above the 200 STR genesis reserve, so the first payment
        # to each destination CREATES the account and every later one is a
        # real transfer. (1 STR payments tec'd with NO_DST_INSUF_STR on
        # every close — a fee-claim flood that also ran the close apply
        # twice per tx via the forced final pass.)
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, master.account_id, start_seq + i, 10,
            {sfAmount: STAmount.from_drops(250_000_000),
             sfDestination: outs[i % dests]},
        )
        tx.sign(master)
        txs.append(tx)
    return txs


def _fresh(txs):
    """Re-deserialize txs so per-object memoized signature verdicts
    (SerializedTransaction._sig_good) can't leak between backend legs."""
    from stellard_tpu.protocol.sttx import SerializedTransaction

    return [SerializedTransaction.from_bytes(t.serialize()) for t in txs]


def _drive_node(backend, txs, chunk=500, setup_phases=(), cfg_kwargs=None,
                max_inflight=None, pin_close_time=None):
    """Submit pre-signed txs through the full async pipeline (verify plane
    -> job queue -> open ledger), closing every `chunk`; -> wall seconds.
    `setup_phases` run first, one ledger close per phase, unmeasured.
    `max_inflight` caps unacknowledged submissions (windowed submit):
    below TX_BACKLOG_SHED the intake gate never drops a tx, which makes
    the run DETERMINISTIC — required when two legs must produce
    byte-identical ledgers (shedding is timing-dependent).
    The returned detail dict also carries close-path evidence: per-close
    latency p50, the final LCL hash, a digest of every per-tx close
    result, and the close-pipeline stats (for the pipelined-flood leg's
    serial-vs-pipelined comparison)."""
    import hashlib
    import threading

    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node

    # admission control stays ON but non-binding: these legs measure
    # at-capacity throughput with single-account chunks the adaptive
    # cap/account-chain limits would otherwise (nondeterministically)
    # shed, breaking the byte-identity discipline. The overload_flood
    # leg pins its own small caps and exercises the queue for real.
    cfg = {"txq_min_cap": 1_000_000, "txq_max_cap": 1_000_000,
           **(cfg_kwargs or {})}
    node = Node(
        Config(signature_backend=backend, **cfg)
    ).setup()
    if pin_close_time is not None:
        # deterministic close-time schedule (one resolution step per
        # close): two legs run minutes apart would otherwise round to
        # different close times and can never be byte-identical
        closes_done = [0]
        node.ops.network_time = lambda: pin_close_time + closes_done[0] * 30
    done = threading.Semaphore(0)

    if backend != "cpu" and node.verify_prewarm is not None:
        # the node already started the background prewarm (compile +
        # steady-state measurement per pad-bucket shape, discarded-
        # first-sample semantics in the routing model); a bench leg
        # wants a DETERMINISTIC warm start, so wait for it here — none
        # of this is inside the timed window
        node.verify_prewarm.join()

    def cb(tx, ter, applied):
        done.release()

    for phase in setup_phases:
        phase = _fresh(phase)
        for tx in phase:
            node.ops.submit_transaction(tx, cb)
        for _ in phase:
            done.acquire()
        node.ops.accept_ledger()

    txs = _fresh(txs)
    # device_share must measure the TIMED window only: zero the routing
    # counters so warm-up and setup-phase signatures don't mask a
    # routed-out device
    vp = node.verify_plane
    vp.device_sigs = vp.cpu_sigs = vp.verified = 0
    close_ms = []
    results_digest = hashlib.sha256()
    t0 = time.perf_counter()
    for start in range(0, len(txs), chunk):
        part = txs[start : start + chunk]
        inflight = 0
        for tx in part:
            if max_inflight is not None and inflight >= max_inflight:
                done.acquire()
                inflight -= 1
            node.ops.submit_transaction(tx, cb)
            inflight += 1
        for _ in range(inflight):
            done.acquire()
        c0 = time.perf_counter()
        closed, results = node.ops.accept_ledger()
        close_ms.append((time.perf_counter() - c0) * 1000.0)
        if pin_close_time is not None:
            closes_done[0] += 1
        for txid in sorted(results):
            results_digest.update(txid + bytes([int(results[txid]) & 0xFF]))
    # the timed window ends when all closes are DURABLE: drain the close
    # pipeline so pipelined throughput never counts unfinished persists
    node.close_pipeline.flush(timeout=300)
    dt = time.perf_counter() - t0
    committed = node.ledger_master.closed_ledger().seq
    detail = node.verify_plane.get_json()
    share = detail.get("device_share", 0.0)
    close_ms.sort()
    detail["close_p50_ms"] = round(close_ms[len(close_ms) // 2], 2) if close_ms else 0.0
    detail["lcl_hash"] = node.ledger_master.closed_ledger().hash().hex()
    detail["results_digest"] = results_digest.hexdigest()
    detail["close_pipeline"] = node.close_pipeline.get_json()
    detail["delta_replay"] = node.ledger_master.delta_replay_json()
    # batched-commit-plane honesty: drains/adoptions actually happened
    # (a 100%-unarmed run would show the old seal cost for the wrong
    # reason), plus the hash-plane routing snapshot when available
    detail["tree"] = node.ledger_master.tree_json()
    if hasattr(node.hasher, "get_json"):
        detail["hash_routing"] = node.hasher.get_json()
    node.stop()
    return dt, committed, share, detail


def bench_payment_flood(backends):
    """BASELINE config #1: standalone payment flood (test/send-test.js
    load, /root/reference/test/send-test.js)."""
    from stellard_tpu.protocol.keys import KeyPair

    n = int(os.environ.get("BENCH_FLOOD_N", "3000"))
    master = KeyPair.from_passphrase("masterpassphrase")
    txs = _payments(master, n)
    rates = {}
    shares = {}
    for b in backends:
        dt, _, shares[b], detail = _drive_node(b, txs)
        rates[b] = n / dt
        _note_detail("payment_flood_tx_per_sec", b, detail)
    _emit_config("payment_flood_tx_per_sec", rates, shares=shares)
    return rates


def bench_pipelined_flood(backends):
    """Close-pipeline leg: the payment flood driven twice on the host
    backend — serial close path ([close_pipeline] enabled=0, the
    pre-pipeline shape) vs pipelined (persistence overlapped with the
    next ledger's verify/apply) — reporting tx/s, close p50, and queue
    depth side by side, plus the equivalence evidence (byte-identical
    final LCL hash and per-tx result digest across modes).

    Unlike the other legs this one runs FILE-BACKED stores (cpplog
    nodestore + sqlite on disk): the pipeline's whole point is taking
    real storage writes (WAL commits, store appends) off the close path,
    and an in-memory store has no such tail to overlap."""
    import shutil
    import tempfile

    from stellard_tpu.protocol.keys import KeyPair

    n = int(os.environ.get("BENCH_FLOOD_N", "3000"))
    master = KeyPair.from_passphrase("masterpassphrase")
    txs = _payments(master, n)

    # interleaved best-of-K pairs (PERF.md's best-of convention): this
    # box's CPU allotment fluctuates ~3x between otherwise-identical
    # runs, so single A/B legs routinely invert; the best rep per mode
    # is the closest observable to the structural rate
    reps = max(1, int(os.environ.get("BENCH_PIPE_REPS", "3")))
    legs = {"serial": [], "pipelined": []}
    for _rep in range(reps):
        for mode, enabled in (("serial", False), ("pipelined", True)):
            # max_inflight under TX_BACKLOG_SHED: the intake gate never
            # sheds, so both modes apply the identical tx set and the
            # byte-identity check below is meaningful (shedding is
            # timing-dependent)
            state_dir = tempfile.mkdtemp(prefix=f"bench-pipe-{mode}-")
            try:
                dt, _, _, detail = _drive_node(
                    "cpu", txs,
                    cfg_kwargs={
                        "close_pipeline_enabled": enabled,
                        "database_path": os.path.join(state_dir, "bench.db"),
                        "node_db_type": _NODE_DB,
                        "node_db_durability": _NODE_DB_DURABILITY,
                        "node_db_path": os.path.join(state_dir, "nodestore"),
                    },
                    max_inflight=64,
                    # both legs close on the identical virtual clock so
                    # byte-identity is immune to wall-time rounding
                    pin_close_time=900_000_000,
                )
            finally:
                shutil.rmtree(state_dir, ignore_errors=True)
            legs[mode].append({"rate": n / dt, "detail": detail})
    _note_detail("pipelined_flood_tx_per_sec", "serial",
                 [leg["detail"] for leg in legs["serial"]])
    _note_detail("pipelined_flood_tx_per_sec", "pipelined",
                 [leg["detail"] for leg in legs["pipelined"]])

    ser = max(legs["serial"], key=lambda leg: leg["rate"])
    pip = max(legs["pipelined"], key=lambda leg: leg["rate"])
    all_details = [leg["detail"] for runs in legs.values() for leg in runs]
    _emit({
        "metric": "pipelined_flood_tx_per_sec",
        "value": round(pip["rate"], 2),
        "unit": "tx/s",
        # vs_baseline here = pipelined over serial (the leg's whole point)
        "vs_baseline": round(pip["rate"] / ser["rate"], 3) if ser["rate"] else 0.0,
        "serial_tx_per_sec": round(ser["rate"], 2),
        "reps": reps,
        "close_p50_ms": pip["detail"]["close_p50_ms"],
        "serial_close_p50_ms": ser["detail"]["close_p50_ms"],
        "queue_depth_hwm": pip["detail"]["close_pipeline"]["depth_hwm"],
        "backpressure_waits": pip["detail"]["close_pipeline"][
            "backpressure_waits"
        ],
        # byte-identical ledger hashes + per-tx results across EVERY rep
        # of BOTH modes (close times are pinned, shedding is disabled)
        "hashes_identical": len(
            {d["lcl_hash"] for d in all_details}
        ) == 1,
        "results_identical": len(
            {d["results_digest"] for d in all_details}
        ) == 1,
        "fallback": False,  # host-plane leg: no device involved
    })
    return legs


def bench_delta_replay_flood(backends):
    """Delta-replay close leg: the payment flood driven twice on the host
    backend — full serial close re-apply ([close] delta_replay=0, the r6
    pipelined baseline shape) vs speculative delta replay (open-pass
    read/write-set records spliced at close) — reporting tx/s, close
    p50, and the spliced/fallback/invalidated split side by side, plus
    byte-identity evidence across every rep of both modes (identical
    final LCL hash and per-tx result digest).

    Same harness discipline as the pipelined leg: FILE-BACKED stores,
    interleaved best-of-K reps, pinned close times, shedding disabled —
    the close-pipeline stays ON in both modes so the comparison isolates
    the apply pass, which is what delta replay attacks."""
    import shutil
    import tempfile

    from stellard_tpu.protocol.keys import KeyPair

    n = int(os.environ.get("BENCH_FLOOD_N", "3000"))
    master = KeyPair.from_passphrase("masterpassphrase")
    txs = _payments(master, n)

    reps = max(1, int(os.environ.get("BENCH_PIPE_REPS", "3")))
    legs = {"serial": [], "delta_replay": []}
    for _rep in range(reps):
        for mode, enabled in (("serial", False), ("delta_replay", True)):
            state_dir = tempfile.mkdtemp(prefix=f"bench-delta-{mode}-")
            try:
                dt, _, _, detail = _drive_node(
                    "cpu", txs,
                    cfg_kwargs={
                        "close_delta_replay": enabled,
                        "database_path": os.path.join(state_dir, "bench.db"),
                        "node_db_type": _NODE_DB,
                        "node_db_durability": _NODE_DB_DURABILITY,
                        "node_db_path": os.path.join(state_dir, "nodestore"),
                    },
                    max_inflight=64,
                    pin_close_time=900_000_000,
                )
            finally:
                shutil.rmtree(state_dir, ignore_errors=True)
            legs[mode].append({"rate": n / dt, "detail": detail})
    _note_detail("delta_replay_flood_tx_per_sec", "serial",
                 [leg["detail"] for leg in legs["serial"]])
    _note_detail("delta_replay_flood_tx_per_sec", "delta_replay",
                 [leg["detail"] for leg in legs["delta_replay"]])

    ser = max(legs["serial"], key=lambda leg: leg["rate"])
    dre = max(legs["delta_replay"], key=lambda leg: leg["rate"])
    all_details = [leg["detail"] for runs in legs.values() for leg in runs]
    dr = dre["detail"]["delta_replay"]

    # observability-overhead provenance: one extra delta-replay rep with
    # the WHOLE observability plane off — tracer, cross-node propagation,
    # metrics history, health watchdog ([trace] enabled=0 propagate=0,
    # [insight] history=0, [health] enabled=0). The main legs run the
    # node defaults (all four ON), so the all-on-vs-all-off close-p50
    # delta rides the provenance block of every line emitted from here
    # on, and drift past the 2% budget is visible without a dedicated
    # leg (doc/observability.md "overhead budget").
    state_dir = tempfile.mkdtemp(prefix="bench-delta-noobs-")
    try:
        _dt_nt, _, _, detail_nt = _drive_node(
            "cpu", txs,
            cfg_kwargs={
                "close_delta_replay": True,
                "trace_enabled": False,
                "trace_propagate": False,
                "insight_history": False,
                "health_enabled": False,
                "database_path": os.path.join(state_dir, "bench.db"),
                "node_db_type": _NODE_DB,
                "node_db_durability": _NODE_DB_DURABILITY,
                "node_db_path": os.path.join(state_dir, "nodestore"),
            },
            max_inflight=64,
            pin_close_time=900_000_000,
        )
    finally:
        shutil.rmtree(state_dir, ignore_errors=True)
    traced_p50 = dre["detail"]["close_p50_ms"]
    untraced_p50 = detail_nt["close_p50_ms"]
    _PROVENANCE_BASE["observability_overhead"] = {
        "close_p50_ms_all_on": traced_p50,
        "close_p50_ms_all_off": untraced_p50,
        "delta_ms": round(traced_p50 - untraced_p50, 2),
        "delta_pct": (
            round((traced_p50 / untraced_p50 - 1.0) * 100.0, 2)
            if untraced_p50 else None
        ),
        "budget_pct": 2.0,
        "plane": "trace+propagate+history+watchdog",
        # all-on is best-of-reps, all-off a single rep — treat small
        # negative deltas as noise, not a speedup
        "note": f"all-on best-of-{reps} vs all-off single rep",
    }
    _emit({
        "metric": "delta_replay_flood_tx_per_sec",
        "value": round(dre["rate"], 2),
        "unit": "tx/s",
        # vs_baseline = delta-replay over serial re-apply (same box,
        # same pinned workload, close pipeline on in both)
        "vs_baseline": round(dre["rate"] / ser["rate"], 3) if ser["rate"] else 0.0,
        "serial_tx_per_sec": round(ser["rate"], 2),
        "reps": reps,
        "close_p50_ms": dre["detail"]["close_p50_ms"],
        "serial_close_p50_ms": ser["detail"]["close_p50_ms"],
        # close-path storage evidence (ISSUE 7 bar: < 25 ms): the
        # persist worker's NodeStore flush p50 for the flood
        "persist_nodestore_p50_ms": dre["detail"]["close_pipeline"][
            "stages"]["nodestore"].get("p50_ms"),
        "close_apply_p50_ms": dr.get("apply_p50_ms"),
        "serial_close_apply_p50_ms": ser["detail"]["delta_replay"].get(
            "apply_p50_ms"
        ),
        # the splice/fallback split is the leg's honesty check: a 100%-
        # fallback run would show a ~1.0 ratio for the wrong reason
        "spliced": dr.get("spliced", 0),
        "fallback_applies": dr.get("fallback", 0),
        "invalidated": dr.get("invalidated", 0),
        "hashes_identical": len({d["lcl_hash"] for d in all_details}) == 1,
        "results_identical": len(
            {d["results_digest"] for d in all_details}
        ) == 1,
        "fallback": False,  # host-plane leg: no device involved
    })
    return legs


def _overload_payments(n, senders=32, fee_of=None):
    """Round-robin multi-account flood: `senders` accounts each paying a
    DISJOINT destination with sequential seqs (disjoint so delta-replay
    splices are not serialized through one hot account), fee tier per
    sender so the queue has something to order."""
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    kps = [KeyPair.from_passphrase(f"ovb-{i}") for i in range(senders)]
    dests = [KeyPair.from_passphrase(f"ovb-dest-{i}").account_id
             for i in range(senders)]
    fee_of = fee_of or (lambda i: 10 + (i % 7))
    txs = []
    per = -(-n // senders)
    for seq in range(1, per + 1):
        for i, kp in enumerate(kps):
            if len(txs) >= n:
                break
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, kp.account_id, seq, fee_of(i),
                {sfAmount: STAmount.from_drops(250_000_000),
                 sfDestination: dests[i]},
            )
            tx.sign(kp)
            txs.append(tx)
    return kps, txs


def _drive_overload(txs, senders, cap, chunk, txq_on, state_dir):
    """Flood driver with per-tx submit->validated latency tracking. The
    inter-close open window is modeled by waiting out the deferred
    queue speculation (unmeasured — production open windows are seconds
    long); the measured close is accept_ledger alone."""
    import threading

    from stellard_tpu.node.config import Config
    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    node = Node(Config(
        txq_enabled=txq_on,
        txq_min_cap=cap, txq_max_cap=cap,
        txq_ledgers_in_queue=8, txq_account_cap=128,
        database_path=os.path.join(state_dir, "bench.db"),
        node_db_type=_NODE_DB,
        node_db_durability=_NODE_DB_DURABILITY,
        node_db_path=os.path.join(state_dir, "nodestore"),
    )).setup()
    closes_done = [0]
    node.ops.network_time = lambda: 910_000_000 + closes_done[0] * 30
    done = threading.Semaphore(0)

    def cb(tx, ter, applied):
        done.release()

    # fund the senders, unmeasured (escalation-proof fee: never queues)
    master = node.master_keys
    for i, kp in enumerate(senders):
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, master.account_id, 1 + i, 10_000_000,
            {sfAmount: STAmount.from_drops(2_000_000_000),
             sfDestination: kp.account_id},
        )
        tx.sign(master)
        node.ops.submit_transaction(tx, cb)
    for _ in senders:
        done.acquire()
    node.ops.accept_ledger()
    closes_done[0] += 1

    def wait_spec_drain(timeout=5.0):
        # model the inter-close open window: the deferred promotion +
        # queue-aware speculation land before the next close fires
        if txq_on:
            node.txq.quiesce(timeout)

    txs = _fresh(txs)
    submit_at = {}
    latencies = []
    close_ms = []

    def close_once():
        c0 = time.perf_counter()
        _closed, results = node.ops.accept_ledger()
        c1 = time.perf_counter()
        close_ms.append((c1 - c0) * 1000.0)
        closes_done[0] += 1
        for txid in results:
            t_sub = submit_at.pop(txid, None)
            if t_sub is not None:
                latencies.append((c1 - t_sub) * 1000.0)

    t0 = time.perf_counter()
    for start in range(0, len(txs), chunk):
        part = txs[start:start + chunk]
        for tx in part:
            submit_at[tx.txid()] = time.perf_counter()
            node.ops.submit_transaction(tx, cb)
        for _ in part:
            done.acquire()
        wait_spec_drain()
        close_once()
    # drain: the queue empties through promotion (queue-off has none)
    for _ in range(32):
        if not txq_on or len(node.txq) == 0:
            break
        wait_spec_drain()
        close_once()
    node.close_pipeline.flush(timeout=300)
    dt = time.perf_counter() - t0

    close_sorted = sorted(close_ms)
    lat_sorted = sorted(latencies)

    def q(xs, p):
        return round(xs[min(len(xs) - 1, int(p * len(xs)))], 2) if xs else None

    detail = {
        "mode": "queue_on" if txq_on else "queue_off",
        "wall_s": round(dt, 3),
        "closes": len(close_ms),
        "close_p50_ms": q(close_sorted, 0.50),
        "close_p90_ms": q(close_sorted, 0.90),
        "close_max_ms": q(close_sorted, 1.0),
        "validated": len(latencies),
        "submitted": len(txs),
        "submit_to_validated_ms": {
            "p50": q(lat_sorted, 0.50),
            "p90": q(lat_sorted, 0.90),
            "p99": q(lat_sorted, 0.99),
        },
        "txq": node.txq.get_json(),
        "held": len(node.ledger_master.held),
        "delta_replay": node.ledger_master.delta_replay_json(),
    }
    node.stop()
    return detail


def bench_overload_flood(backends):
    """Admission-control leg: interleaved queue-on vs queue-off floods
    at 4x a pinned per-ledger capacity, plus a queue-on at-capacity
    reference run. The acceptance shape: queue-on keeps close p50
    within ~25% of its at-capacity value under the 4x flood (the soft
    cap + promotion bound every close) while queue-off's closes grow
    4x; submit->validated latency percentiles and eviction counts ride
    the emitted line. Host-plane leg (file-backed stores, pinned close
    times); `[txq]` caps are pinned (min_cap == max_cap) so "capacity"
    is a controlled constant, not an EWMA moving target."""
    import shutil
    import tempfile

    cap = int(os.environ.get("BENCH_OVERLOAD_CAP", "125"))
    n = int(os.environ.get("BENCH_FLOOD_N", "3000"))
    reps = max(1, int(os.environ.get("BENCH_OVERLOAD_REPS", "2")))
    senders, flood_txs = _overload_payments(n)
    _kps, cap_txs = _overload_payments(cap * max(4, n // (4 * cap)))

    legs = {"at_capacity_on": [], "flood_on": [], "flood_off": []}
    plans = (
        ("at_capacity_on", cap_txs, cap, True),
        ("flood_on", flood_txs, 4 * cap, True),
        ("flood_off", flood_txs, 4 * cap, False),
    )
    for _rep in range(reps):
        for mode, txs, chunk, txq_on in plans:
            state_dir = tempfile.mkdtemp(prefix=f"bench-ovl-{mode}-")
            try:
                legs[mode].append(_drive_overload(
                    txs, senders, cap, chunk, txq_on, state_dir
                ))
            finally:
                shutil.rmtree(state_dir, ignore_errors=True)
    for mode, runs in legs.items():
        _note_detail("overload_flood_close_p50_ms", mode, runs)

    best = {m: min(runs, key=lambda r: r["close_p50_ms"] or 1e9)
            for m, runs in legs.items()}
    atc = best["at_capacity_on"]["close_p50_ms"] or 0.0
    on = best["flood_on"]
    off = best["flood_off"]
    txq = on["txq"]
    promoted = txq["promoted"] or 1
    _emit({
        "metric": "overload_flood_close_p50_ms",
        "value": on["close_p50_ms"],
        "unit": "ms",
        # vs_baseline = queue-off p50 over queue-on p50 (>1: the queue
        # kept closes bounded while the uncapped node degraded)
        "vs_baseline": round(
            (off["close_p50_ms"] or 0.0) / (on["close_p50_ms"] or 1.0), 3
        ),
        "reps": reps,
        "capacity": cap,
        "flood_rate_x": 4,
        "at_capacity_close_p50_ms": atc,
        "within_pct_of_capacity": round(
            ((on["close_p50_ms"] or 0.0) / atc - 1.0) * 100.0, 1
        ) if atc else None,
        "queue_off_close_p50_ms": off["close_p50_ms"],
        "queue_off_close_max_ms": off["close_max_ms"],
        "submit_to_validated_ms_on": on["submit_to_validated_ms"],
        "submit_to_validated_ms_off": off["submit_to_validated_ms"],
        "validated_on": on["validated"],
        "validated_off": off["validated"],
        "evicted": txq["evicted"],
        "rejected": txq["rejected"],
        "promoted": txq["promoted"],
        "promote_spliced": txq["promote_spliced"],
        "promote_splice_rate": round(
            txq["promote_spliced"] / promoted, 3
        ),
        "held_pile": on["held"],
        "fallback": False,  # host-plane leg: no device involved
    })
    return legs


def _spec_flood_txs(n, senders=32, group=8):
    """Multi-account flood in per-sender RUNS of `group` sequential txs:
    one sender's sequence chain lands contiguously (a single worker
    chunk chains it tentatively), different senders are independent —
    the many-independent-users shape the worker pool scales on.
    test_parallel_spec pins the hot-account worst case; this leg
    measures the throughput ceiling. -> (fund_txs, work_txs)."""
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import sfAmount, sfDestination
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    master = KeyPair.from_passphrase("masterpassphrase")
    kps = [KeyPair.from_passphrase(f"spec-bench-{i}")
           for i in range(senders)]
    dests = [KeyPair.from_passphrase(f"spec-bench-d{i}").account_id
             for i in range(senders)]
    fund = []
    for i, kp in enumerate(kps):
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, master.account_id, 1 + i, 10,
            {sfAmount: STAmount.from_drops(50_000_000_000),
             sfDestination: kp.account_id},
        )
        tx.sign(master)
        fund.append(tx)
    work = []
    seqs = [1] * senders
    s = 0
    while len(work) < n:
        for _ in range(min(group, n - len(work))):
            kp = kps[s]
            tx = SerializedTransaction.build(
                TxType.ttPAYMENT, kp.account_id, seqs[s], 10,
                {sfAmount: STAmount.from_drops(250_000_000),
                 sfDestination: dests[s]},
            )
            tx.sign(kp)
            work.append(tx)
            seqs[s] += 1
        s = (s + 1) % senders
    return fund, work


def _spec_stage_run(workers, fund, work, chunk=500):
    """LedgerMaster-level speculation-stage measurement: submit `work`
    in `chunk`-sized open windows and time each window from first
    submit until EVERY speculation record is committed (serial: the
    submit loop itself; parallel: an advisory non-forcing drain of the
    worker session). The closes run outside the timed window — this
    isolates the stage the worker pool attacks. -> evidence dict."""
    import hashlib

    from stellard_tpu.engine.engine import TxParams
    from stellard_tpu.engine.specexec import SpecExecutor
    from stellard_tpu.node.ledgermaster import LedgerMaster
    from stellard_tpu.protocol.keys import KeyPair

    open_params = TxParams.OPEN_LEDGER | TxParams.RETRY
    master = KeyPair.from_passphrase("masterpassphrase")
    lm = LedgerMaster()
    ex = None
    if workers > 1:
        ex = lm.spec_executor = SpecExecutor(workers=workers,
                                             mode="process")
        ex.start()
    lm.start_new_ledger(master.account_id, close_time=900_000_000)
    hashes, close_ms = [], []
    digest = hashlib.sha256()
    n_close = 0
    try:
        def close():
            nonlocal n_close
            n_close += 1
            c0 = time.perf_counter()
            closed, results = lm.close_and_advance(
                900_000_000 + n_close * 30, 30
            )
            close_ms.append((time.perf_counter() - c0) * 1000.0)
            hashes.append(closed.hash().hex())
            for txid in sorted(results):
                digest.update(txid + bytes([int(results[txid]) & 0xFF]))

        for tx in _fresh(fund):
            lm.do_transaction(tx, open_params)
        close()

        work = _fresh(work)
        spec_wall = 0.0
        for start in range(0, len(work), chunk):
            part = work[start : start + chunk]
            t0 = time.perf_counter()
            for tx in part:
                lm.do_transaction(tx, open_params)
            if ex is not None:
                spec = getattr(lm.current, "_spec_state", None)
                session = getattr(spec, "_exec_session", None)
                if session is not None and not ex.drain(
                    session, timeout=300.0, force=False
                ):
                    raise RuntimeError("spec pool failed to drain")
            spec_wall += time.perf_counter() - t0
            if ex is not None:
                # seal prep, not speculation: flush the fold burst to
                # the background pre-hasher before closing (the node's
                # accept_ledger pre-drain does the same)
                lm.kick_seal_drain(wait_s=1.0)
            close()
        close_ms.sort()
        return {
            "spec_rate": len(work) / spec_wall,
            "close_p50_ms": round(close_ms[len(close_ms) // 2], 2),
            "hashes": tuple(hashes),
            "results_digest": digest.hexdigest(),
            "delta": dict(lm.delta_stats),
            "spec": ex.get_json() if ex is not None else None,
        }
    finally:
        if ex is not None:
            ex.stop()
        # the incremental-seal drainer was lazily started by the fold
        # bursts; without this each rep leaks a daemon thread pinning
        # its whole LedgerMaster (and fork-based executors in later
        # runs would fork with those threads live)
        lm.stop_seal_drainer()


def bench_parallel_spec_flood(backends):
    """Parallel speculative execution leg ([spec] workers=N,
    engine/specexec.py). Two measurements, both interleaved best-of-K
    at workers 1/2/4:

    - **speculation throughput** (the headline): LedgerMaster-level
      windows timed from first submit until every speculation record is
      committed — the stage the Block-STM pool attacks, isolated from
      verify/persist. Serial speculation runs inline on the submit
      thread; the pool overlaps it with the open-ledger applies.
    - **full-node flood** (file-backed stores, pinned close times, the
      delta_replay_flood harness discipline): end-to-end tx/s and close
      p50 with the whole pipeline around the pool.

    Byte identity is asserted at BOTH levels across every worker count
    and every rep (per-close ledger hashes + per-tx result digests),
    and the splice/abort/retry split rides the emitted line — a leg
    that scaled by falling back serially would show it here."""
    import shutil
    import tempfile

    n = int(os.environ.get("BENCH_SPEC_N", "2000"))
    reps = max(1, int(os.environ.get("BENCH_SPEC_REPS", "3")))
    worker_counts = (1, 2, 4)
    fund, work = _spec_flood_txs(n)

    stage = {w: [] for w in worker_counts}
    for _rep in range(reps):
        for w in worker_counts:
            stage[w].append(_spec_stage_run(w, fund, work))
    for w, runs in stage.items():
        _note_detail("parallel_spec_flood_spec_tx_per_sec",
                     f"workers{w}", runs)

    node = {w: [] for w in worker_counts}
    for _rep in range(reps):
        for w in worker_counts:
            state_dir = tempfile.mkdtemp(prefix=f"bench-spec-w{w}-")
            try:
                dt, _, _, detail = _drive_node(
                    "cpu", work,
                    setup_phases=(fund,),
                    cfg_kwargs={
                        "spec_workers": w,
                        "spec_mode": "process",
                        "database_path": os.path.join(state_dir,
                                                      "bench.db"),
                        "node_db_type": _NODE_DB,
                        "node_db_durability": _NODE_DB_DURABILITY,
                        "node_db_path": os.path.join(state_dir,
                                                     "nodestore"),
                    },
                    max_inflight=64,
                    pin_close_time=900_000_000,
                )
            finally:
                shutil.rmtree(state_dir, ignore_errors=True)
            node[w].append({"rate": n / dt, "detail": detail})

    # byte identity across every run of every config, both levels
    stage_ids = {(r["hashes"], r["results_digest"])
                 for runs in stage.values() for r in runs}
    node_ids = {(leg["detail"]["lcl_hash"],
                 leg["detail"]["results_digest"])
                for runs in node.values() for leg in runs}

    best_stage = {w: max(runs, key=lambda r: r["spec_rate"])
                  for w, runs in stage.items()}
    best_node = {w: max(runs, key=lambda r: r["rate"])
                 for w, runs in node.items()}
    s1, s4 = best_stage[1], best_stage[4]
    spec4 = s4["spec"] or {}
    d4 = s4["delta"]
    _emit({
        "metric": "parallel_spec_flood_spec_tx_per_sec",
        "value": round(s4["spec_rate"], 2),
        "unit": "tx/s",
        # vs_baseline = workers=4 speculation throughput over the
        # serial inline path (same workload, same box)
        "vs_baseline": round(s4["spec_rate"] / s1["spec_rate"], 3),
        "reps": reps,
        "spec_tx_per_sec": {
            str(w): round(best_stage[w]["spec_rate"], 2)
            for w in worker_counts
        },
        "stage_close_p50_ms": {
            str(w): best_stage[w]["close_p50_ms"] for w in worker_counts
        },
        "node_tx_per_sec": {
            str(w): round(best_node[w]["rate"], 2) for w in worker_counts
        },
        "node_close_p50_ms": {
            str(w): best_node[w]["detail"]["close_p50_ms"]
            for w in worker_counts
        },
        # honesty split: the scaling must come from optimistic commits,
        # not from everything draining through the serial fallback
        "spliced": d4.get("spliced", 0),
        "fallback_applies": d4.get("fallback", 0),
        "committed": spec4.get("committed", 0),
        "retries": spec4.get("retries", 0),
        "validation_aborts": spec4.get("validation_aborts", 0),
        "serial_fallbacks": spec4.get("serial_fallbacks", 0),
        "drains_forced": spec4.get("drains_forced", 0),
        # transport provenance (ISSUE 16): which wire the pool rode —
        # shared-memory rings by default — plus the ring counters so a
        # "ring" run that actually moved nothing is self-refuting
        "transport": spec4.get("transport"),
        "ring": spec4.get("ring"),
        "hashes_identical": len(stage_ids) == 1,
        "node_hashes_identical": len(node_ids) == 1,
        # scaling context: the pool's ceiling is min(cores - 1, GIL
        # headroom of the submit+commit parent) — on a 2-core host the
        # parent alone saturates both, so expect ~parity, not Nx
        "host_cpus": os.cpu_count(),
        "fallback": False,  # host-plane leg: no device involved
    })
    return stage, node


def bench_tree_commit(backends):
    """State-tree commit-plane leg: apply the SAME 3000-write delta to a
    populated state tree via per-key set_item/del_item (the pre-PR
    splice shape) vs ONE sorted bulk merge (SHAMap.bulk_update), then
    seal (batched tree hash) and flush into a FILE-BACKED cpplog store.
    Interleaved best-of-K; byte-identity (root hash + flushed node
    count) asserted per rep. vs_baseline = per-key merge time over bulk
    merge time — the tentpole's headline ratio. The hash-plane routing
    snapshot and device share ride BENCH_DETAIL.json like the verify
    legs, so a routed-out device is self-explaining."""
    import hashlib
    import shutil
    import tempfile

    from stellard_tpu.crypto.backend import make_watched_hasher
    from stellard_tpu.nodestore import NodeObjectType, make_database
    from stellard_tpu.state.shamap import SHAMap, SHAMapItem, TNType

    n_base = int(os.environ.get("BENCH_TREE_BASE", "20000"))
    n_delta = int(os.environ.get("BENCH_TREE_DELTA", "3000"))
    n_del = n_delta // 10
    reps = max(1, int(os.environ.get("BENCH_PIPE_REPS", "3")))

    def key(tag: str, i: int) -> bytes:
        return hashlib.sha256(f"tree-commit:{tag}:{i}".encode()).digest()

    base_items = [
        SHAMapItem(key("base", i), hashlib.sha512(key("base", i)).digest())
        for i in range(n_base)
    ]
    # delta: half overwrite existing keys, half create new; deletes hit
    # existing keys the sets don't touch (adversarial for collapse)
    sets = [
        SHAMapItem(
            key("base", i) if i % 2 == 0 else key("new", i),
            hashlib.sha512(key("delta", i)).digest() * 2,
        )
        for i in range(n_delta)
    ]
    deletes = [key("base", n_base - 1 - i) for i in range(n_del)]

    for b in backends:
        hasher = make_watched_hasher(b)
        base = SHAMap(TNType.ACCOUNT_STATE, hash_batch=hasher)
        base.bulk_update(base_items)
        base.get_hash()
        base_root = base.root

        state_dir = tempfile.mkdtemp(prefix="bench-tree-")
        db = make_database(
            type="cpplog", path=os.path.join(state_dir, "nodestore")
        )
        # base tree pre-flushed ONCE (unmeasured): each rep's timed
        # flush then writes the delta only, like a close does — the
        # per-rep `known` copy re-drives the delta writes while the
        # content-addressed store dedupes repeats
        base.flush(
            db.store_fn(NodeObjectType.ACCOUNT_NODE), db.flushed,
            store_many=db.store_many_fn(NodeObjectType.ACCOUNT_NODE),
        )
        db.sync()
        base_known = set(db.flushed)

        legs = {"per_key": [], "bulk": []}
        identical = True
        try:
            for _rep in range(reps):
                rep_hashes = {}
                for mode in ("per_key", "bulk"):
                    hasher.device_nodes = hasher.host_nodes = 0
                    known = set(base_known)
                    m = SHAMap(TNType.ACCOUNT_STATE, base_root,
                               hash_batch=hasher)
                    t0 = time.perf_counter()
                    if mode == "bulk":
                        m.bulk_update(sets, deletes)
                    else:
                        for item in sets:
                            m.set_item(SHAMapItem(item.tag, item.data))
                        for k in deletes:
                            m.del_item(k)
                    t_merge = time.perf_counter()
                    m.get_hash()
                    t_hash = time.perf_counter()
                    flushed = m.flush(
                        db.store_fn(NodeObjectType.ACCOUNT_NODE), known,
                        store_many=db.store_many_fn(
                            NodeObjectType.ACCOUNT_NODE
                        ),
                    )
                    db.sync()
                    t_flush = time.perf_counter()
                    rep_hashes[mode] = (m.get_hash(), flushed)
                    legs[mode].append({
                        "merge_s": t_merge - t0,
                        "hash_s": t_hash - t_merge,
                        "flush_s": t_flush - t_hash,
                        "total_s": t_flush - t0,
                        "device_nodes": hasher.device_nodes,
                        "host_nodes": hasher.host_nodes,
                    })
                identical = identical and (
                    rep_hashes["per_key"] == rep_hashes["bulk"]
                )
        finally:
            db.close()
            shutil.rmtree(state_dir, ignore_errors=True)

        best_pk = min(legs["per_key"], key=lambda r: r["merge_s"])
        best_bk = min(legs["bulk"], key=lambda r: r["merge_s"])
        dev = sum(r["device_nodes"] for r in legs["bulk"])
        host = sum(r["host_nodes"] for r in legs["bulk"])
        detail = {
            "per_key": legs["per_key"],
            "bulk": legs["bulk"],
            "device_share": (dev / (dev + host)) if dev + host else 0.0,
        }
        if hasattr(hasher, "get_json"):
            detail["hash_routing"] = hasher.get_json()
        _note_detail("tree_commit_writes_per_sec", b, detail)
        n_ops = n_delta + n_del
        # this leg drives a cpplog store directly (comparable with the
        # r8 numbers); its provenance must say so, not the node default
        _STORAGE_INFO.update(backend="cpplog", durability="fsync")
        _emit({
            "metric": "tree_commit_writes_per_sec",
            "value": round(n_ops / best_bk["merge_s"], 1),
            "unit": "writes/s",
            # the leg's whole point: bulk merge over per-key application
            "vs_baseline": round(
                best_pk["merge_s"] / best_bk["merge_s"], 3
            ),
            "per_key_writes_per_sec": round(n_ops / best_pk["merge_s"], 1),
            "reps": reps,
            "backend": b,
            "base_entries": n_base,
            "delta_writes": n_delta,
            "delta_deletes": n_del,
            "seal_ms": round(best_bk["hash_s"] * 1000.0, 2),
            "flush_ms": round(best_bk["flush_s"] * 1000.0, 2),
            "hashes_identical": identical,
            "device_share": round(detail["device_share"], 4),
            "fallback": b == "cpu",
        })
    _STORAGE_INFO.update(backend=_NODE_DB, durability=_NODE_DB_DURABILITY)


def bench_storage_flush(backends):
    """Storage-plane flush leg (the segstore tentpole's headline): the
    SAME sequence of per-close tree deltas flushed into each durable
    backend × durability mode, timing ONLY the flush (trees pre-hashed,
    stores synchronous). vs_baseline on the segstore-fsync line is
    cpplog_p50 / segstore_p50 at EQUAL durability (fsync per batch) —
    the ISSUE's ≥3× bar. Byte identity is pinned every rep: every
    flushed node is fetched back and compared, and the final root is
    re-materialized from the store with content verification on
    (from_store, cache off). Open cost rides the detail: close + reopen
    per config, recording open_ms and the replayed-record count (tail
    only when the checkpoint landed)."""
    import hashlib
    import shutil
    import tempfile

    from stellard_tpu.nodestore import NodeObjectType, make_database
    from stellard_tpu.state.shamap import SHAMap, SHAMapItem, TNType

    # leg-local base size: the per-key baseline pays ~4ms/record on this
    # box's 9p filesystem, so the unmeasured base pre-flush dominates
    # wall time at tree_commit's 20k default
    n_base = int(os.environ.get("BENCH_STORE_BASE", "10000"))
    n_delta = int(os.environ.get("BENCH_TREE_DELTA", "3000"))
    n_flushes = int(os.environ.get("BENCH_STORAGE_FLUSHES", "8"))
    reps = max(1, int(os.environ.get("BENCH_STORAGE_REPS", "2")))

    def key(tag: str, i: int) -> bytes:
        return hashlib.sha256(f"storage-flush:{tag}:{i}".encode()).digest()

    # base tree + a chain of per-"close" deltas (2/3 fresh keys, 1/3
    # overwrites), all pre-hashed so the timed window is flush-only
    base = SHAMap(TNType.ACCOUNT_STATE)
    base.bulk_update([
        SHAMapItem(key("base", i), hashlib.sha512(key("base", i)).digest())
        for i in range(n_base)
    ])
    base.get_hash()
    trees = []
    prev = base
    for f in range(n_flushes):
        sets = [
            SHAMapItem(
                key(f"d{f}", j) if j % 3 else key("base", (f * 997 + j)
                                                 % n_base),
                hashlib.sha512(key(f"v{f}", j)).digest() * 2,
            )
            for j in range(n_delta)
        ]
        t = SHAMap(TNType.ACCOUNT_STATE, prev.root)
        t.bulk_update(sets)
        t.get_hash()
        trees.append(t)
        prev = t

    configs = [
        ("cpplog", "fsync", {}),
        ("segstore", "fsync", {"durability": "fsync"}),
        ("segstore", "batch", {"durability": "batch"}),
        ("segstore", "async", {"durability": "async"}),
        ("sqlite", "normal", {}),
    ]
    results = {}
    for _rep in range(reps):
        for store_type, mode, kw in configs:
            name = f"{store_type}-{mode}"
            state_dir = tempfile.mkdtemp(prefix=f"bench-store-{name}-")
            try:
                try:
                    db = make_database(
                        type=store_type,
                        path=os.path.join(state_dir, "nodestore"),
                        async_writes=False, **kw,
                    )
                except (RuntimeError, OSError) as e:
                    results.setdefault(name, {})["error"] = repr(e)[:120]
                    continue
                r = results.setdefault(
                    name, {"flush_ms": [], "bytes": 0, "nodes": 0,
                           "identical": True},
                )
                base.flush(  # unmeasured: each timed flush is delta-only
                    db.store_fn(NodeObjectType.ACCOUNT_NODE), db.flushed,
                    store_packed=db.store_packed_fn(
                        NodeObjectType.ACCOUNT_NODE
                    ),
                )
                db.sync()
                for t in trees:
                    recorded = []
                    packed = db.store_packed_fn(NodeObjectType.ACCOUNT_NODE)

                    def sink(hashes, buf, offsets, _p=packed,
                             _r=recorded):
                        _r.append((list(hashes), buf, list(offsets)))
                        return _p(hashes, buf, offsets)

                    t0 = time.perf_counter()
                    n_nodes = t.flush(
                        db.store_fn(NodeObjectType.ACCOUNT_NODE),
                        db.flushed, store_packed=sink,
                    )
                    dt = time.perf_counter() - t0
                    r["flush_ms"].append(dt * 1000.0)
                    r["nodes"] += n_nodes
                    # byte identity OUTSIDE the timed window: every
                    # flushed node fetches back byte-equal
                    for hashes, buf, offsets in recorded:
                        r["bytes"] += offsets[-1]
                        for i, h in enumerate(hashes):
                            got = db.fetch(h)
                            if got is None or \
                                    got.data != buf[offsets[i]:
                                                    offsets[i + 1]]:
                                r["identical"] = False
                # root identity: re-materialize the final tree from the
                # store, content verification on, memo OFF (a cache hit
                # must not mask a store miss)
                final_root = trees[-1].get_hash()
                db.sync()
                rebuilt = SHAMap.from_store(
                    final_root,
                    lambda h: (lambda o: o.data if o else None)(
                        db.fetch(h)
                    ),
                    verify=True, use_cache=False,
                )
                r["identical"] = r["identical"] and (
                    rebuilt.get_hash() == final_root
                )
                db.close()
                t0 = time.perf_counter()
                db2 = make_database(
                    type=store_type,
                    path=os.path.join(state_dir, "nodestore"),
                    async_writes=False, **kw,
                )
                r["open_ms"] = round((time.perf_counter() - t0) * 1000.0,
                                     2)
                stats = getattr(db2.backend, "get_json", dict)()
                r["replayed_records"] = stats.get("replayed_records")
                r["opened_from_checkpoint"] = stats.get(
                    "opened_from_checkpoint"
                )
                r["identical"] = r["identical"] and (
                    db2.fetch(final_root) is not None
                )
                db2.close()
            finally:
                shutil.rmtree(state_dir, ignore_errors=True)

    def q(xs, p):
        xs = sorted(xs)
        return round(xs[min(len(xs) - 1, int(len(xs) * p))], 3)

    _note_detail("storage_flush_p50_ms", "all", results)
    baseline_p50 = None
    if results.get("cpplog-fsync", {}).get("flush_ms"):
        baseline_p50 = q(results["cpplog-fsync"]["flush_ms"], 0.5)
    for store_type, mode, _kw in configs:
        name = f"{store_type}-{mode}"
        r = results.get(name, {})
        if not r.get("flush_ms"):
            _emit({"metric": "storage_flush_p50_ms", "value": 0.0,
                   "unit": "skipped", "vs_baseline": 0.0, "mode": name,
                   "error": r.get("error", "no samples")})
            continue
        p50 = q(r["flush_ms"], 0.5)
        total_s = sum(r["flush_ms"]) / 1000.0
        _STORAGE_INFO.update(backend=store_type, durability=mode)
        _emit({
            "metric": "storage_flush_p50_ms",
            "value": p50,
            "unit": "ms",
            "lower_is_better": True,
            # the tentpole's bar: how many times faster than the
            # file-backed per-key store at the same durability (only
            # the fsync-mode line compares like with like)
            "vs_baseline": (
                round(baseline_p50 / p50, 3) if baseline_p50 else 0.0
            ),
            "mode": name,
            "flush_p99_ms": q(r["flush_ms"], 0.99),
            "mb_per_sec": round(r["bytes"] / total_s / 1e6, 2)
            if total_s else 0.0,
            "flushes": len(r["flush_ms"]),
            "nodes_flushed": r["nodes"],
            "bytes_flushed": r["bytes"],
            "open_ms": r.get("open_ms"),
            "replayed_records": r.get("replayed_records"),
            "opened_from_checkpoint": r.get("opened_from_checkpoint"),
            "identical": r["identical"],
            "reps": reps,
            "fallback": False,  # host-plane leg: no device involved
        })
    _STORAGE_INFO.update(backend=_NODE_DB, durability=_NODE_DB_DURABILITY)


def _offer_workload(n):
    """-> (setup_txs, work_txs): funding + trustlines, then an
    OfferCreate/OfferCancel mix with crossing price ladders."""
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import (
        sfAmount,
        sfDestination,
        sfLimitAmount,
        sfOfferSequence,
        sfTakerGets,
        sfTakerPays,
    )
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    master = KeyPair.from_passphrase("masterpassphrase")
    gateway = KeyPair.from_passphrase("bench-gateway")
    traders = [KeyPair.from_passphrase(f"bench-trader-{i}") for i in range(8)]
    USD = b"USD" + b"\x00" * 17

    fund = []
    seq = 1
    for who in [gateway] + traders:
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, master.account_id, seq, 10,
            {sfAmount: STAmount.from_drops(1_000_000_000),
             sfDestination: who.account_id},
        )
        tx.sign(master)
        fund.append(tx)
        seq += 1
    trust = []
    seqs = {}
    for t in traders:
        tx = SerializedTransaction.build(
            TxType.ttTRUST_SET, t.account_id, 1, 10,
            {sfLimitAmount: STAmount.from_iou(USD, gateway.account_id, 10**9, 0)},
        )
        tx.sign(t)
        trust.append(tx)
        seqs[t.account_id] = 2
    # phases must be separated by closes: the open ledger runs checks
    # only, so a tx depending on another account's same-ledger creation
    # would fail rather than hold
    setup = [fund, trust]

    seqs[gateway.account_id] = 1
    work = []
    live_offers = []  # (account, seq) for cancels
    for i in range(n):
        if i % 5 == 4 and live_offers:
            who, oseq = live_offers.pop(0)
            tx = SerializedTransaction.build(
                TxType.ttOFFER_CANCEL, who.account_id,
                seqs[who.account_id], 10, {sfOfferSequence: oseq},
            )
            tx.sign(who)
            seqs[who.account_id] += 1
        elif i % 2 == 0:
            # gateway sells its own USD for XRP (always funded)
            price = 50 + (i % 20)
            gw_seq = seqs[gateway.account_id]
            tx = SerializedTransaction.build(
                TxType.ttOFFER_CREATE, gateway.account_id, gw_seq, 10,
                {sfTakerPays: STAmount.from_drops(price * 1_000_000),
                 sfTakerGets: STAmount.from_iou(USD, gateway.account_id, 100, 0)},
            )
            tx.sign(gateway)
            live_offers.append((gateway, gw_seq))
            seqs[gateway.account_id] += 1
        else:
            who = traders[i % len(traders)]
            price = 40 + (i % 25)  # overlaps the ask ladder -> crossings
            tx = SerializedTransaction.build(
                TxType.ttOFFER_CREATE, who.account_id,
                seqs[who.account_id], 10,
                {sfTakerPays: STAmount.from_iou(USD, gateway.account_id, 100, 0),
                 sfTakerGets: STAmount.from_drops(price * 1_000_000)},
            )
            tx.sign(who)
            live_offers.append((who, seqs[who.account_id]))
            seqs[who.account_id] += 1
        work.append(tx)
    return setup, work


def bench_ooc_state(backends):
    """Out-of-core state plane (ISSUE 13): a ≥5M-account ledger state
    under a flood-shaped write workload, opened three ways — eager
    (all-in-RAM baseline), lazy with an unbounded hot-node cache, and
    lazy with the capped [tree] cache_mb hot set. Each mode runs in its
    OWN subprocess (clean RSS accounting) against one shared store
    built once on disk (tools/oocbench.py). The bars: per-close ROOTS
    byte-identical across all three modes in every rep, capped-mode
    RSS bounded near the hot set, steady-state close p50 within 15% of
    the eager baseline. Host-plane leg: no device involved."""
    import shutil
    import subprocess
    import tempfile

    accounts = int(os.environ.get("BENCH_OOC_ACCOUNTS", "5000000"))
    closes = int(os.environ.get("BENCH_OOC_CLOSES", "30"))
    writes = int(os.environ.get("BENCH_OOC_WRITES", "200"))
    keep_dir = os.environ.get("BENCH_OOC_DIR", "")
    d = keep_dir or tempfile.mkdtemp(prefix="oocbench-")
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "oocbench.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # host-plane leg

    def run(args, timeout=7200):
        r = subprocess.run(
            [sys.executable, tool, "--dir", d,
             "--accounts", str(accounts), *args],
            capture_output=True, text=True, timeout=timeout, env=env,
        )
        if r.returncode != 0:
            raise RuntimeError(f"oocbench {args}: {r.stderr[-300:]}")
        return json.loads(r.stdout.strip().splitlines()[-1])

    try:
        run(["--build-only"])
        results = {}
        for mode in ("eager", "uncapped", "capped"):
            results[mode] = run([
                "--mode", mode, "--closes", str(closes),
                "--writes", str(writes),
            ])

        def p50(res):
            cm = sorted(res["close_ms"])
            return cm[len(cm) // 2]

        # byte-identity across ALL reps (warmup closes included): the
        # three modes replay one seeded workload, so any divergence is
        # a faulting bug, not noise
        roots_ok = (
            results["eager"]["roots"] == results["uncapped"]["roots"]
            == results["capped"]["roots"]
        )
        eager_p50 = p50(results["eager"])
        capped_p50 = p50(results["capped"])
        from tools.oocbench import CACHE_CAPPED_MB

        _emit({
            "metric": "ooc_state_close_p50_ms",
            "value": round(capped_p50, 2),
            "unit": "ms",
            # lower-is-better ratio: >= 0.87 means the capped run holds
            # within 15% of the all-in-RAM baseline
            "vs_baseline": round(eager_p50 / capped_p50, 3)
            if capped_p50 else 0.0,
            "cpu_baseline": round(eager_p50, 2),
            "accounts": accounts,
            "closes": closes,
            "writes_per_close": writes,
            "capped_cache_mb": CACHE_CAPPED_MB,
            "roots_identical_all_reps": roots_ok,
            "rss_mb": {
                m: results[m]["rss_mb_final"] for m in results
            },
            "load_s": {m: results[m]["load_s"] for m in results},
            "cache": {
                m: {
                    k: results[m]["cache"][k]
                    for k in ("faults", "evictions", "resident_bytes",
                              "hits", "misses")
                }
                for m in results
            },
            "fallback": False,  # host-plane leg: no device involved
        })
        _note_detail("ooc_state", "host", results)
    finally:
        if not keep_dir:
            shutil.rmtree(d, ignore_errors=True)


def bench_offer_mix(backends):
    """BASELINE config #2: OfferCreate/OfferCancel order-book mix
    (test/offer-test.js)."""
    n = int(os.environ.get("BENCH_OFFER_N", "1500"))
    setup, work = _offer_workload(n)

    rates = {}
    shares = {}
    for b in backends:
        dt, _, shares[b], detail = _drive_node(
            b, work, chunk=300, setup_phases=setup
        )
        rates[b] = len(work) / dt
        _note_detail("offer_mix_tx_per_sec", b, detail)
    _emit_config("offer_mix_tx_per_sec", rates, shares=shares)
    return rates


def _regular_key_workload(n, holders=24):
    """BASELINE config #3 workload: `holders` accounts each set a
    RegularKey, then flood AccountSet txs SIGNED WITH THE REGULAR KEY —
    every tx exercises the regular-key authority branch of checkSig
    (reference: Transactor::checkSig master-vs-regular, :151-180)."""
    from stellard_tpu.protocol.formats import TxType
    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.protocol.sfields import (
        sfAmount,
        sfDestination,
        sfRegularKey,
        sfTransferRate,
    )
    from stellard_tpu.protocol.stamount import STAmount
    from stellard_tpu.protocol.sttx import SerializedTransaction

    master = KeyPair.from_passphrase("masterpassphrase")
    accounts = [KeyPair.from_passphrase(f"bench-rk-{i}") for i in range(holders)]
    regulars = [KeyPair.from_passphrase(f"bench-rk-reg-{i}") for i in range(holders)]

    fund = []
    for i, who in enumerate(accounts):
        tx = SerializedTransaction.build(
            TxType.ttPAYMENT, master.account_id, i + 1, 10,
            {sfAmount: STAmount.from_drops(1_000_000_000),
             sfDestination: who.account_id},
        )
        tx.sign(master)
        fund.append(tx)
    setkeys = []
    for who, reg in zip(accounts, regulars):
        tx = SerializedTransaction.build(
            TxType.ttREGULAR_KEY_SET, who.account_id, 1, 10,
            {sfRegularKey: reg.account_id},
        )
        tx.sign(who)
        setkeys.append(tx)

    work = []
    seqs = [2] * holders
    for i in range(n):
        k = i % holders
        tx = SerializedTransaction.build(
            TxType.ttACCOUNT_SET, accounts[k].account_id, seqs[k], 10,
            {sfTransferRate: 1_000_000_000 + (i % 7) * 1_000_000},
        )
        tx.sign(regulars[k])  # regular-key signature
        seqs[k] += 1
        work.append(tx)
    return [fund, setkeys], work


def bench_regular_key_fanout(backends):
    """BASELINE config #3: SetRegularKey + AccountSet verify fan-out."""
    n = int(os.environ.get("BENCH_RK_N", "1500"))
    setup, work = _regular_key_workload(n)
    rates = {}
    shares = {}
    for b in backends:
        dt, _, shares[b], detail = _drive_node(
            b, work, chunk=300, setup_phases=setup
        )
        rates[b] = len(work) / dt
        _note_detail("regular_key_fanout_tx_per_sec", b, detail)
    _emit_config("regular_key_fanout_tx_per_sec", rates, shares=shares)
    return rates


def bench_consensus_close(backends):
    """BASELINE config #4: 4-validator private net, wall-clock p50 compute
    time per consensus round (virtual protocol waits cost nothing in the
    deterministic simnet, so wall time IS the verify/hash/apply work)."""
    from stellard_tpu.node.verifyplane import VerifyPlane
    from stellard_tpu.overlay.simnet import SimNet
    from stellard_tpu.protocol.keys import KeyPair

    rounds = int(os.environ.get("BENCH_CONSENSUS_ROUNDS", "10"))
    per_round = int(os.environ.get("BENCH_CONSENSUS_TXS", "100"))
    master = KeyPair.from_passphrase("masterpassphrase")
    txs = _payments(master, rounds * per_round)

    p50s = {}
    shares = {}
    for b in backends:
        plane = VerifyPlane(backend=b, window_ms=1.0)
        if b != "cpu":
            # unmeasured device warm-up (compile + steady samples for
            # the routing model) — same seam the node uses at startup
            plane.start_prewarm().join()
        net = SimNet(4)
        for v in net.validators:
            v.node.verify_many = plane.verify_many
        net.start()
        net.run_until(lambda: net.all_validated_at_least(2), 30)
        # device_share covers the measured rounds only (not warm-up)
        plane.device_sigs = plane.cpu_sigs = plane.verified = 0
        times = []
        submitted = 0
        leg_txs = _fresh(txs)  # no memoized-signature leak across legs
        base = net.validators[0].node.lm.validated.seq
        for r in range(rounds):
            for tx in leg_txs[submitted : submitted + per_round]:
                net.validators[0].submit_client_tx(tx)
            submitted += per_round
            t0 = time.perf_counter()
            target = base + r + 1
            ok = net.run_until(
                lambda: net.all_validated_at_least(target), 120
            )
            if not ok:
                break
            times.append((time.perf_counter() - t0) * 1000.0)
        detail = plane.get_json()
        shares[b] = detail.get("device_share", 0.0)
        _note_detail("consensus_close_p50_ms", b, detail)
        plane.stop()
        times.sort()
        if times:  # a leg that never closed is omitted, not Infinity
            p50s[b] = times[len(times) // 2]
    _emit_config(
        "consensus_close_p50_ms", p50s, lower_is_better=True, unit="ms",
        shares=shares,
    )
    return p50s


def bench_replay(backends):
    """BASELINE config #5: ledger replay / catch-up throughput with
    hash_backend = cpu vs tpu (full SHAMap re-hash + tx re-apply)."""
    from stellard_tpu.node.config import Config
    from stellard_tpu.node.ledgertools import replay_ledger, replay_range
    from stellard_tpu.node.node import Node
    from stellard_tpu.protocol.keys import KeyPair

    # a catch-up span long enough that the range-wide signature batch
    # rides the device's throughput curve (6x300 kept the crypto
    # fraction too small to ever show the chip)
    ledgers = int(os.environ.get("BENCH_REPLAY_LEDGERS", "8"))
    per = int(os.environ.get("BENCH_REPLAY_TXS", "600"))
    master = KeyPair.from_passphrase("masterpassphrase")
    txs = _payments(master, ledgers * per)

    node = Node(Config()).setup()
    hashes = []
    for i in range(ledgers):
        for tx in txs[i * per : (i + 1) * per]:
            node.ops.process_transaction(tx)
        closed, _ = node.ops.accept_ledger()
        closed.save(node.nodestore)
        hashes.append(closed.hash())
    db = node.nodestore

    from stellard_tpu.node.verifyplane import VerifyPlane

    rates = {}
    shares = {}
    for b in backends:
        # the node's exact hasher wiring (tpu rides the wedge watchdog:
        # a tunnel that dies MID-LEG degrades this unattended run to the
        # host path — flagged via device share — instead of hanging)
        from stellard_tpu.crypto.backend import make_watched_hasher

        hasher = make_watched_hasher(b)
        plane = VerifyPlane(backend=b, window_ms=1.0)
        # unmeasured warm-up: one full UNMEASURED pass over the whole
        # range. The tree kernels compile per (pow2 batch, block-ladder)
        # shape, and a growing chain hits NEW shapes on later ledgers —
        # warming only the first ledger left compiles inside the timed
        # window on every earlier round (r2 0.237x, r4-contaminated
        # 0.477x). Steady-state is what the config measures; the cpu leg
        # runs the identical warm pass.
        replay_range(db, hashes, hash_batch=hasher,
                     verify_many=plane.verify_many)
        hasher.device_nodes = hasher.host_nodes = 0
        plane.device_sigs = plane.cpu_sigs = plane.verified = 0
        # bulk catch-up: one range-wide signature batch + per-ledger
        # re-apply (ledgertools.replay_range — the TPU-native catch-up
        # formulation; the cpu leg runs the identical code path)
        t0 = time.perf_counter()
        stats = replay_range(db, hashes, hash_batch=hasher,
                             verify_many=plane.verify_many)
        total_tx = stats.get("tx_count", per * len(hashes))
        rates[b] = total_tx / (time.perf_counter() - t0)
        work = (hasher.device_nodes + hasher.host_nodes
                + plane.verified)
        dev_work = hasher.device_nodes + plane.device_sigs
        shares[b] = (dev_work / work) if work else 0.0
        detail = plane.get_json()
        detail["hasher_device_nodes"] = hasher.device_nodes
        detail["hasher_host_nodes"] = hasher.host_nodes
        _note_detail("replay_tx_per_sec", b, detail)
        plane.stop()
    node.stop()
    _emit_config("replay_tx_per_sec", rates, shares=shares)
    return rates


def bench_scenario_matrix(backends):
    """Adversarial scenario matrix (stellard_tpu/testkit): one JSON line
    per scenario — convergence, commit completeness, splice/fallback
    rates under hostile workloads, byzantine defense counts, cold-node
    catch-up counters, TxQ fairness verdicts. Wall-clock is incidental
    (the simnet is discrete-time); the VALUE is the scenario outcome,
    with converged+single_hash as the pass/fail spine. Deterministic:
    the same seed re-emits identical scorecard fields."""
    from stellard_tpu.testkit import MATRIX, build_scenario, run_simnet

    seed = int(os.environ.get("BENCH_SCENARIO_SEED", "7"))
    for name in MATRIX:
        t0 = time.perf_counter()
        card = run_simnet(build_scenario(name, seed=seed))
        wall_s = time.perf_counter() - t0
        ok = card["converged"] and card["single_hash"]
        line = {
            "metric": f"scenario_{name}",
            "value": 1.0 if ok else 0.0,
            "unit": "converged_single_hash",
            "vs_baseline": 1.0 if ok else 0.0,
            "seed": seed,
            "wall_s": round(wall_s, 2),
            "submitted": card["submitted"],
            "committed": card["committed"],
            "tail_steps": card["tail_steps"],
            "splice": card["splice"],
            "fault_digest": card["fault_digest"],
        }
        if card.get("byzantine"):
            line["byzantine"] = card["byzantine"]
        if "catchup" in card:
            line["catchup"] = {
                "synced": card["catchup"]["synced"],
                **{k: card["catchup"]["segfetch"][k] for k in (
                    "segments", "records", "timeouts", "retries",
                    "backoffs", "peer_switches", "garbage_peers",
                )},
            }
        if "txq" in card:
            line["txq"] = {
                k: card["txq"][k] for k in (
                    "queued", "fee_order_drain", "no_starvation",
                )
            }
        _emit(line)


def bench_scenario_fuzz(backends):
    """Scenario-search leg (ROADMAP item 5): coverage-guided vs uniform
    random scenario generation over the same seeded budget — distinct
    scorecard DYNAMICS states reached per N runs (testkit.search's
    coverage map). The novelty bias must at least match uniform
    sampling (vs_baseline = guided/uniform distinct states, >= 1.0 is
    the pass line; tools/scenariofuzz.py --smoke gates the same
    comparison in tier-1). Also records invariant violations found per
    arm — on a healthy tree both are 0; anything else is a bug the
    fuzz smoke will be failing on. Deterministic per seed."""
    from stellard_tpu.testkit.search import coverage_comparison

    seed = int(os.environ.get("BENCH_FUZZ_SEED", "7"))
    n = int(os.environ.get("BENCH_FUZZ_N", "30"))
    t0 = time.perf_counter()
    cmp = coverage_comparison(seed, n)
    _emit({
        "metric": "scenario_fuzz_coverage",
        "value": cmp["guided_distinct"],
        "unit": "distinct_states",
        "vs_baseline": round(
            cmp["guided_distinct"] / max(1, cmp["uniform_distinct"]), 3
        ),
        "seed": seed,
        "runs_per_arm": n,
        "uniform_distinct": cmp["uniform_distinct"],
        "guided_violations": cmp["guided_violations"],
        "uniform_violations": cmp["uniform_violations"],
        "wall_s": round(time.perf_counter() - t0, 1),
    })


def bench_overlay_fanin(backends):
    """Overlay fan-in leg (ISSUE 11): the flood_survival scenario at
    100 vs 1000 simnet nodes — 5-validator core, relay-peer tier,
    squelched validator-message relay, enforced resource pricing, one
    byzantine flooder hammering its neighbor set. One JSON line per
    size recording:

      - relay sends per validator per round (the squelched gossip
        cost; with squelch=8 the per-node fan-out bound is 13 at BOTH
        sizes — peer-count-independent, which is the whole point);
      - drop latency: virtual ms of flooding before the first honest
        node walked the flooder's balance to DROP and refused it;
      - convergence + commit completeness under fire, and the close
        cadence vs the same seed with no flooder.

    Wall-clock is incidental (discrete-time simnet); the VALUE is the
    bounded fan-out and the enforcement latency. Deterministic per
    seed."""
    from stellard_tpu.testkit.scenario import run_simnet
    from stellard_tpu.testkit.scenarios import scenario_flood_survival

    seed = int(os.environ.get("BENCH_FANIN_SEED", "7"))
    steps = 44
    for total in (100, 1000):
        scn = scenario_flood_survival(
            seed=seed, n_peers=total - 5, steps=steps
        )
        t0 = time.perf_counter()
        card = run_simnet(scn)
        wall_s = time.perf_counter() - t0
        base = run_simnet(scenario_flood_survival(
            seed=seed, n_peers=total - 5, steps=steps, flooder=False,
        ))
        relay = card.get("relay", {})
        rounds = max(1, card["final_seq"])
        relay_events = (
            relay.get("relay_proposal", 0) + relay.get("relay_validation", 0)
        )
        per_validator_round = relay_events / (scn.n_validators * rounds)
        fl = next(iter(card["flooders"].values()))
        ok = (
            card["converged"] and card["single_hash"]
            and card["committed"] >= card["submitted"]
            and relay.get("relay_fanout_max", 0)
            <= scn.squelch_size + scn.n_validators
            and fl["refused_by"] >= scn.flooders[0]["fan"]
            and card["final_seq"] >= 0.75 * base["final_seq"]
        )
        _emit({
            "metric": f"overlay_fanin_{total}",
            "value": round(per_validator_round, 1),
            "unit": "relay_events/validator/round",
            "vs_baseline": 1.0 if ok else 0.0,
            "seed": seed,
            "nodes": total,
            "wall_s": round(wall_s, 2),
            "relay_fanout_max": relay.get("relay_fanout_max", 0),
            "squelch_bound": scn.squelch_size + scn.n_validators,
            "drop_latency_ms": fl.get("first_refusal_ms"),
            "flooder_refused_by": fl["refused_by"],
            "resource": {
                k: card["resource"][k] for k in (
                    "charged", "warned", "dropped", "refused", "throttled",
                )
            },
            "final_seq": card["final_seq"],
            "baseline_seq": base["final_seq"],
            "converged_single_hash": bool(
                card["converged"] and card["single_hash"]
            ),
            "committed": card["committed"],
            "submitted": card["submitted"],
        })


def bench_follower_fanout(backends):
    """Follower read-plane leg (ISSUE 10 / ROADMAP item 3): a LEADER
    validator (separate process, quorum=1, flooded over its HTTP door)
    plus an in-process FOLLOWER ([node] mode=follower) ingesting the
    validated chain over real TCP and serving the read surface.

    Measures, interleaved best-of-3 under the same combined load:
      - follower-served vs leader-served read-RPC p99 over a mixed
        workload (account_info / ledger / book_offers / account_tx),
        both through real HTTP doors from the same client
        (criterion: follower p99 <= 0.5x leader p99);
      - publish→deliver fanout lag p99 across a 10k-subscriber
        in-process fanout on the follower (bounded + reported);
      - state-root byte identity: every validated seq seen in every
        rep must hash identically on both nodes.
    """
    import shutil
    import subprocess
    import tempfile
    import threading

    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.rpc.infosub import InfoSub
    from stellard_tpu.testkit.tcpnet import REPO, free_ports, rpc, wait_until

    n_subs = int(os.environ.get("BENCH_FANOUT_SUBS", "10000"))
    n_reads = int(os.environ.get("BENCH_FANOUT_READS", "240"))
    reps = 3
    speed = 8.0
    tmp = tempfile.mkdtemp(prefix="bench-follower-")
    leader_peer, follower_peer, leader_rpc = free_ports(3)
    val_key = KeyPair.from_passphrase("bench-follower-leader")
    master = KeyPair.from_passphrase("masterpassphrase")

    cfg_path = os.path.join(tmp, "leader.cfg")
    with open(cfg_path, "w") as f:
        f.write(f"""
[standalone]
0

[node_db]
type=memory

[signature_backend]
type=cpu

[validation_seed]
{val_key.human_seed}

[validation_quorum]
1

[peer_port]
{leader_peer}

[clock_speed]
{speed}

[rpc_port]
{leader_rpc}
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    leader_proc = subprocess.Popen(
        [sys.executable, "-m", "stellard_tpu", "--conf", cfg_path,
         "--start"],
        cwd=REPO, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT,
    )
    follower = None
    stop_flood = threading.Event()
    try:
        if not wait_until(
            lambda: rpc(leader_rpc, "ping") is not None, 60, 1.0
        ):
            raise RuntimeError("leader RPC door never opened")

        def leader_validated():
            try:
                return rpc(leader_rpc, "server_info")["info"][
                    "validated_ledger"]["seq"]
            except Exception:
                return 0

        if not wait_until(lambda: leader_validated() >= 2, 90, 0.5):
            raise RuntimeError("leader never validated solo")

        from stellard_tpu.node.config import Config
        from stellard_tpu.node.node import Node

        follower = Node(Config(
            standalone=False,
            node_mode="follower",
            signature_backend="cpu",
            validators=[val_key.human_node_public],
            validation_quorum=1,
            peer_port=follower_peer,
            ips=[f"127.0.0.1 {leader_peer}"],
            clock_speed=speed,
            rpc_port=0,
        )).setup().serve()
        follower_rpc = follower.http_server.port

        def follower_validated():
            v = follower.ledger_master.validated
            return v.seq if v is not None else 0

        if not wait_until(
            lambda: follower_validated() >= leader_validated() - 1
            and follower_validated() >= 2, 120, 0.5,
        ):
            raise RuntimeError("follower never caught up")

        # 10k-subscriber fanout on the follower: ledger stream for all,
        # account streams for a spread (counting sinks — the cost under
        # measurement is the fanout plane, not the sink)
        counts = [0] * n_subs
        dests = [KeyPair.from_passphrase(f"bench-dest-{i}").account_id
                 for i in range(16)]
        for i in range(n_subs):
            def sink(_msg, i=i):
                counts[i] += 1
            sub = InfoSub(sink)
            follower.subs.subscribe_streams(sub, ["ledger"])
            if i % 10 == 0:
                follower.subs.subscribe_accounts(
                    sub, [dests[i % len(dests)]]
                )

        # 1x flood against the leader door for the whole measured window
        txs = _payments(master, 4000)
        blobs = [tx.serialize().hex() for tx in txs]
        flood_stats = {"submitted": 0, "errors": 0}

        def flood(work):
            for blob in work:
                if stop_flood.is_set():
                    return
                try:
                    rpc(leader_rpc, "submit", {"tx_blob": blob},
                        timeout=15)
                    flood_stats["submitted"] += 1
                except Exception:
                    flood_stats["errors"] += 1
            stop_flood.set()  # workload exhausted

        # two submit threads: one HTTP-serialized submitter cannot
        # saturate a leader core (interleaved halves keep per-account
        # sequence order within each thread's slice)
        flooders = [
            threading.Thread(
                target=flood, args=(blobs[k::2],), daemon=True
            )
            for k in range(2)
        ]
        for t in flooders:
            t.start()
        time.sleep(2.0)  # let the flood reach steady state

        master_id = master.human_account_id
        dest_ids = [KeyPair.from_passphrase(f"bench-dest-{i}")
                    .human_account_id for i in range(16)]

        def read_batch(port) -> list[float]:
            lat = []
            book = {
                "taker_pays": {"currency": "STR"},
                "taker_gets": {"currency": "USD",
                               "issuer": master_id},
            }
            for i in range(n_reads):
                kind = i % 4
                t0 = time.perf_counter()
                try:
                    if kind == 0:
                        rpc(port, "account_info",
                            {"account": master_id,
                             "ledger_index": "validated"}, timeout=30)
                    elif kind == 1:
                        rpc(port, "ledger",
                            {"ledger_index": "validated"}, timeout=30)
                    elif kind == 2:
                        rpc(port, "book_offers",
                            {**book, "ledger_index": "validated"},
                            timeout=30)
                    else:
                        rpc(port, "account_tx",
                            {"account": dest_ids[i % 16], "limit": 20},
                            timeout=30)
                except Exception:
                    pass  # timed at full cost below either way
                lat.append((time.perf_counter() - t0) * 1000.0)
            return lat

        def p99(lat: list[float]) -> float:
            s = sorted(lat)
            return s[min(len(s) - 1, int(0.99 * len(s)))]

        follower_p99s, leader_p99s = [], []
        roots_identical = True
        checked_seqs = 0
        for rep in range(reps):
            # interleave: follower batch, then leader batch, same load
            follower_p99s.append(p99(read_batch(follower_rpc)))
            leader_p99s.append(p99(read_batch(leader_rpc)))
            # state-root identity over every seq both currently hold
            common = min(leader_validated(), follower_validated())
            lo = max(2, common - 6)
            for seq in range(lo, common + 1):
                try:
                    lh = rpc(leader_rpc, "ledger",
                             {"ledger_index": seq}, timeout=30)[
                        "ledger"].get("hash")
                    fh = rpc(follower_rpc, "ledger",
                             {"ledger_index": seq}, timeout=30)[
                        "ledger"].get("hash")
                except Exception:
                    continue
                if lh and fh:
                    checked_seqs += 1
                    if lh != fh:
                        roots_identical = False
        stop_flood.set()
        for t in flooders:
            t.join(timeout=30)
        follower.subs.flush(timeout=30)

        subs_json = follower.subs.get_json()
        cache_json = follower.read_cache.get_json()
        fol = min(follower_p99s)
        led = min(leader_p99s)
        ratio = led / fol if fol > 0 else 0.0
        _emit({
            "metric": "follower_fanout_read_p99_ms",
            "value": round(fol, 2),
            "unit": "ms",
            # leader-p99 / follower-p99: >= 2.0 meets the <=0.5x bar
            "vs_baseline": round(ratio, 3),
            "criterion_read_p99": bool(fol <= 0.5 * led),
            "leader_read_p99_ms": round(led, 2),
            "follower_p99s_ms": [round(v, 2) for v in follower_p99s],
            "leader_p99s_ms": [round(v, 2) for v in leader_p99s],
            "fanout_subscribers": n_subs,
            "fanout_lag_p50_ms": subs_json.get("fanout_lag_p50_ms"),
            "fanout_lag_p99_ms": subs_json.get("fanout_lag_p99_ms"),
            "fanout_delivered": subs_json.get("delivered"),
            "fanout_dropped": subs_json.get("dropped_events"),
            "roots_identical": roots_identical,
            "seqs_checked": checked_seqs,
            "cache_hit_rate": cache_json.get("hit_rate"),
            "ledgers_ingested": follower.overlay.node.ledgers_ingested,
            "flood": flood_stats,
            "reads_per_batch": n_reads,
            "host_cpus": os.cpu_count(),
            # honest scope: leader process, follower, flood client and
            # read client all time-slice the same cores here — the
            # read-p99 separation the tier buys needs the follower on
            # its own core(s) (>= 3 physical cores) to show
            "note": (
                "criterion_read_p99 requires >=3 physical cores "
                "(follower isolation); identity/fanout gates are "
                "core-count-independent"
            ) if (os.cpu_count() or 1) < 3 else None,
        })
    finally:
        stop_flood.set()
        if follower is not None:
            follower.stop()
        leader_proc.terminate()
        try:
            leader_proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            leader_proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_follower_tree(backends):
    """ISSUE 19: the cascading follower tree at 100k-subscriber scale.
    A LEADER validator (separate process, quorum=1, flooded over its
    HTTP door) feeds a depth-2 follower cascade over real TCP: F1 is
    pinned to the leader, F2 is pinned to F1 — the leader's egress is
    its direct children (here exactly one peer session), never the
    follower fleet, and F2 cold-syncs through F1's epoch-stamped
    sealed shards.

    Measures, under the same flood:
      - publish→deliver fanout lag p99 across BENCH_TREE_SUBS (default
        100k) aggregate subscribers split across both followers' fanout
        planes (criterion: p99 <= BENCH_TREE_LAG_MS, default 2000);
      - leader egress: peer sessions and relay fan-out per message from
        the leader's own get_counts — must equal its direct children
        (1), not the follower count;
      - a reconnect storm: BENCH_TREE_STORM (default 2000) subscribers
        dropped from F2 mid-flood, each resuming later from its
        client-side cursor — >=95% must replay with zero missed seqs
        (criterion) and past-horizon cursors must answer cold, never
        gap silently;
      - state-root byte identity at EVERY tier (leader, F1, F2) for
        every checked seq in every rep.
    """
    import shutil
    import subprocess
    import tempfile
    import threading

    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.rpc.infosub import InfoSub
    from stellard_tpu.testkit.tcpnet import REPO, free_ports, rpc, wait_until

    n_subs = int(os.environ.get("BENCH_TREE_SUBS", "100000"))
    n_storm = int(os.environ.get("BENCH_TREE_STORM", "2000"))
    lag_bound_ms = float(os.environ.get("BENCH_TREE_LAG_MS", "2000"))
    reps = 3
    speed = 8.0
    tmp = tempfile.mkdtemp(prefix="bench-tree-")
    leader_peer, f1_peer, f2_peer, leader_rpc = free_ports(4)
    val_key = KeyPair.from_passphrase("bench-tree-leader")
    master = KeyPair.from_passphrase("masterpassphrase")

    cfg_path = os.path.join(tmp, "leader.cfg")
    with open(cfg_path, "w") as f:
        f.write(f"""
[standalone]
0

[node_db]
type=segstore
path={os.path.join(tmp, "leader-ns")}

[database_path]
{os.path.join(tmp, "leader.db")}

[signature_backend]
type=cpu

[validation_seed]
{val_key.human_seed}

[validation_quorum]
1

[peer_port]
{leader_peer}

[clock_speed]
{speed}

[rpc_port]
{leader_rpc}
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    leader_proc = subprocess.Popen(
        [sys.executable, "-m", "stellard_tpu", "--conf", cfg_path,
         "--start"],
        cwd=REPO, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT,
    )
    followers = []
    stop_flood = threading.Event()
    try:
        if not wait_until(
            lambda: rpc(leader_rpc, "ping") is not None, 60, 1.0
        ):
            raise RuntimeError("leader RPC door never opened")

        def leader_validated():
            try:
                return rpc(leader_rpc, "server_info")["info"][
                    "validated_ledger"]["seq"]
            except Exception:
                return 0

        if not wait_until(lambda: leader_validated() >= 2, 90, 0.5):
            raise RuntimeError("leader never validated solo")

        from stellard_tpu.node.config import Config
        from stellard_tpu.node.node import Node

        def follower_cfg(name, port, upstream):
            # pinned upstream: the follower dials ONLY its named parent
            # (discovery dialing off, no self-advert into gossip) — the
            # tree shape under measurement cannot flatten mid-run
            return Config(
                standalone=False,
                node_mode="follower",
                signature_backend="cpu",
                node_db_type="segstore",
                node_db_path=os.path.join(tmp, f"{name}-ns"),
                database_path=os.path.join(tmp, f"{name}.db"),
                validators=[val_key.human_node_public],
                validation_quorum=1,
                peer_port=port,
                ips=[],
                node_upstream=[upstream],
                clock_speed=speed,
                rpc_port=0,
            )

        f1 = Node(follower_cfg(
            "f1", f1_peer, f"127.0.0.1 {leader_peer}")).setup().serve()
        followers.append(f1)

        def validated(node):
            v = node.ledger_master.validated
            return v.seq if v is not None else 0

        if not wait_until(
            lambda: validated(f1) >= leader_validated() - 1
            and validated(f1) >= 2, 120, 0.5,
        ):
            raise RuntimeError("F1 never caught up from the leader")

        # F2 joins COLD through F1 — its whole warm-up (snapshot epoch
        # handoff + validated tail) must come from the peer follower
        f2 = Node(follower_cfg(
            "f2", f2_peer, f"127.0.0.1 {f1_peer}")).setup().serve()
        followers.append(f2)
        if not wait_until(
            lambda: validated(f2) >= leader_validated() - 1
            and validated(f2) >= 2, 120, 0.5,
        ):
            raise RuntimeError("F2 never caught up through F1")

        # aggregate subscriber load, split across both followers'
        # sharded fanout planes (counting sinks — the cost under
        # measurement is the fanout plane, not the sink)
        per_node = max(1, n_subs // 2)
        counts = [0, 0]
        lock0, lock1 = threading.Lock(), threading.Lock()

        def make_sink(idx, lk):
            def sink(_msg):
                with lk:
                    counts[idx] += 1
            return sink

        for idx, (node, lk) in enumerate(((f1, lock0), (f2, lock1))):
            s = make_sink(idx, lk)
            for _ in range(per_node):
                sub = InfoSub(s)
                node.subs.subscribe_streams(sub, ["ledger"])

        # the reconnect-storm cohort rides F2 on top of the base load:
        # each member records its own client-side cursor (last
        # ledgerClosed seq it actually received)
        n_storm = max(1, min(n_storm, per_node))
        storm = []
        for _ in range(n_storm):
            cell = [0]

            def sink(msg, cell=cell):
                cell[0] = msg.get("ledger_index", cell[0])

            sub = InfoSub(sink)
            f2.subs.subscribe_streams(sub, ["ledger"])
            storm.append((sub, cell))

        txs = _payments(master, 4000)
        blobs = [tx.serialize().hex() for tx in txs]
        flood_stats = {"submitted": 0, "errors": 0}

        def flood(work):
            for blob in work:
                if stop_flood.is_set():
                    return
                try:
                    rpc(leader_rpc, "submit", {"tx_blob": blob},
                        timeout=15)
                    flood_stats["submitted"] += 1
                except Exception:
                    flood_stats["errors"] += 1
            stop_flood.set()  # workload exhausted

        flooders = [
            threading.Thread(
                target=flood, args=(blobs[k::2],), daemon=True
            )
            for k in range(2)
        ]
        for t in flooders:
            t.start()
        time.sleep(2.0)  # steady state before anything is measured

        # ---- reconnect storm: drop the cohort mid-flood ----
        for sub, _cell in storm:
            f2.subs.remove(sub.id)
        storm_floor = max(cell[0] for _s, cell in storm)
        # the network keeps closing while the cohort is gone
        if not wait_until(
            lambda: validated(f2) >= storm_floor + 2, 120, 0.5
        ):
            raise RuntimeError("no closes while the storm cohort was out")

        storm_replayed = 0
        rejoined = []  # (cursor, got) — judged only after a full drain
        for _sub, cell in storm:
            cursor = cell[0]
            got: list = []
            res = f2.subs.resume(InfoSub(got.append), cursor)
            if not res.get("resumed"):
                continue  # a cold answer counts as a miss for the rate
            storm_replayed += res.get("replayed", 0)
            rejoined.append((cursor, got))
        # replays ride the sharded fanout (async): drain before judging
        f2.subs.flush(timeout=60)
        storm_ok = 0
        for cursor, got in rejoined:
            seqs = sorted(m["ledger_index"] for m in got)
            if seqs and seqs[0] == cursor + 1 and \
                    seqs == list(range(seqs[0], seqs[-1] + 1)):
                storm_ok += 1
        storm_rate = storm_ok / n_storm
        # anti-vacuity: a cursor past the horizon must answer COLD with
        # the current floor, never attach with a silent gap
        cold = f2.subs.resume(InfoSub(lambda m: None), 0) \
            if f2.subs.resume_horizon else {"cold": True}
        cold_ok = bool(cold.get("cold")) or bool(cold.get("resumed"))

        # ---- state-root identity at every tier, every rep ----
        f1_rpc_port = f1.http_server.port
        f2_rpc_port = f2.http_server.port
        roots_identical = True
        checked_seqs = 0
        for rep in range(reps):
            common = min(leader_validated(), validated(f1), validated(f2))
            lo = max(2, common - 4)
            for seq in range(lo, common + 1):
                hashes = []
                for port in (leader_rpc, f1_rpc_port, f2_rpc_port):
                    try:
                        hashes.append(rpc(
                            port, "ledger", {"ledger_index": seq},
                            timeout=30)["ledger"].get("hash"))
                    except Exception:
                        hashes.append(None)
                live = [h for h in hashes if h]
                if len(live) == 3:
                    checked_seqs += 1
                    if len(set(live)) != 1:
                        roots_identical = False
            time.sleep(1.5)

        stop_flood.set()
        for t in flooders:
            t.join(timeout=30)
        for node in followers:
            node.subs.flush(timeout=60)

        # ---- leader egress: measured from the leader's own counters --
        lc = rpc(leader_rpc, "get_counts", timeout=30)
        leader_peers = lc.get("peers", -1)
        relay_fanout_max = lc.get("squelch", {}).get("relay_fanout_max")
        leader_children = 1  # F1 is the leader's only direct child

        f1_subs = f1.subs.get_json()
        f2_subs = f2.subs.get_json()
        lag_p99 = max(
            f1_subs.get("fanout_lag_p99_ms") or 0.0,
            f2_subs.get("fanout_lag_p99_ms") or 0.0,
        )
        _emit({
            "metric": "follower_tree_fanout_lag_p99_ms",
            "value": round(lag_p99, 2),
            "unit": "ms",
            "vs_baseline": round(lag_bound_ms / lag_p99, 3)
            if lag_p99 > 0 else 0.0,
            "criterion_lag_p99": bool(lag_p99 <= lag_bound_ms),
            "lag_bound_ms": lag_bound_ms,
            "fanout_subscribers": 2 * per_node + n_storm,
            "fanout_lag_p50_ms": max(
                f1_subs.get("fanout_lag_p50_ms") or 0.0,
                f2_subs.get("fanout_lag_p50_ms") or 0.0,
            ),
            "fanout_delivered": (f1_subs.get("delivered") or 0)
            + (f2_subs.get("delivered") or 0),
            "fanout_dropped": (f1_subs.get("dropped_events") or 0)
            + (f2_subs.get("dropped_events") or 0),
            # leader egress = O(children): one peer session, relay
            # fan-out bounded by it — independent of the follower count
            "leader_peer_sessions": leader_peers,
            "leader_relay_fanout_max": relay_fanout_max,
            "criterion_leader_egress": bool(
                leader_peers == leader_children
                and (relay_fanout_max or 0) <= leader_children
            ),
            "tree": {"depth": 2, "branching": 1,
                     "followers": len(followers)},
            # reconnect storm: zero-missed-seq resume rate
            "storm_clients": n_storm,
            "storm_zero_gap": storm_ok,
            "storm_zero_gap_rate": round(storm_rate, 4),
            "criterion_storm_resume": bool(storm_rate >= 0.95),
            "storm_replayed_events": storm_replayed,
            "resume_counters": {
                k: f2_subs.get(k) for k in (
                    "resumed", "resume_replayed", "resume_cold",
                    "dup_suppressed",
                )
            },
            "cold_answer_ok": cold_ok,
            "roots_identical": roots_identical,
            "seqs_checked": checked_seqs,
            # F2's cold warm-up came through F1's epoch-stamped shards
            "f2_segfetch": f2.overlay.node.segment_catchup.get_json()
            if getattr(f2.overlay.node, "segment_catchup", None)
            else None,
            "f1_ledgers_ingested": f1.overlay.node.ledgers_ingested,
            "f2_ledgers_ingested": f2.overlay.node.ledgers_ingested,
            "flood": flood_stats,
            "host_cpus": os.cpu_count(),
            # honest scope: both follower nodes and all 100k sinks
            # time-slice this one process alongside the leader process
            # and the flood client — the lag bound is a one-box floor,
            # not the per-follower production number
            "note": (
                "single-box: leader process + 2 in-process followers "
                "+ all sinks share the host's cores"
            ),
        })
    finally:
        stop_flood.set()
        for node in followers:
            try:
                node.stop()
            except Exception:
                pass
        leader_proc.terminate()
        try:
            leader_proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            leader_proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_archive_paging(backends):
    """ISSUE 20: deep-history account_tx paging against the archive
    tier while the leader floods. A LEADER validator (separate process,
    quorum=1, online deletion + history shards on) floods until deep
    history exists only in sealed shard files; an in-process ARCHIVE
    node backfills them over the wire, then BENCH_ARCHIVE_CLIENTS
    (default 16) concurrent pagers walk account_tx windows below the
    leader's retain floor through the archive's real HTTP door.

    Measures:
      - archive paging throughput (pages/s) at high client concurrency,
        with the single-client rate as the scaling baseline;
      - the forever-tier result-cache hit rate over the concurrent
        window (immutable below-floor windows must hit, not recompute);
      - the leader's close-interval p50 with and without the paging
        load — the archive tier must not tax the validator's cadence
        (separate process; the delta is recorded in the emit).
    """
    import shutil
    import subprocess
    import tempfile
    import threading

    from stellard_tpu.protocol.keys import KeyPair
    from stellard_tpu.testkit.tcpnet import REPO, free_ports, rpc, wait_until

    n_clients = int(os.environ.get("BENCH_ARCHIVE_CLIENTS", "16"))
    page_seconds = float(os.environ.get("BENCH_ARCHIVE_SECONDS", "10"))
    base_seconds = 8.0
    speed = 8.0
    tmp = tempfile.mkdtemp(prefix="bench-archive-")
    leader_peer, arch_peer, leader_rpc = free_ports(3)
    val_key = KeyPair.from_passphrase("bench-archive-leader")
    master = KeyPair.from_passphrase("masterpassphrase")

    cfg_path = os.path.join(tmp, "leader.cfg")
    with open(cfg_path, "w") as f:
        f.write(f"""
[standalone]
0

[node_db]
type=segstore
path={os.path.join(tmp, "leader-ns")}
segment_mb=1
online_delete=4
online_delete_interval=2
shards=1

[database_path]
{os.path.join(tmp, "leader.db")}

[signature_backend]
type=cpu

[validation_seed]
{val_key.human_seed}

[validation_quorum]
1

[peer_port]
{leader_peer}

[clock_speed]
{speed}

[rpc_port]
{leader_rpc}
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    leader_proc = subprocess.Popen(
        [sys.executable, "-m", "stellard_tpu", "--conf", cfg_path,
         "--start"],
        cwd=REPO, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT,
    )
    arch = None
    stop_flood = threading.Event()
    try:
        if not wait_until(
            lambda: rpc(leader_rpc, "ping") is not None, 60, 1.0
        ):
            raise RuntimeError("leader RPC door never opened")

        def leader_validated():
            try:
                return rpc(leader_rpc, "server_info")["info"][
                    "validated_ledger"]["seq"]
            except Exception:
                return 0

        if not wait_until(lambda: leader_validated() >= 2, 90, 0.5):
            raise RuntimeError("leader never validated solo")

        # continuous flood for the whole run: the leader keeps closing
        # non-empty ledgers through every measurement window below
        txs = _payments(master, 8000)
        blobs = [tx.serialize().hex() for tx in txs]
        flood_stats = {"submitted": 0, "errors": 0}

        def flood(work):
            for blob in work:
                if stop_flood.is_set():
                    return
                try:
                    rpc(leader_rpc, "submit", {"tx_blob": blob},
                        timeout=15)
                    flood_stats["submitted"] += 1
                except Exception:
                    flood_stats["errors"] += 1
                time.sleep(0.01)

        flooders = [
            threading.Thread(target=flood, args=(blobs[k::2],),
                             daemon=True)
            for k in range(2)
        ]
        for t in flooders:
            t.start()

        # the archive boots early and tracks the leader's rotation: its
        # rescan keeps importing shards as the leader seals them
        from stellard_tpu.node.config import Config
        from stellard_tpu.node.node import Node

        arch = Node(Config(
            standalone=False,
            node_mode="archive",
            signature_backend="cpu",
            node_db_type="segstore",
            node_db_path=os.path.join(tmp, "arch-ns"),
            database_path=os.path.join(tmp, "arch.db"),
            archive_path=os.path.join(tmp, "arch-shards"),
            archive_rescan_s=2.0,
            validators=[val_key.human_node_public],
            validation_quorum=1,
            peer_port=arch_peer,
            node_upstream=[f"127.0.0.1 {leader_peer}"],
            clock_speed=speed,
            rpc_port=0,
        )).setup().serve()

        if not wait_until(
            lambda: len(arch.shardstore.shards()) >= 2
            and arch.read_plane.archive_floor > 0, 180, 0.5,
        ):
            raise RuntimeError(
                f"archive never backfilled 2 shards "
                f"(shards={arch.shardstore.shards()})"
            )
        floor = arch.read_plane.archive_floor
        windows = [
            (sh["lo"], sh["hi"]) for sh in arch.shardstore.shards()
            if sh["hi"] <= floor
        ]
        aport = arch.http_server.port
        acct = master.human_account_id

        page_stats = {"pages": 0, "rows": 0, "errors": 0}
        stats_lock = threading.Lock()

        def page_once() -> tuple[int, int]:
            """One full walk of every deep window; returns (pages, rows)."""
            pages = rows = 0
            for lo, hi in windows:
                marker = None
                while True:
                    p = {"account": acct, "ledger_index_min": lo,
                         "ledger_index_max": hi, "forward": True,
                         "binary": True, "limit": 10}
                    if marker is not None:
                        p["marker"] = marker
                    r = rpc(aport, "account_tx", p, timeout=30)
                    if r.get("status") != "success":
                        raise RuntimeError(f"deep page refused: {r}")
                    pages += 1
                    rows += len(r.get("transactions", []))
                    marker = r.get("marker")
                    if marker is None:
                        break
            return pages, rows

        # single-client scaling baseline (also warms the forever tier
        # with the first computation of every page)
        t0 = time.monotonic()
        solo_pages = 0
        while time.monotonic() - t0 < 3.0:
            p, _r = page_once()
            solo_pages += p
        solo_rate = solo_pages / (time.monotonic() - t0)

        # close-cadence sampler: validated-seq transitions timestamped
        # from the leader's own door (separate process — the pagers
        # cannot slow it through the GIL, only through the host's cores)
        def sample_closes(seconds: float) -> list:
            stamps = []
            last = leader_validated()
            t_end = time.monotonic() + seconds
            while time.monotonic() < t_end:
                v = leader_validated()
                if v > last:
                    stamps.append(time.monotonic())
                    last = v
                time.sleep(0.025)
            return [
                (b - a) * 1000.0 for a, b in zip(stamps, stamps[1:])
            ]

        def p50(xs: list) -> float:
            return float(np.percentile(xs, 50)) if xs else 0.0

        base_gaps = sample_closes(base_seconds)

        cache0 = arch.read_cache.get_json()
        stop_page = threading.Event()

        def pager():
            while not stop_page.is_set():
                try:
                    p, r = page_once()
                    with stats_lock:
                        page_stats["pages"] += p
                        page_stats["rows"] += r
                except Exception:
                    with stats_lock:
                        page_stats["errors"] += 1

        pagers = [threading.Thread(target=pager, daemon=True)
                  for _ in range(n_clients)]
        t0 = time.monotonic()
        for t in pagers:
            t.start()
        load_gaps = sample_closes(page_seconds)
        stop_page.set()
        for t in pagers:
            t.join(timeout=30)
        elapsed = time.monotonic() - t0
        cache1 = arch.read_cache.get_json()

        stop_flood.set()
        for t in flooders:
            t.join(timeout=30)

        fh = cache1["forever_hits"] - cache0["forever_hits"]
        fi = cache1["forever_inserts"] - cache0["forever_inserts"]
        forever_rate = fh / (fh + fi) if (fh + fi) else 0.0
        page_rate = page_stats["pages"] / elapsed if elapsed > 0 else 0.0
        base_p50 = p50(base_gaps)
        load_p50 = p50(load_gaps)
        sb = arch.overlay.node.shard_backfill
        _emit({
            "metric": "archive_paging_pages_per_sec",
            "value": round(page_rate, 1),
            "unit": "pages/s",
            "vs_baseline": round(page_rate / solo_rate, 3)
            if solo_rate > 0 else 0.0,
            "clients": n_clients,
            "solo_pages_per_sec": round(solo_rate, 1),
            "pages": page_stats["pages"],
            "rows_served": page_stats["rows"],
            "page_errors": page_stats["errors"],
            "deep_windows": windows,
            "verified_floor": floor,
            # the forever tier over the concurrent window: immutable
            # below-floor pages must HIT, not recompute per epoch
            "forever_hit_rate": round(forever_rate, 4),
            "forever_hits": fh,
            "forever_inserts": fi,
            "criterion_forever_cache": bool(forever_rate >= 0.5),
            # validator cadence under the paging load (ms, wall clock
            # at clock_speed={speed}: deltas are comparable, absolute
            # values are accelerated)
            "close_p50_baseline_ms": round(base_p50, 1),
            "close_p50_paging_ms": round(load_p50, 1),
            "close_p50_delta_ms": round(load_p50 - base_p50, 1),
            "closes_sampled": len(base_gaps) + len(load_gaps),
            "backfill": {
                k: sb.get_json()[k]
                for k in ("imported", "bytes", "requests",
                          "garbage_peers")
            },
            "flood": flood_stats,
            "host_cpus": os.cpu_count(),
            # honest scope: thousands of deep rows, not millions — the
            # seal cadence bounds what a one-box bench can flood; the
            # paging path, two-tier walk, and cache tiers are what is
            # measured. The archive + all pagers share this process
            # (GIL) while the leader runs separately; the close-p50
            # delta still includes host core contention.
            "note": (
                "single-box: leader process + in-process archive + "
                f"{n_clients} pager threads share the host's cores"
            ),
        })
    finally:
        stop_flood.set()
        if arch is not None:
            try:
                arch.stop()
            except Exception:
                pass
        leader_proc.terminate()
        try:
            leader_proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            leader_proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_path_plane(backends):
    """ISSUE 17: the liquidity read plane under a crossfire flood —
    a file-backed node floods an order-book mix (creates, tier-consuming
    crossings, cancels) over a ledger seeded with many idle books, with
    and without live path_find subscriptions, interleaved best-of-3.
    Criteria: (a) book re-reads per close << total books (the
    incremental index only re-scans what the close's write set touched,
    counter-pinned), (b) p99 subscription staleness recorded under a
    deliberately tight per-close budget, (c) subscribed close p50 within
    10% of the no-subscription baseline (pathfinding never serializes
    into the close), (d) the routed device evaluator byte-identical to
    the host arm at mesh widths 1/2/4/8. Subprocess: the virtual
    device-count flag must precede backend init. Honest provenance: on
    this box the mesh is virtual CPU shards and the line says so."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "path_plane_bench.py")],
            capture_output=True, text=True, timeout=900, env=env,
        )
        line = r.stdout.strip().splitlines()[-1]
        data = json.loads(line)
    except Exception as e:
        _emit({"metric": "path_plane_close_p50_ms", "value": 0.0,
               "unit": "error", "vs_baseline": 0.0, "error": repr(e)[:300]})
        return
    subs_p50 = data["subs_close_p50_ms"]
    nosub_p50 = data["nosub_close_p50_ms"]
    rereads_per_close = data["book_rereads"] / max(data["closes"], 1)
    dev = data["device"]
    _emit({
        "metric": "path_plane_close_p50_ms",
        "value": subs_p50,
        "unit": "ms",
        # subscribed over baseline close p50: <= 1.10 meets criterion (c)
        "vs_baseline": round(subs_p50 / max(nosub_p50, 1e-9), 3),
        "criterion_close_p50": bool(subs_p50 <= 1.10 * nosub_p50),
        "nosub_close_p50_ms": nosub_p50,
        "reps": data["reps"],
        "subs_p50s_ms": data["subs_p50s_ms"],
        "nosub_p50s_ms": data["nosub_p50s_ms"],
        # (a): the incremental index re-read ~1 book per close out of a
        # 14-book plane — a full scan would touch every book every close
        "book_rereads_per_close": round(rereads_per_close, 2),
        "total_books": data["total_books"],
        "criterion_rereads": bool(
            rereads_per_close * 4 <= data["total_books"]),
        "index": data["index"],
        # (b): staleness under budget < subs (shedding engaged)
        "subs_staleness_p99_ledgers": data["subs"]["staleness_p99"],
        "subs_detail": data["subs"],
        # (d): host/device byte identity at every mesh width; the
        # devices are virtual CPU shards here — fallback says so
        "device_identical_every_width": dev["identical_every_width"],
        "device_per_width": dev["per_width"],
        "widths": dev["widths"],
        "virtual_devices": dev["virtual_devices"],
        "platform": dev["platform"],
        "fallback": dev["platform"] != "tpu",
    })
    _note_detail("path_plane", "subprocess", data)


def bench_mesh():
    """SURVEY §2.9 mapping #3: the sharded verify step on an 8-virtual-
    device CPU mesh, as a throughput number (a sharding/collective
    regression in parallel/mesh.py shows up here as a number, not just
    a dryrun pass/fail). Runs in a subprocess — the device-count flag
    must be set before backend init. vs_baseline is mesh-vs-single-
    device scaling; ~1.0 on this 1-core box is healthy (the virtual
    devices time-slice one core)."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "mesh_bench.py")],
            capture_output=True, text=True, timeout=900, env=env,
        )
        line = r.stdout.strip().splitlines()[-1]
        data = json.loads(line)
    except Exception as e:
        _emit({"metric": "mesh8_verify_sigs_per_sec", "value": 0.0,
               "unit": "error", "vs_baseline": 0.0, "error": repr(e)[:300]})
        return
    out = {
        "metric": "mesh8_verify_sigs_per_sec",
        "value": data["mesh_rate"],
        "unit": "sigs/s",
        "vs_baseline": data["scaling"],
        "cpu_baseline": data["single_rate"],
        "mesh_devices": data["mesh_devices"],
        "batch": data["batch"],
        "fallback": False,  # always runs (virtual cpu mesh)
    }
    if "mesh_hash_nodes_per_sec" in data:
        out["mesh_hash_nodes_per_sec"] = data["mesh_hash_nodes_per_sec"]
    _emit(out)


def bench_multichip():
    """ISSUE 15: mesh width as a config axis, swept through the PRODUCT
    seams (make_verifier(mesh=W) / make_watched_hasher(mesh=W)) at
    widths 1/2/4/8 on a virtual 8-device CPU mesh — verify sigs/s and
    packed tree-hash nodes/s per width, byte identity pinned at every
    width in every rep. Subprocess: the device-count flag must precede
    backend init. Honest provenance (BENCH_r04's lesson): on this box
    the mesh is virtual CPU shards, so the lines carry fallback=true and
    the full per-width mesh/cost-model provenance; the >=100k sigs/s
    ROADMAP target is recorded for on-TPU runs, never gated here."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "multichip_bench.py")],
            # cold-cache budget: four mesh widths compile four sharded
            # verify programs on first run (the persistent .jax_cache
            # makes later runs cheap)
            capture_output=True, text=True, timeout=1800, env=env,
        )
        line = r.stdout.strip().splitlines()[-1]
        data = json.loads(line)
    except Exception as e:
        _emit({"metric": "multichip_verify_sigs_per_sec", "value": 0.0,
               "unit": "error", "vs_baseline": 0.0, "error": repr(e)[:300]})
        return
    widths = data["widths"]
    wide, w1 = str(max(widths)), str(min(widths))
    on_device = data.get("platform") == "tpu"
    ver, hsh = data["verify"], data["hash"]
    identical = (
        all(v["identical_every_rep"] for v in ver.values())
        and all(h["identical_every_rep"] for h in hsh.values())
    )
    common = {
        "widths": widths,
        "virtual_devices": data.get("virtual_devices"),
        "platform": data.get("platform"),
        # fallback=true: the mesh is host-emulated shards, NOT chips —
        # vs_baseline is wide-vs-width-1 scaling, ~1.0 healthy when the
        # shards time-slice one core
        "fallback": not on_device,
        "identical_every_width": identical,
    }
    _emit({
        "metric": "multichip_verify_sigs_per_sec",
        "value": ver[wide]["sigs_per_sec"],
        "unit": "sigs/s",
        "vs_baseline": round(
            ver[wide]["sigs_per_sec"] / max(ver[w1]["sigs_per_sec"], 1e-9),
            3,
        ),
        "cpu_baseline": ver[w1]["sigs_per_sec"],
        "per_width": {w: v["sigs_per_sec"] for w, v in ver.items()},
        "kernels": {w: v["kernel"] for w, v in ver.items()},
        "roadmap_target_sigs_per_sec": 100_000,  # on-TPU goal, recorded
        **common,
    })
    _emit({
        "metric": "multichip_tree_hash_nodes_per_sec",
        "value": hsh[wide]["nodes_per_sec"],
        "unit": "nodes/s",
        "vs_baseline": round(
            hsh[wide]["nodes_per_sec"] / max(hsh[w1]["nodes_per_sec"], 1e-9),
            3,
        ),
        "cpu_baseline": hsh[w1]["nodes_per_sec"],
        "per_width": {w: h["nodes_per_sec"] for w, h in hsh.items()},
        **common,
    })
    _note_detail("multichip", "widths", {
        "verify": ver, "hash": hsh, "devices": data.get("devices"),
    })


def _emit_config(metric, rates, lower_is_better=False, unit="tx/s",
                 shares=None):
    cpu = rates.get("cpu")
    dev = rates.get("tpu")
    value = dev if dev is not None else cpu
    if value is None:  # no leg produced a number
        _emit({"metric": metric, "value": 0.0, "unit": "error",
               "vs_baseline": 0.0, "error": "no backend leg completed"})
        return
    if cpu and dev:
        vs = (cpu / dev) if lower_is_better else (dev / cpu)
    else:
        vs = 0.0
    out = {
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": round(vs, 3),
        "cpu_baseline": round(cpu, 2) if cpu else None,
        "fallback": dev is None,
    }
    if shares is not None and "tpu" in shares:
        # device share of the work actually routed to the chip on the
        # tpu leg: a ~1.0 ratio with device_share 0 means the routing
        # model benched the device OUT, not that the device kept up
        out["device_share"] = round(shares["tpu"], 4)
    _emit(out)


def main() -> None:
    _install_stderr_dedupe()
    platform = _init_device_backend()

    from stellard_tpu.crypto import VerifyRequest, make_verifier
    from stellard_tpu.ops.ed25519_jax import (
        prepare_batch,
        verify_kernel,
        verify_stream,
    )
    from stellard_tpu.protocol.keys import KeyPair

    # honor the tuned kernel implementation: with impl=pallas in the
    # tuning file the headline must measure the Pallas kernel, not the
    # XLA formulation run at the pallas winner's batch size
    if os.environ.get("STELLARD_VERIFY_IMPL", "xla") == "pallas":
        from stellard_tpu.ops.ed25519_pallas import (
            verify_kernel_pallas as verify_kernel,
        )

    batch = int(os.environ.get("BENCH_BATCH", _TUNED_BATCH or "4096"))
    seconds = float(os.environ.get("BENCH_SECONDS", "10"))

    # BASELINE configs 1-5 (one JSON line each); the headline metric
    # prints LAST so a single-line consumer reads the north-star number
    if os.environ.get("BENCH_ONLY", "") != "headline":
        # when no device is reachable the tpu legs are meaningless
        # (JAX-on-one-cpu-core); run cpu-only and flag the fallback
        backends = ["cpu"] + (["tpu"] if platform != "cpu" else [])
        for fn in (
            bench_payment_flood,
            bench_pipelined_flood,
            bench_delta_replay_flood,
            bench_overload_flood,
            bench_parallel_spec_flood,
            bench_tree_commit,
            bench_storage_flush,
            bench_ooc_state,
            bench_offer_mix,
            bench_regular_key_fanout,
            bench_consensus_close,
            bench_replay,
            bench_scenario_matrix,
            bench_scenario_fuzz,
            bench_overlay_fanin,
            bench_follower_fanout,
            bench_follower_tree,
            bench_archive_paging,
            bench_path_plane,
        ):
            try:
                fn(backends)
            except Exception as e:  # a failed config must not kill the rest
                _emit({"metric": fn.__name__, "value": 0.0, "unit": "error",
                       "vs_baseline": 0.0, "error": repr(e)[:300]})
        try:
            bench_mesh()
        except Exception as e:
            _emit({"metric": "mesh8_verify_sigs_per_sec", "value": 0.0,
                   "unit": "error", "vs_baseline": 0.0,
                   "error": repr(e)[:300]})
        try:
            bench_multichip()
        except Exception as e:
            _emit({"metric": "multichip_verify_sigs_per_sec", "value": 0.0,
                   "unit": "error", "vs_baseline": 0.0,
                   "error": repr(e)[:300]})
        _write_detail()

    rng = np.random.default_rng(42)
    keys = [KeyPair.from_seed(bytes(rng.integers(0, 256, 32, dtype=np.uint8))) for _ in range(64)]
    # several DISTINCT input sets, cycled per timed iteration: repeated
    # identical executions can be memoized below the runtime (the axon
    # tunnel dedupes identical (executable, inputs) submissions), which
    # would inflate every rate below
    N_SETS = 4
    sets = []
    for _ in range(N_SETS):
        msgs = [bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in range(batch)]
        sigs = [keys[i % 64].sign(msgs[i]) for i in range(batch)]
        pubs = [keys[i % 64].public for i in range(batch)]
        sets.append((pubs, msgs, sigs))
    pubs, msgs, sigs = sets[0]
    req_sets = [
        [VerifyRequest(p, m, s) for p, m, s in zip(pu, ms, si)]
        for pu, ms, si in sets
    ]
    reqs = req_sets[0]

    # CPU baseline (libsodium-role path, threaded)
    cpu = make_verifier("cpu", threads=os.cpu_count() or 4)
    cpu.verify_batch(reqs[:64])  # warm
    t0 = time.time()
    n = 0
    while time.time() - t0 < max(2.0, seconds / 3):
        assert cpu.verify_batch(req_sets[n % N_SETS]).all()
        n += 1
    cpu_rate = batch * n / (time.time() - t0)

    # sub-metric: host prep only (bytes -> kernel inputs, no device)
    prepare_batch(pubs, msgs, sigs, device_put=False)
    t0 = time.time()
    n = 0
    while time.time() - t0 < max(2.0, seconds / 3):
        prepare_batch(pubs, msgs, sigs, device_put=False)
        n += 1
    prep_rate = batch * n / (time.time() - t0)

    # sub-metric: device kernel only (inputs resident, compile excluded),
    # cycling distinct resident input sets so no layer can memoize
    input_sets = [prepare_batch(*s) for s in sets]
    out = verify_kernel(**input_sets[0])
    out.block_until_ready()  # compile
    assert bool(np.asarray(out).all())
    t0 = time.time()
    n = 0
    while time.time() - t0 < seconds:
        verify_kernel(**input_sets[n % N_SETS]).block_until_ready()
        n += 1
    device_rate = batch * n / (time.time() - t0)

    # headline: END-TO-END bytes-in -> bools-out through the double-buffered
    # pipeline (host prep of batch i+1 overlaps device execution of i)
    t0 = time.time()
    deadline = t0 + seconds

    def feed():  # time-bounded (at least 4 batches for pipeline overlap)
        i = 0
        while i < 4 or time.time() < deadline:
            yield sets[i % N_SETS]
            i += 1

    total = 0
    for flags in verify_stream(feed(), kernel=verify_kernel):
        assert flags.all()
        total += len(flags)
    e2e_rate = total / (time.time() - t0)

    _emit(
        {
            "metric": "ed25519_tx_sig_verifications_per_sec_per_chip",
            "value": round(e2e_rate, 1),
            "unit": "sigs/s",
            "vs_baseline": round(e2e_rate / cpu_rate, 3),
            "cpu_baseline": round(cpu_rate, 1),
            "prep_only": round(prep_rate, 1),
            "device_only": round(device_rate, 1),
            "batch": batch,
            "impl": os.environ.get("STELLARD_VERIFY_IMPL", "xla"),
            "platform": platform,
            # fallback=true means NO device kernel ran — the value is the
            # device program emulated on one cpu core, not a chip number
            "fallback": platform == "cpu",
        }
    )


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:  # never exit without a parseable JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        _emit(
            {
                "metric": "ed25519_tx_sig_verifications_per_sec_per_chip",
                "value": 0.0,
                "unit": "sigs/s",
                "vs_baseline": 0.0,
                "error": repr(e)[:400],
            }
        )
        sys.exit(0)
