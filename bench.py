"""Round benchmark: Ed25519 tx-signature verification throughput per chip.

Mirrors BASELINE.json's headline metric. The CPU baseline (the reference's
libsodium-style per-signature path, threaded) is measured in-process on the
same workload, so vs_baseline = tpu_rate / cpu_rate.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    from stellard_tpu.crypto import VerifyRequest, make_verifier
    from stellard_tpu.ops.ed25519_jax import prepare_batch, verify_kernel
    from stellard_tpu.protocol.keys import KeyPair

    batch = int(os.environ.get("BENCH_BATCH", "4096"))
    seconds = float(os.environ.get("BENCH_SECONDS", "10"))

    rng = np.random.default_rng(42)
    keys = [KeyPair.from_seed(bytes(rng.integers(0, 256, 32, dtype=np.uint8))) for _ in range(64)]
    msgs = [bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in range(batch)]
    sigs = [keys[i % 64].sign(msgs[i]) for i in range(batch)]
    pubs = [keys[i % 64].public for i in range(batch)]
    reqs = [VerifyRequest(p, m, s) for p, m, s in zip(pubs, msgs, sigs)]

    # CPU baseline (libsodium-role path, threaded)
    cpu = make_verifier("cpu", threads=os.cpu_count() or 4)
    cpu.verify_batch(reqs[:64])  # warm
    t0 = time.time()
    n = 0
    while time.time() - t0 < max(2.0, seconds / 3):
        assert cpu.verify_batch(reqs).all()
        n += 1
    cpu_rate = batch * n / (time.time() - t0)

    # device path: host prep overlaps in steady state; measure device kernel
    inputs = prepare_batch(pubs, msgs, sigs)
    out = verify_kernel(**inputs)
    out.block_until_ready()  # compile
    assert bool(np.asarray(out).all())
    t0 = time.time()
    n = 0
    while time.time() - t0 < seconds:
        verify_kernel(**inputs).block_until_ready()
        n += 1
    tpu_rate = batch * n / (time.time() - t0)

    print(
        json.dumps(
            {
                "metric": "ed25519_tx_sig_verifications_per_sec_per_chip",
                "value": round(tpu_rate, 1),
                "unit": "sigs/s",
                "vs_baseline": round(tpu_rate / cpu_rate, 3),
                "cpu_baseline": round(cpu_rate, 1),
                "batch": batch,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
