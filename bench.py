"""Round benchmark: Ed25519 tx-signature verification throughput per chip.

Mirrors BASELINE.json's headline metric. The CPU baseline (the reference's
libsodium-style per-signature path, threaded) is measured in-process on the
same workload, so vs_baseline = tpu_rate / cpu_rate.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def _probe_device_backend(timeout_s: float) -> bool:
    """Check, in a throwaway subprocess, that the pinned JAX backend comes up.

    The env pins JAX_PLATFORMS=axon (a real TPU via a tunnel). Init can fail
    fast (round-1 bench died on one UNAVAILABLE) or hang indefinitely when
    the tunnel is down — so the probe needs a hard wall-clock timeout, which
    an in-process try/except can't give us.
    """
    import subprocess

    for attempt in range(2):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if r.returncode == 0:
                return True
            print(f"bench: backend probe rc={r.returncode}: "
                  f"{r.stderr.strip()[-300:]}", file=sys.stderr)
        except subprocess.TimeoutExpired:
            # a hung init won't be fixed by an immediate retry; don't
            # stall another full timeout window
            print(f"bench: backend probe timed out after {timeout_s}s",
                  file=sys.stderr)
            return False
        time.sleep(2.0)
    return False


def _init_device_backend() -> str:
    """Initialise a JAX backend, falling back to cpu so the bench always
    records a number. Returns the platform name actually in use."""
    pinned = os.environ.get("JAX_PLATFORMS", "")
    if pinned and pinned != "cpu":
        probe_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", "240"))
        if not _probe_device_backend(probe_s):
            print("bench: device backend unusable; falling back to cpu",
                  file=sys.stderr)
            os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0].platform


def main() -> None:
    platform = _init_device_backend()

    from stellard_tpu.crypto import VerifyRequest, make_verifier
    from stellard_tpu.ops.ed25519_jax import (
        prepare_batch,
        verify_kernel,
        verify_stream,
    )
    from stellard_tpu.protocol.keys import KeyPair

    batch = int(os.environ.get("BENCH_BATCH", "4096"))
    seconds = float(os.environ.get("BENCH_SECONDS", "10"))

    rng = np.random.default_rng(42)
    keys = [KeyPair.from_seed(bytes(rng.integers(0, 256, 32, dtype=np.uint8))) for _ in range(64)]
    msgs = [bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in range(batch)]
    sigs = [keys[i % 64].sign(msgs[i]) for i in range(batch)]
    pubs = [keys[i % 64].public for i in range(batch)]
    reqs = [VerifyRequest(p, m, s) for p, m, s in zip(pubs, msgs, sigs)]

    # CPU baseline (libsodium-role path, threaded)
    cpu = make_verifier("cpu", threads=os.cpu_count() or 4)
    cpu.verify_batch(reqs[:64])  # warm
    t0 = time.time()
    n = 0
    while time.time() - t0 < max(2.0, seconds / 3):
        assert cpu.verify_batch(reqs).all()
        n += 1
    cpu_rate = batch * n / (time.time() - t0)

    # sub-metric: host prep only (bytes -> kernel inputs, no device)
    prepare_batch(pubs, msgs, sigs, device_put=False)
    t0 = time.time()
    n = 0
    while time.time() - t0 < max(2.0, seconds / 3):
        prepare_batch(pubs, msgs, sigs, device_put=False)
        n += 1
    prep_rate = batch * n / (time.time() - t0)

    # sub-metric: device kernel only (inputs resident, compile excluded)
    inputs = prepare_batch(pubs, msgs, sigs)
    out = verify_kernel(**inputs)
    out.block_until_ready()  # compile
    assert bool(np.asarray(out).all())
    t0 = time.time()
    n = 0
    while time.time() - t0 < seconds:
        verify_kernel(**inputs).block_until_ready()
        n += 1
    device_rate = batch * n / (time.time() - t0)

    # headline: END-TO-END bytes-in -> bools-out through the double-buffered
    # pipeline (host prep of batch i+1 overlaps device execution of i)
    t0 = time.time()
    deadline = t0 + seconds

    def feed():  # time-bounded (at least 4 batches for pipeline overlap)
        i = 0
        while i < 4 or time.time() < deadline:
            yield (pubs, msgs, sigs)
            i += 1

    total = 0
    for flags in verify_stream(feed()):
        assert flags.all()
        total += len(flags)
    e2e_rate = total / (time.time() - t0)

    _emit(
        {
            "metric": "ed25519_tx_sig_verifications_per_sec_per_chip",
            "value": round(e2e_rate, 1),
            "unit": "sigs/s",
            "vs_baseline": round(e2e_rate / cpu_rate, 3),
            "cpu_baseline": round(cpu_rate, 1),
            "prep_only": round(prep_rate, 1),
            "device_only": round(device_rate, 1),
            "batch": batch,
            "platform": platform,
            # fallback=true means NO device kernel ran — the value is the
            # device program emulated on one cpu core, not a chip number
            "fallback": platform == "cpu",
        }
    )


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:  # never exit without a parseable JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        _emit(
            {
                "metric": "ed25519_tx_sig_verifications_per_sec_per_chip",
                "value": 0.0,
                "unit": "sigs/s",
                "vs_baseline": 0.0,
                "error": repr(e)[:400],
            }
        )
        sys.exit(0)
