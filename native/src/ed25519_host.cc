// Batched Ed25519 host preparation: h = SHA512(R || A || M) mod l.
//
// Role: the per-signature host work feeding the TPU verify kernel
// (stellard_tpu/ops/ed25519_jax.py). Round-1 did this in a Python loop
// (hashlib + bigint % l) which capped end-to-end throughput; this C++
// kernel does the hash and the scalar reduction in one threaded pass so
// host prep stays far ahead of the device.
//
// The reduction uses the standard fold identity for the Ed25519 group
// order l = 2^252 + delta (RFC 8032): 2^252 === -delta (mod l), applied
// on 28-bit limbs (252 = 9*28, so the split is limb-aligned). Values are
// carried as signed limbs between folds; a final canonicalization brings
// the result into [0, l).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

typedef __int128 i128;
typedef int64_t i64;

constexpr int LB = 28;                 // limb bits
constexpr i64 LIMB_MASK = (1LL << LB) - 1;
constexpr int NL = 19;                 // limbs to hold 512 + slack bits

struct Limbs {
  i64 v[NL];  // signed 28-bit limbs, little-endian
};

// load a little-endian byte string into 28-bit limbs
void load_le(const uint8_t* b, int nbytes, Limbs* out) {
  for (int i = 0; i < NL; i++) out->v[i] = 0;
  for (int bit = 0, i = 0; i < nbytes; i++) {
    int limb = (i * 8) / LB;
    int off = (i * 8) % LB;
    out->v[limb] |= ((i64)b[i] << off) & LIMB_MASK;
    if (off + 8 > LB && limb + 1 < NL)
      out->v[limb + 1] |= (i64)b[i] >> (LB - off);
    (void)bit;
  }
}

// propagate carries so every limb is in [0, 2^28) except possibly the
// top (which carries the overall sign); arithmetic >> gives floor
void normalize(Limbs* x) {
  i64 carry = 0;
  for (int i = 0; i < NL; i++) {
    i64 t = x->v[i] + carry;
    carry = t >> LB;
    x->v[i] = t - (carry << LB);
  }
  x->v[NL - 1] += carry << LB;  // keep any residual in the top limb
}

bool is_negative(const Limbs* x) { return x->v[NL - 1] < 0; }

// x >= l ?  (x must be normalized, non-negative)
bool geq_l(const Limbs* x, const i64* l_limbs) {
  for (int i = NL - 1; i >= 0; i--) {
    i64 li = i < 10 ? l_limbs[i] : 0;
    if (x->v[i] != li) return x->v[i] > li;
  }
  return true;  // equal
}

void add_l(Limbs* x, const i64* l_limbs) {
  for (int i = 0; i < 10; i++) x->v[i] += l_limbs[i];
  normalize(x);
}

void sub_l(Limbs* x, const i64* l_limbs) {
  for (int i = 0; i < 10; i++) x->v[i] -= l_limbs[i];
  normalize(x);
}

// one fold: x = lo_252(x) - delta * (x >> 252); delta_limbs has 5 limbs
void fold(Limbs* x, const i64* delta_limbs) {
  i64 b[NL - 9];
  for (int i = 9; i < NL; i++) b[i - 9] = x->v[i];
  i128 acc[NL];
  for (int i = 0; i < NL; i++) acc[i] = i < 9 ? (i128)x->v[i] : 0;
  for (int i = 0; i < NL - 9; i++) {
    if (b[i] == 0) continue;
    for (int j = 0; j < 5; j++) {
      if (i + j < NL) acc[i + j] -= (i128)b[i] * delta_limbs[j];
    }
  }
  // carry the 128-bit accumulators back into signed 28-bit limbs
  i128 carry = 0;
  for (int i = 0; i < NL; i++) {
    i128 t = acc[i] + carry;
    carry = t >> LB;
    x->v[i] = (i64)(t - (carry << LB));
  }
  x->v[NL - 1] += (i64)(carry << LB);
}

struct Consts {
  i64 delta[5];
  i64 l[10];
};

Consts make_consts() {
  // delta and l from their big-endian hex forms, limb-decomposed at
  // runtime (no hand-packed tables to get wrong)
  static const uint8_t DELTA_LE[16] = {
      0xED, 0xD3, 0xF5, 0x5C, 0x1A, 0x63, 0x12, 0x58,
      0xD6, 0x9C, 0xF7, 0xA2, 0xDE, 0xF9, 0xDE, 0x14};
  static const uint8_t L_LE[33] = {
      0xED, 0xD3, 0xF5, 0x5C, 0x1A, 0x63, 0x12, 0x58,
      0xD6, 0x9C, 0xF7, 0xA2, 0xDE, 0xF9, 0xDE, 0x14,
      0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
      0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x10, 0x00};
  Consts c;
  Limbs d, l;
  load_le(DELTA_LE, 16, &d);
  load_le(L_LE, 33, &l);
  for (int i = 0; i < 5; i++) c.delta[i] = d.v[i];
  for (int i = 0; i < 10; i++) c.l[i] = l.v[i];
  return c;
}

// h (64 bytes LE) -> h mod l (32 bytes LE)
void sc_reduce(const uint8_t* h, uint8_t* out, const Consts& c) {
  Limbs x;
  load_le(h, 64, &x);
  fold(&x, c.delta);  // 512 -> ~406 bits
  fold(&x, c.delta);  // -> ~294
  fold(&x, c.delta);  // -> ~253
  fold(&x, c.delta);  // -> within +-2^168 of [0, 2^252)
  normalize(&x);
  while (is_negative(&x)) add_l(&x, c.l);
  while (geq_l(&x, c.l)) sub_l(&x, c.l);
  memset(out, 0, 32);
  for (int i = 0; i < 10; i++) {
    i64 v = x.v[i];
    for (int bit = 0; bit < LB; bit++) {
      int pos = i * LB + bit;
      if (pos >= 256) break;
      out[pos / 8] |= (uint8_t)(((v >> bit) & 1) << (pos % 8));
    }
  }
}

}  // namespace

// three-part streaming SHA-512, exported by sha512.cc
extern "C" void sha512_parts(const uint8_t* p1, size_t n1, const uint8_t* p2,
                             size_t n2, const uint8_t* p3, size_t n3,
                             uint8_t* out, size_t out_len);

extern "C" {

// For each i: out[i*32..] = SHA512(R_i || A_i || M_i) mod l, little-endian.
// rs/as are packed 32-byte arrays; messages are packed with offsets[n+1].
void ed25519_h_batch(const uint8_t* rs, const uint8_t* as,
                     const uint8_t* msgs, const uint64_t* offsets,
                     uint8_t* out, uint64_t n) {
  static const Consts c = make_consts();
  auto work = [&](uint64_t lo, uint64_t hi) {
    uint8_t digest[64];
    for (uint64_t i = lo; i < hi; i++) {
      sha512_parts(rs + 32 * i, 32, as + 32 * i, 32, msgs + offsets[i],
                   (size_t)(offsets[i + 1] - offsets[i]), digest, 64);
      sc_reduce(digest, out + 32 * i, c);
    }
  };
  unsigned nt = std::thread::hardware_concurrency();
  if (nt > 8) nt = 8;
  if (nt < 2 || n < 512) {
    work(0, n);
    return;
  }
  std::vector<std::thread> ts;
  uint64_t chunk = (n + nt - 1) / nt;
  for (unsigned t = 0; t < nt; t++) {
    uint64_t lo = t * chunk, hi = lo + chunk > n ? n : lo + chunk;
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// standalone batched scalar reduction (64B LE in, 32B LE out) — used by
// tests to differential-check sc_reduce against Python ints
void sc_reduce_batch(const uint8_t* h, uint8_t* out, uint64_t n) {
  static const Consts c = make_consts();
  for (uint64_t i = 0; i < n; i++) sc_reduce(h + 64 * i, out + 32 * i, c);
}

}  // extern "C"
