// Batched Ed25519 signature verification on the host (threaded C++).
//
// Role: the reference's per-signature host verify path is libsodium's C
// (StellarPublicKey::verifySignature,
// /root/reference/src/ripple_data/crypto/StellarPublicKey.cpp:67-77); our
// Python host path goes through OpenSSL one call at a time and pays
// per-call interpreter + GIL costs that cap it near 8.5k sigs/s however
// many threads run. This kernel verifies a whole batch in one ctypes
// call: R' = [S]B + [h](-A), accept iff encode(R') == R_bytes, with
// h = SHA512(R || A || M) mod l — the same cofactorless equation as the
// Python oracle (stellard_tpu/ops/ed25519_ref.py) and the JAX kernel
// (stellard_tpu/ops/ed25519_jax.py), written from the curve equations.
//
// Field arithmetic: radix-2^51 limbs with __int128 products (the natural
// 64-bit-host layout; the JAX kernel's 13-bit×20 limbs are a TPU-lane
// format, not a host format). Curve constants (d, sqrt(-1), the base
// point) are DERIVED at init from first principles — d = -121665/121666,
// By = 4/5 — so there are no hand-packed tables to get wrong.
//
// Scalar strategy: 4-bit unsigned Straus/Shamir interleaving. A static
// 15-entry cached table of B (shared, built once) and a per-signature
// 15-entry table of -A; 64 window steps of 4 doublings + up to 2 cached
// additions. All point formulas are the complete unified a=-1 twisted
// Edwards forms (add-2008-hwcd-3 / dbl-2008-hwcd), so identity and
// doubling cases need no special-casing.

#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

// exported by sha512.cc / ed25519_host.cc
extern "C" void sha512_parts(const uint8_t* p1, size_t n1, const uint8_t* p2,
                             size_t n2, const uint8_t* p3, size_t n3,
                             uint8_t* out, size_t out_len);
extern "C" void sc_reduce_batch(const char* h, uint8_t* out, uint64_t n);

namespace {

typedef unsigned __int128 u128;
typedef uint64_t u64;

constexpr u64 MASK51 = (1ULL << 51) - 1;

struct Fe {
  u64 v[5];  // radix-2^51, little-endian limbs, loosely reduced
};

const Fe FE_ZERO = {{0, 0, 0, 0, 0}};
const Fe FE_ONE = {{1, 0, 0, 0, 0}};

inline Fe fe_add(const Fe& a, const Fe& b) {
  Fe r;
  for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + b.v[i];
  return r;
}

// a - b + 2p (keeps limbs non-negative; inputs must be carry-reduced)
inline Fe fe_sub(const Fe& a, const Fe& b) {
  static const u64 TWO_P[5] = {
      2 * ((1ULL << 51) - 19), 2 * MASK51, 2 * MASK51, 2 * MASK51,
      2 * MASK51};
  Fe r;
  for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + TWO_P[i] - b.v[i];
  return r;
}

// one carry pass: brings limbs to ~51 bits (top folds ×19 into limb 0)
inline Fe fe_carry(const Fe& a) {
  Fe r = a;
  u64 c;
  c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
  c = r.v[1] >> 51; r.v[1] &= MASK51; r.v[2] += c;
  c = r.v[2] >> 51; r.v[2] &= MASK51; r.v[3] += c;
  c = r.v[3] >> 51; r.v[3] &= MASK51; r.v[4] += c;
  c = r.v[4] >> 51; r.v[4] &= MASK51; r.v[0] += c * 19;
  c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
  return r;
}

inline Fe fe_mul(const Fe& a, const Fe& b) {
  const u64 a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3], a4 = a.v[4];
  const u64 b0 = b.v[0], b1 = b.v[1], b2 = b.v[2], b3 = b.v[3], b4 = b.v[4];
  const u64 b1_19 = b1 * 19, b2_19 = b2 * 19, b3_19 = b3 * 19,
            b4_19 = b4 * 19;
  u128 r0 = (u128)a0 * b0 + (u128)a1 * b4_19 + (u128)a2 * b3_19 +
            (u128)a3 * b2_19 + (u128)a4 * b1_19;
  u128 r1 = (u128)a0 * b1 + (u128)a1 * b0 + (u128)a2 * b4_19 +
            (u128)a3 * b3_19 + (u128)a4 * b2_19;
  u128 r2 = (u128)a0 * b2 + (u128)a1 * b1 + (u128)a2 * b0 +
            (u128)a3 * b4_19 + (u128)a4 * b3_19;
  u128 r3 = (u128)a0 * b3 + (u128)a1 * b2 + (u128)a2 * b1 +
            (u128)a3 * b0 + (u128)a4 * b4_19;
  u128 r4 = (u128)a0 * b4 + (u128)a1 * b3 + (u128)a2 * b2 +
            (u128)a3 * b1 + (u128)a4 * b0;
  Fe out;
  u64 c;
  out.v[0] = (u64)r0 & MASK51; c = (u64)(r0 >> 51); r1 += c;
  out.v[1] = (u64)r1 & MASK51; c = (u64)(r1 >> 51); r2 += c;
  out.v[2] = (u64)r2 & MASK51; c = (u64)(r2 >> 51); r3 += c;
  out.v[3] = (u64)r3 & MASK51; c = (u64)(r3 >> 51); r4 += c;
  out.v[4] = (u64)r4 & MASK51; c = (u64)(r4 >> 51);
  out.v[0] += c * 19;
  c = out.v[0] >> 51; out.v[0] &= MASK51; out.v[1] += c;
  return out;
}

inline Fe fe_sq(const Fe& a) { return fe_mul(a, a); }

// full reduction to the canonical representative in [0, p)
Fe fe_freeze(const Fe& a) {
  Fe r = fe_carry(fe_carry(a));
  // subtract p if r >= p: add 19 and check overflow past 2^255
  u64 t[5];
  t[0] = r.v[0] + 19;
  u64 c = t[0] >> 51; t[0] &= MASK51;
  for (int i = 1; i < 5; i++) {
    t[i] = r.v[i] + c;
    c = t[i] >> 51;
    t[i] &= MASK51;
  }
  // c is 1 iff r + 19 >= 2^255, i.e. r >= p
  u64 use_t = (u64)0 - c;  // all-ones if r >= p
  for (int i = 0; i < 5; i++) r.v[i] = (t[i] & use_t) | (r.v[i] & ~use_t);
  return r;
}

void fe_tobytes(const Fe& a, uint8_t out[32]) {
  Fe f = fe_freeze(a);
  memset(out, 0, 32);
  for (int i = 0; i < 5; i++) {
    u64 v = f.v[i];
    for (int bit = 0; bit < 51; bit++) {
      int pos = i * 51 + bit;
      if (pos >= 256) break;
      out[pos / 8] |= (uint8_t)(((v >> bit) & 1) << (pos % 8));
    }
  }
}

// bytes (LE, high bit masked off by caller) -> limbs
Fe fe_frombytes(const uint8_t in[32]) {
  Fe r = FE_ZERO;
  for (int i = 0; i < 255; i++) {
    if ((in[i / 8] >> (i % 8)) & 1) r.v[i / 51] |= 1ULL << (i % 51);
  }
  return r;
}

// generic square-and-multiply, MSB-first over 255 bits of a LE exponent
Fe fe_pow(const Fe& base, const uint8_t exp_le[32]) {
  Fe r = FE_ONE;
  bool started = false;
  for (int i = 254; i >= 0; i--) {
    if (started) r = fe_sq(r);
    if ((exp_le[i / 8] >> (i % 8)) & 1) {
      r = started ? fe_mul(r, base) : base;
      started = true;
    }
  }
  return r;
}

bool fe_is_zero(const Fe& a) {
  uint8_t b[32];
  fe_tobytes(a, b);
  uint8_t acc = 0;
  for (int i = 0; i < 32; i++) acc |= b[i];
  return acc == 0;
}

bool fe_eq(const Fe& a, const Fe& b) { return fe_is_zero(fe_sub(a, b)); }

inline Fe fe_neg(const Fe& a) { return fe_sub(FE_ZERO, a); }

inline int fe_parity(const Fe& a) {
  uint8_t b[32];
  fe_tobytes(a, b);
  return b[0] & 1;
}

// --------------------------------------------------------------------------
// curve constants, derived at init

struct Consts {
  Fe d;        // -121665/121666
  Fe d2;       // 2d
  Fe sqrt_m1;  // 2^((p-1)/4)
  uint8_t p_le[32];         // p, little-endian bytes
  uint8_t pm2_le[32];       // p - 2   (invert exponent)
  uint8_t pm5_8_le[32];     // (p-5)/8 (sqrt-candidate exponent)
  uint8_t l_le[32];         // group order l (canonical-S bound)
};

// subtract a small value from a LE byte string in place
void bytes_sub_small(uint8_t* b, int len, unsigned v) {
  unsigned borrow = v;
  for (int i = 0; i < len && borrow; i++) {
    unsigned cur = b[i];
    b[i] = (uint8_t)(cur - (borrow & 0xFF));
    borrow = (cur < (borrow & 0xFF)) ? 1 + (borrow >> 8) : (borrow >> 8);
  }
}

Fe fe_invert(const Fe& a, const Consts& c) { return fe_pow(a, c.pm2_le); }

Fe fe_from_u64(u64 x) {
  Fe r = FE_ZERO;
  r.v[0] = x & MASK51;
  r.v[1] = x >> 51;
  return r;
}

Consts make_consts() {
  Consts c;
  // p = 2^255 - 19, LE
  memset(c.p_le, 0xFF, 32);
  c.p_le[31] = 0x7F;
  c.p_le[0] = 0xED;
  memcpy(c.pm2_le, c.p_le, 32);
  bytes_sub_small(c.pm2_le, 32, 2);
  // (p-5)/8 = 2^252 - 3
  memset(c.pm5_8_le, 0xFF, 32);
  c.pm5_8_le[31] = 0x0F;
  c.pm5_8_le[0] = 0xFD;
  // l = 2^252 + 27742317777372353535851937790883648493 (RFC 8032), from
  // the same LE byte form ed25519_host.cc derives its fold constants from
  static const uint8_t L_LE[32] = {
      0xED, 0xD3, 0xF5, 0x5C, 0x1A, 0x63, 0x12, 0x58,
      0xD6, 0x9C, 0xF7, 0xA2, 0xDE, 0xF9, 0xDE, 0x14,
      0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
      0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x10};
  memcpy(c.l_le, L_LE, 32);
  // d = -121665 / 121666
  c.d = fe_mul(fe_neg(fe_from_u64(121665)),
               fe_pow(fe_from_u64(121666), c.pm2_le));
  c.d2 = fe_carry(fe_add(c.d, c.d));
  // sqrt(-1) = 2^((p-1)/4); (p-1)/4 = 2^253 - 5
  uint8_t e[32];
  memset(e, 0xFF, 32);
  e[31] = 0x1F;
  e[0] = 0xFB;
  c.sqrt_m1 = fe_pow(fe_from_u64(2), e);
  return c;
}

const Consts& consts() {
  static const Consts c = make_consts();
  return c;
}

// --------------------------------------------------------------------------
// points

struct Ge {
  Fe X, Y, Z, T;  // extended: x = X/Z, y = Y/Z, T = XY/Z
};

struct GeCached {
  Fe ypx, ymx, t2d, z2;  // Y+X, Y-X, 2dT, 2Z
};

const Ge GE_IDENTITY = {FE_ZERO, FE_ONE, FE_ONE, FE_ZERO};

GeCached ge_to_cached(const Ge& p) {
  GeCached r;
  r.ypx = fe_carry(fe_add(p.Y, p.X));
  r.ymx = fe_carry(fe_sub(p.Y, p.X));
  r.t2d = fe_mul(p.T, consts().d2);
  r.z2 = fe_carry(fe_add(p.Z, p.Z));
  return r;
}

// complete unified addition, q cached (add-2008-hwcd-3, a=-1): 8M
Ge ge_add_cached(const Ge& p, const GeCached& q) {
  Fe a = fe_mul(fe_carry(fe_sub(p.Y, p.X)), q.ymx);
  Fe b = fe_mul(fe_carry(fe_add(p.Y, p.X)), q.ypx);
  Fe cc = fe_mul(p.T, q.t2d);
  Fe dd = fe_mul(p.Z, q.z2);
  Fe e = fe_carry(fe_sub(b, a));
  Fe f = fe_carry(fe_sub(dd, cc));
  Fe g = fe_carry(fe_add(dd, cc));
  Fe h = fe_carry(fe_add(b, a));
  Ge r;
  r.X = fe_mul(e, f);
  r.Y = fe_mul(g, h);
  r.Z = fe_mul(f, g);
  r.T = fe_mul(e, h);
  return r;
}

// dedicated doubling (dbl-2008-hwcd, a=-1): 4S + 4M
Ge ge_double(const Ge& p) {
  Fe a = fe_sq(p.X);
  Fe b = fe_sq(p.Y);
  Fe zz = fe_sq(p.Z);
  Fe cc = fe_carry(fe_add(zz, zz));
  Fe xy = fe_carry(fe_add(p.X, p.Y));
  Fe e = fe_carry(fe_sub(fe_carry(fe_sub(fe_sq(xy), a)), b));
  Fe g = fe_carry(fe_sub(b, a));         // G = aA + B = B - A
  Fe f = fe_carry(fe_sub(g, cc));        // F = G - C
  Fe h = fe_carry(fe_sub(fe_neg(a), b)); // H = aA - B = -A - B
  Ge r;
  r.X = fe_mul(e, f);
  r.Y = fe_mul(g, h);
  r.Z = fe_mul(f, g);
  r.T = fe_mul(e, h);
  return r;
}

// y-encoding (+ sign bit of x) of p, canonical
void ge_encode(const Ge& p, uint8_t out[32]) {
  Fe zi = fe_invert(p.Z, consts());
  Fe x = fe_mul(p.X, zi);
  Fe y = fe_mul(p.Y, zi);
  fe_tobytes(y, out);
  out[31] |= (uint8_t)(fe_parity(x) << 7);
}

// decode 32 bytes -> point; rejects non-canonical y (>= p) the way the
// production host library (RFC 8032 decode) does, recovers x from the
// curve equation, rejects non-residues and x=0-with-sign
bool ge_decode(const uint8_t in[32], Ge* out) {
  const Consts& c = consts();
  // canonical check: y bytes (high bit masked) must be < p
  uint8_t yb[32];
  memcpy(yb, in, 32);
  int sign = yb[31] >> 7;
  yb[31] &= 0x7F;
  bool lt = false;  // yb < p ?
  for (int i = 31; i >= 0; i--) {
    if (yb[i] < c.p_le[i]) { lt = true; break; }
    if (yb[i] > c.p_le[i]) return false;
  }
  if (!lt) return false;  // y == p is non-canonical too
  Fe y = fe_frombytes(yb);
  Fe y2 = fe_sq(y);
  Fe u = fe_carry(fe_sub(y2, FE_ONE));
  Fe v = fe_carry(fe_add(fe_mul(y2, c.d), FE_ONE));
  // candidate x = u v^3 (u v^7)^((p-5)/8)
  Fe v3 = fe_mul(fe_sq(v), v);
  Fe v7 = fe_mul(fe_sq(v3), v);
  Fe x = fe_mul(fe_mul(u, v3), fe_pow(fe_mul(u, v7), c.pm5_8_le));
  Fe vxx = fe_mul(v, fe_sq(x));
  if (!fe_eq(vxx, u)) {
    if (!fe_eq(vxx, fe_neg(u))) return false;  // non-residue: not a point
    x = fe_mul(x, c.sqrt_m1);
  }
  if (fe_is_zero(x)) {
    if (sign) return false;  // -0 is not encodable
  } else if (fe_parity(x) != sign) {
    x = fe_neg(x);
  }
  out->X = fe_carry(x);
  out->Y = y;
  out->Z = FE_ONE;
  out->T = fe_mul(out->X, y);
  return true;
}

// --------------------------------------------------------------------------
// Straus 4-bit double-scalar multiplication

// table[k] = (k+1) * p in cached form, k = 0..14
void build_table(const Ge& p, GeCached table[15]) {
  Ge multiples[15];
  multiples[0] = p;
  for (int k = 1; k < 15; k++)
    multiples[k] = (k & 1) ? ge_double(multiples[k / 2])
                           : ge_add_cached(multiples[k - 1],
                                           ge_to_cached(p));
  for (int k = 0; k < 15; k++) table[k] = ge_to_cached(multiples[k]);
}

const GeCached* base_table() {
  static GeCached table[15];
  static std::once_flag flag;
  std::call_once(flag, [] {
    const Consts& c = consts();
    // By = 4/5, Bx = sqrt from the curve equation with even parity
    Fe by = fe_mul(fe_from_u64(4), fe_invert(fe_from_u64(5), c));
    uint8_t enc[32];
    fe_tobytes(by, enc);  // sign bit 0 = even x
    Ge b;
    bool ok = ge_decode(enc, &b);
    (void)ok;  // by construction 4/5 decodes
    build_table(b, table);
  });
  return table;
}

inline int nibble(const uint8_t* le32, int i) {
  return (le32[i >> 1] >> ((i & 1) << 2)) & 0xF;
}

// R' = [s]B + [h]negA  (s, h little-endian 32-byte scalars < l)
Ge straus(const uint8_t s_le[32], const uint8_t h_le[32],
          const GeCached nega_table[15]) {
  const GeCached* btab = base_table();
  Ge q = GE_IDENTITY;
  for (int i = 63; i >= 0; i--) {
    q = ge_double(ge_double(ge_double(ge_double(q))));
    int ns = nibble(s_le, i);
    if (ns) q = ge_add_cached(q, btab[ns - 1]);
    int nh = nibble(h_le, i);
    if (nh) q = ge_add_cached(q, nega_table[nh - 1]);
  }
  return q;
}

// s (LE 32 bytes) < l ?
bool scalar_canonical(const uint8_t s_le[32]) {
  const Consts& c = consts();
  for (int i = 31; i >= 0; i--) {
    if (s_le[i] < c.l_le[i]) return true;
    if (s_le[i] > c.l_le[i]) return false;
  }
  return false;  // equal
}

// one full verification; msg is the (usually 32-byte) signing hash
bool verify_one(const uint8_t pub[32], const uint8_t* msg, size_t msg_len,
                const uint8_t sig[64]) {
  if (!scalar_canonical(sig + 32)) return false;  // canonical-S rule
  Ge a;
  if (!ge_decode(pub, &a)) return false;
  // h = SHA512(R || A || M) mod l
  uint8_t digest[64], h[32];
  sha512_parts(sig, 32, pub, 32, msg, msg_len, digest, 64);
  sc_reduce_batch((const char*)digest, h, 1);
  // negate A, build its window table
  Ge nega;
  nega.X = fe_neg(a.X);
  nega.Y = a.Y;
  nega.Z = a.Z;
  nega.T = fe_neg(a.T);
  GeCached nega_table[15];
  build_table(nega, nega_table);
  Ge rp = straus(sig + 32, h, nega_table);
  uint8_t enc[32];
  ge_encode(rp, enc);
  return memcmp(enc, sig, 32) == 0;
}

}  // namespace

extern "C" {

// out[i] = 1 if signature i verifies. pubs: packed 32B; sigs: packed
// 64B; msgs: packed with offsets[n+1] (same shape as ed25519_h_batch).
void ed25519_verify_batch(const uint8_t* pubs, const uint8_t* msgs,
                          const uint64_t* offsets, const uint8_t* sigs,
                          uint8_t* out, uint64_t n) {
  (void)base_table();  // build the shared table before threads fan out
  auto work = [&](uint64_t lo, uint64_t hi) {
    for (uint64_t i = lo; i < hi; i++) {
      out[i] = verify_one(pubs + 32 * i, msgs + offsets[i],
                          (size_t)(offsets[i + 1] - offsets[i]),
                          sigs + 64 * i)
                   ? 1
                   : 0;
    }
  };
  unsigned nt = std::thread::hardware_concurrency();
  if (nt > 8) nt = 8;
  if (nt < 2 || n < 16) {
    work(0, n);
    return;
  }
  std::vector<std::thread> ts;
  uint64_t chunk = (n + nt - 1) / nt;
  for (unsigned t = 0; t < nt; t++) {
    uint64_t lo = t * chunk, hi = lo + chunk > n ? n : lo + chunk;
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // extern "C"
