// cpplog: a log-structured content-addressed NodeStore backend.
//
// Role parity: the reference vendors LevelDB/HyperLevelDB/RocksDB as
// NodeStore backends (SURVEY §2.8). A ledger NodeStore is a much easier
// case than a general KV store: keys are 32-byte content hashes
// (immutable, never overwritten, no range scans), so an append-only
// data log plus an open-addressed hash index gives O(1) reads/writes
// with one fsync per batch — the same role, a fraction of the machinery.
//
// File layout:
//   <path>.log : [u32 len | u8 type | 32B key | blob] records, appended
//   index      : in-memory open addressing, rebuilt by scanning the log
//                on open (the log IS the database; crash-safe by replay)
//
// C ABI consumed via ctypes from stellard_tpu/nodestore/cpplog.py.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <unistd.h>  // fsync, ftruncate

namespace {

struct Slot {
  uint8_t key[32];
  uint64_t offset;  // offset of the record body in the log, +1 (0 = empty)
};

struct Store {
  FILE* f = nullptr;
  std::string path;
  std::vector<Slot> slots;
  uint64_t count = 0;
  uint64_t file_size = 0;

  size_t mask() const { return slots.size() - 1; }
};

static inline uint64_t key_hash(const uint8_t* key) {
  // keys are uniform hashes already: take 8 bytes
  uint64_t h;
  memcpy(&h, key, 8);
  return h;
}

static void index_put(Store* s, const uint8_t* key, uint64_t offset_plus1) {
  size_t i = key_hash(key) & s->mask();
  while (s->slots[i].offset != 0) {
    if (memcmp(s->slots[i].key, key, 32) == 0) return;  // content-addressed
    i = (i + 1) & s->mask();
  }
  memcpy(s->slots[i].key, key, 32);
  s->slots[i].offset = offset_plus1;
  s->count++;
}

static void index_grow(Store* s) {
  std::vector<Slot> old = std::move(s->slots);
  s->slots.assign(old.size() * 2, Slot{});
  s->count = 0;
  for (const Slot& sl : old)
    if (sl.offset) index_put(s, sl.key, sl.offset);
}

static bool read_exact(FILE* f, void* buf, size_t n) {
  return fread(buf, 1, n, f) == n;
}

}  // namespace

extern "C" {

void* cpplog_open(const char* path) {
  Store* s = new Store();
  s->path = path;
  s->slots.assign(1 << 16, Slot{});
  FILE* f = fopen(path, "ab+");
  if (!f) {
    delete s;
    return nullptr;
  }
  s->f = f;
  // replay the log to rebuild the index; a torn tail (crash mid-append)
  // is truncated away so new appends land exactly where the last VALID
  // record ends — otherwise the torn header's length would desynchronize
  // every later replay
  fseek(f, 0, SEEK_END);
  uint64_t end = (uint64_t)ftell(f);
  fseek(f, 0, SEEK_SET);
  uint64_t off = 0;
  for (;;) {
    uint8_t hdr[5];
    if (!read_exact(f, hdr, 5)) break;
    uint32_t len;
    memcpy(&len, hdr, 4);
    uint64_t body = off + 5 + 32;
    if (body + len > end) break;  // torn record: header claims past EOF
    uint8_t key[32];
    if (!read_exact(f, key, 32)) break;
    if (fseek(f, (long)len, SEEK_CUR) != 0) break;
    if (s->count * 10 >= s->slots.size() * 7) index_grow(s);
    index_put(s, key, body + 1);
    off = body + len;
  }
  if (off < end) {
    fflush(f);
    if (ftruncate(fileno(f), (off_t)off) != 0) {
      fclose(f);
      delete s;
      return nullptr;
    }
  }
  fseek(f, 0, SEEK_END);
  s->file_size = (uint64_t)ftell(f);
  return s;
}

// store one record; returns 0 on success
int cpplog_put(void* handle, const uint8_t* key, uint8_t type,
               const uint8_t* blob, uint32_t len) {
  Store* s = (Store*)handle;
  if (!s->f) return -1;  // store previously failed; refuse further puts
  {
    // dedup: content-addressed, second write is a no-op
    size_t i = key_hash(key) & s->mask();
    while (s->slots[i].offset != 0) {
      if (memcmp(s->slots[i].key, key, 32) == 0) return 0;
      i = (i + 1) & s->mask();
    }
  }
  uint8_t hdr[5];
  uint32_t body_len = len + 1;  // type byte + blob
  memcpy(hdr, &body_len, 4);
  hdr[4] = 0;  // reserved
  fseek(s->f, 0, SEEK_END);
  uint64_t off = (uint64_t)ftell(s->f);
  bool ok = fwrite(hdr, 1, 5, s->f) == 5 && fwrite(key, 1, 32, s->f) == 32 &&
            fwrite(&type, 1, 1, s->f) == 1 &&
            (len == 0 || fwrite(blob, 1, len, s->f) == len);
  if (!ok) {
    // a torn record would desynchronize the reopen replay at its header,
    // silently dropping every later record — truncate it away so a
    // subsequent successful put appends at a clean boundary. If either
    // the flush or the truncate fails we cannot guarantee a clean tail
    // (stale stdio-buffered bytes could later be flushed past the
    // truncated EOF): mark the store failed and refuse further puts.
    if (fflush(s->f) != 0 ||
        ftruncate(fileno(s->f), (off_t)off) != 0) {
      fclose(s->f);
      s->f = nullptr;
    }
    return -1;
  }
  if (s->count * 10 >= s->slots.size() * 7) index_grow(s);
  index_put(s, key, off + 5 + 32 + 1);
  s->file_size = off + 5 + 32 + body_len;
  return 0;
}

// fetch: returns blob length (incl. type byte at out[0]); -1 if absent;
// when the caller's buffer is too small, returns -2 - needed_length so
// the caller can resize exactly and retry
int64_t cpplog_get(void* handle, const uint8_t* key, uint8_t* out,
                   uint64_t out_cap) {
  Store* s = (Store*)handle;
  if (!s->f) return -1;
  size_t i = key_hash(key) & s->mask();
  while (s->slots[i].offset != 0) {
    if (memcmp(s->slots[i].key, key, 32) == 0) {
      uint64_t body = s->slots[i].offset - 1;
      // record header sits 37 bytes before the body
      fseek(s->f, (long)(body - 37), SEEK_SET);
      uint8_t hdr[5];
      if (!read_exact(s->f, hdr, 5)) return -1;
      uint32_t body_len;
      memcpy(&body_len, hdr, 4);
      if (body_len > out_cap) return -2 - (int64_t)body_len;
      fseek(s->f, (long)body, SEEK_SET);
      if (!read_exact(s->f, out, body_len)) return -1;
      return (int64_t)body_len;
    }
    i = (i + 1) & s->mask();
  }
  return -1;
}

uint64_t cpplog_count(void* handle) { return ((Store*)handle)->count; }

int cpplog_sync(void* handle) {
  FILE* f = ((Store*)handle)->f;
  if (!f || fflush(f) != 0) return -1;
  return fsync(fileno(f));  // page cache → disk: the durability promise
}

void cpplog_close(void* handle) {
  Store* s = (Store*)handle;
  if (s->f) {
    fflush(s->f);
    fclose(s->f);
  }
  delete s;
}

}  // extern "C"
