// cpplog: a log-structured content-addressed NodeStore backend.
//
// Role parity: the reference vendors LevelDB/HyperLevelDB/RocksDB as
// NodeStore backends (SURVEY §2.8). A ledger NodeStore is a much easier
// case than a general KV store: keys are 32-byte content hashes
// (immutable, never overwritten, no range scans), so an append-only
// data log plus an open-addressed hash index gives O(1) reads/writes
// with one fsync per batch — the same role, a fraction of the machinery.
//
// File layout:
//   <path>.log : [u32 len | u8 type | 32B key | blob] records, appended
//   index      : in-memory open addressing, rebuilt by scanning the log
//                on open (the log IS the database; crash-safe by replay)
//
// C ABI consumed via ctypes from stellard_tpu/nodestore/cpplog.py.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <unistd.h>  // fsync, ftruncate

namespace {

struct Slot {
  uint8_t key[32];
  uint64_t offset;  // offset of the record body in the log, +1 (0 = empty)
};

struct Store {
  FILE* f = nullptr;
  std::string path;
  std::vector<Slot> slots;
  uint64_t count = 0;
  uint64_t file_size = 0;

  size_t mask() const { return slots.size() - 1; }
};

static inline uint64_t key_hash(const uint8_t* key) {
  // keys are uniform hashes already: take 8 bytes
  uint64_t h;
  memcpy(&h, key, 8);
  return h;
}

static void index_put(Store* s, const uint8_t* key, uint64_t offset_plus1) {
  size_t i = key_hash(key) & s->mask();
  while (s->slots[i].offset != 0) {
    if (memcmp(s->slots[i].key, key, 32) == 0) return;  // content-addressed
    i = (i + 1) & s->mask();
  }
  memcpy(s->slots[i].key, key, 32);
  s->slots[i].offset = offset_plus1;
  s->count++;
}

static void index_grow(Store* s) {
  std::vector<Slot> old = std::move(s->slots);
  s->slots.assign(old.size() * 2, Slot{});
  s->count = 0;
  for (const Slot& sl : old)
    if (sl.offset) index_put(s, sl.key, sl.offset);
}

static bool read_exact(FILE* f, void* buf, size_t n) {
  return fread(buf, 1, n, f) == n;
}

}  // namespace

extern "C" {

void* cpplog_open(const char* path) {
  Store* s = new Store();
  s->path = path;
  s->slots.assign(1 << 16, Slot{});
  FILE* f = fopen(path, "ab+");
  if (!f) {
    delete s;
    return nullptr;
  }
  s->f = f;
  // replay the log to rebuild the index; a torn tail (crash mid-append)
  // is truncated away so new appends land exactly where the last VALID
  // record ends — otherwise the torn header's length would desynchronize
  // every later replay
  fseek(f, 0, SEEK_END);
  uint64_t end = (uint64_t)ftell(f);
  fseek(f, 0, SEEK_SET);
  uint64_t off = 0;
  for (;;) {
    uint8_t hdr[5];
    if (!read_exact(f, hdr, 5)) break;
    uint32_t len;
    memcpy(&len, hdr, 4);
    uint64_t body = off + 5 + 32;
    if (body + len > end) break;  // torn record: header claims past EOF
    uint8_t key[32];
    if (!read_exact(f, key, 32)) break;
    if (fseek(f, (long)len, SEEK_CUR) != 0) break;
    if (s->count * 10 >= s->slots.size() * 7) index_grow(s);
    index_put(s, key, body + 1);
    off = body + len;
  }
  if (off < end) {
    fflush(f);
    if (ftruncate(fileno(f), (off_t)off) != 0) {
      fclose(f);
      delete s;
      return nullptr;
    }
  }
  fseek(f, 0, SEEK_END);
  s->file_size = (uint64_t)ftell(f);
  return s;
}

// store one record; returns 0 on success
int cpplog_put(void* handle, const uint8_t* key, uint8_t type,
               const uint8_t* blob, uint32_t len) {
  Store* s = (Store*)handle;
  if (!s->f) return -1;  // store previously failed; refuse further puts
  {
    // dedup: content-addressed, second write is a no-op
    size_t i = key_hash(key) & s->mask();
    while (s->slots[i].offset != 0) {
      if (memcmp(s->slots[i].key, key, 32) == 0) return 0;
      i = (i + 1) & s->mask();
    }
  }
  uint8_t hdr[5];
  uint32_t body_len = len + 1;  // type byte + blob
  memcpy(hdr, &body_len, 4);
  hdr[4] = 0;  // reserved
  fseek(s->f, 0, SEEK_END);
  uint64_t off = (uint64_t)ftell(s->f);
  bool ok = fwrite(hdr, 1, 5, s->f) == 5 && fwrite(key, 1, 32, s->f) == 32 &&
            fwrite(&type, 1, 1, s->f) == 1 &&
            (len == 0 || fwrite(blob, 1, len, s->f) == len);
  if (!ok) {
    // a torn record would desynchronize the reopen replay at its header,
    // silently dropping every later record — truncate it away so a
    // subsequent successful put appends at a clean boundary. If either
    // the flush or the truncate fails we cannot guarantee a clean tail
    // (stale stdio-buffered bytes could later be flushed past the
    // truncated EOF): mark the store failed and refuse further puts.
    if (fflush(s->f) != 0 ||
        ftruncate(fileno(s->f), (off_t)off) != 0) {
      fclose(s->f);
      s->f = nullptr;
    }
    return -1;
  }
  if (s->count * 10 >= s->slots.size() * 7) index_grow(s);
  index_put(s, key, off + 5 + 32 + 1);
  s->file_size = off + 5 + 32 + body_len;
  return 0;
}

// fetch: returns blob length (incl. type byte at out[0]); -1 if absent;
// when the caller's buffer is too small, returns -2 - needed_length so
// the caller can resize exactly and retry
int64_t cpplog_get(void* handle, const uint8_t* key, uint8_t* out,
                   uint64_t out_cap) {
  Store* s = (Store*)handle;
  if (!s->f) return -1;
  size_t i = key_hash(key) & s->mask();
  while (s->slots[i].offset != 0) {
    if (memcmp(s->slots[i].key, key, 32) == 0) {
      uint64_t body = s->slots[i].offset - 1;
      // record header sits 37 bytes before the body
      fseek(s->f, (long)(body - 37), SEEK_SET);
      uint8_t hdr[5];
      if (!read_exact(s->f, hdr, 5)) return -1;
      uint32_t body_len;
      memcpy(&body_len, hdr, 4);
      if (body_len > out_cap) return -2 - (int64_t)body_len;
      fseek(s->f, (long)body, SEEK_SET);
      if (!read_exact(s->f, out, body_len)) return -1;
      return (int64_t)body_len;
    }
    i = (i + 1) & s->mask();
  }
  return -1;
}

uint64_t cpplog_count(void* handle) { return ((Store*)handle)->count; }

// iterate every live record through a callback (ctypes CFUNCTYPE on the
// Python side). Deletion/export/crash-recovery audits need iteration on
// every durable backend; the index already holds every key, so this is
// one pass over the slots with one read per record. A nonzero callback
// return stops the scan early. Returns records visited, or -1 on a read
// error (a record the index points at that cannot be read back is
// corruption, not end-of-data).
typedef int (*cpplog_iter_cb)(void* ctx, const uint8_t* key, uint8_t type,
                              const uint8_t* blob, uint32_t len);

int64_t cpplog_iterate(void* handle, cpplog_iter_cb cb, void* ctx) {
  Store* s = (Store*)handle;
  if (!s->f) return -1;
  if (fflush(s->f) != 0) return -1;  // buffered appends must be visible
  std::vector<uint8_t> buf(65536);
  int64_t visited = 0;
  for (const Slot& sl : s->slots) {
    if (!sl.offset) continue;
    uint64_t body = sl.offset - 1;
    fseek(s->f, (long)(body - 37), SEEK_SET);
    uint8_t hdr[5];
    if (!read_exact(s->f, hdr, 5)) return -1;
    uint32_t body_len;
    memcpy(&body_len, hdr, 4);
    if (body_len < 1) return -1;
    if (body_len > buf.size()) buf.resize(body_len);
    fseek(s->f, (long)body, SEEK_SET);
    if (!read_exact(s->f, buf.data(), body_len)) return -1;
    visited++;
    if (cb(ctx, sl.key, buf[0], buf.data() + 1, body_len - 1) != 0) break;
  }
  fseek(s->f, 0, SEEK_END);
  return visited;
}

// ---------------------------------------------------------------------------
// segstore: native primitives for the segmented log-structured backend
// (stellard_tpu/nodestore/segstore.py). The Python side owns segments,
// durability, checkpoint files and compaction policy; the C side owns
// the three O(store)/O(batch) inner loops a 1M-node store cannot afford
// in the interpreter: the in-memory key index, the one-call append-image
// pack from the flat-buffer node encoding, and the open-time segment
// replay that rebuilds the index without a per-record Python round-trip.
//
// loc encoding (shared contract with segstore.py): 64-bit
//   (seg_id << 44) | record_offset
// record layout (shared with cpplog so torn-tail logic stays uniform):
//   [u32 body_len LE | u8 flags=0 | 32B key | u8 type | blob]
// body_len counts the type byte + blob; a record is 37 + body_len bytes.

namespace {

constexpr uint64_t kTombLoc = ~0ull;  // slot marker: removed entry

struct SegIdx {
  std::vector<Slot> slots;  // offset field stores loc+1 (0 empty, ~0 tomb)
  uint64_t live = 0;
  uint64_t used = 0;  // live + tombstones (grow trigger)

  size_t mask() const { return slots.size() - 1; }
};

static void segidx_insert(SegIdx* x, const uint8_t* key, uint64_t loc_plus1) {
  size_t i = key_hash(key) & x->mask();
  size_t first_tomb = SIZE_MAX;
  while (x->slots[i].offset != 0) {
    if (x->slots[i].offset == kTombLoc) {
      if (first_tomb == SIZE_MAX) first_tomb = i;
    } else if (memcmp(x->slots[i].key, key, 32) == 0) {
      x->slots[i].offset = loc_plus1;  // overwrite: latest write wins
      return;
    }
    i = (i + 1) & x->mask();
  }
  if (first_tomb != SIZE_MAX) {
    i = first_tomb;  // reuse the tombstone: bounded probe chains
  } else {
    x->used++;
  }
  memcpy(x->slots[i].key, key, 32);
  x->slots[i].offset = loc_plus1;
  x->live++;
}

static void segidx_grow(SegIdx* x, size_t min_size) {
  size_t size = x->slots.size();
  while (size < min_size || x->live * 10 >= size * 6) size *= 2;
  std::vector<Slot> old = std::move(x->slots);
  x->slots.assign(size, Slot{});
  x->live = x->used = 0;
  for (const Slot& sl : old)
    if (sl.offset != 0 && sl.offset != kTombLoc)
      segidx_insert(x, sl.key, sl.offset);
}

static void segidx_maybe_grow(SegIdx* x, uint64_t incoming) {
  if ((x->used + incoming) * 10 >= x->slots.size() * 7)
    segidx_grow(x, x->slots.size() * 2);
}

}  // namespace

void* segidx_new(uint64_t cap_hint) {
  SegIdx* x = new SegIdx();
  size_t size = 1 << 12;
  while (size * 7 < (cap_hint ? cap_hint : 1) * 10) size *= 2;
  x->slots.assign(size, Slot{});
  return x;
}

void segidx_free(void* h) { delete (SegIdx*)h; }

uint64_t segidx_count(void* h) { return ((SegIdx*)h)->live; }

int segidx_put_batch(void* h, uint64_t n, const uint8_t* keys,
                     const uint64_t* locs) {
  SegIdx* x = (SegIdx*)h;
  segidx_maybe_grow(x, n);
  for (uint64_t i = 0; i < n; i++) {
    if (locs[i] >= kTombLoc - 1) return -1;  // loc+1 would collide w/ tomb
    segidx_maybe_grow(x, 1);
    segidx_insert(x, keys + 32 * i, locs[i] + 1);
  }
  return 0;
}

int64_t segidx_get(void* h, const uint8_t* key) {
  SegIdx* x = (SegIdx*)h;
  size_t i = key_hash(key) & x->mask();
  while (x->slots[i].offset != 0) {
    if (x->slots[i].offset != kTombLoc &&
        memcmp(x->slots[i].key, key, 32) == 0)
      return (int64_t)(x->slots[i].offset - 1);
    i = (i + 1) & x->mask();
  }
  return -1;
}

// remove `key` iff its loc equals expect_loc (pass ~0 to remove
// unconditionally) — the compare-and-delete the sweep's re-append race
// needs: a key re-written after the dead-set snapshot has a new loc and
// must survive. Returns 1 removed, 0 not present / loc mismatch.
int segidx_remove(void* h, const uint8_t* key, uint64_t expect_loc) {
  SegIdx* x = (SegIdx*)h;
  size_t i = key_hash(key) & x->mask();
  while (x->slots[i].offset != 0) {
    if (x->slots[i].offset != kTombLoc &&
        memcmp(x->slots[i].key, key, 32) == 0) {
      if (expect_loc + 1 != 0 && x->slots[i].offset != expect_loc + 1)
        return 0;
      x->slots[i].offset = kTombLoc;
      x->live--;
      return 1;
    }
    i = (i + 1) & x->mask();
  }
  return 0;
}

// mask[i]=1 where keys[i] is NOT in the index — the batch dedup filter
// (one call per store_batch instead of one segidx_get per node). Also
// dedups WITHIN the batch: the second occurrence of a key gets mask 0.
void segidx_filter_new(void* h, uint64_t n, const uint8_t* keys,
                       uint8_t* mask) {
  SegIdx* x = (SegIdx*)h;
  for (uint64_t i = 0; i < n; i++)
    mask[i] = segidx_get(h, keys + 32 * i) < 0 ? 1 : 0;
  // in-batch duplicates: keep the first occurrence only (content-
  // addressed, so both carry identical bytes)
  if (n > 1) {
    SegIdx seen;
    seen.slots.assign(1 << 12, Slot{});
    for (uint64_t i = 0; i < n; i++) {
      if (!mask[i]) continue;
      if (segidx_get(&seen, keys + 32 * i) >= 0) {
        mask[i] = 0;
        continue;
      }
      segidx_maybe_grow(&seen, 1);
      segidx_insert(&seen, keys + 32 * i, 1);
    }
  }
  (void)x;
}

// serialize every live entry as [32B key | u64 loc LE] for the index
// checkpoint; returns entries written (stops at cap_entries).
uint64_t segidx_dump(void* h, uint8_t* out, uint64_t cap_entries) {
  SegIdx* x = (SegIdx*)h;
  uint64_t n = 0;
  for (const Slot& sl : x->slots) {
    if (sl.offset == 0 || sl.offset == kTombLoc) continue;
    if (n >= cap_entries) break;
    memcpy(out + n * 40, sl.key, 32);
    uint64_t loc = sl.offset - 1;
    memcpy(out + n * 40 + 32, &loc, 8);
    n++;
  }
  return n;
}

// bulk-load a checkpoint blob (n entries of [32B key | u64 loc LE]) —
// the open path for a 1M-node store; one call, no Python per entry.
int segidx_load(void* h, const uint8_t* blob, uint64_t n) {
  SegIdx* x = (SegIdx*)h;
  segidx_maybe_grow(x, n);
  for (uint64_t i = 0; i < n; i++) {
    uint64_t loc;
    memcpy(&loc, blob + i * 40 + 32, 8);
    if (loc >= kTombLoc - 1) return -1;
    segidx_maybe_grow(x, 1);
    segidx_insert(x, blob + i * 40, loc + 1);
  }
  return 0;
}

// build the one-append segment image for n records whose blobs live in
// ONE contiguous buffer (the pack_nodes flat-buffer output, consumed
// as-is): [u32 body_len | u8 flags | 32B key | u8 type | blob] each.
// Returns total bytes written, or -1 when cap is too small.
int64_t segstore_pack(uint64_t n, const uint8_t* keys, const uint8_t* types,
                      const uint8_t* blobs, const uint64_t* offsets,
                      uint8_t* out, uint64_t cap) {
  uint64_t pos = 0;
  for (uint64_t i = 0; i < n; i++) {
    uint64_t blen = offsets[i + 1] - offsets[i];
    uint64_t rec = 37 + 1 + blen;
    if (pos + rec > cap || blen + 1 > 0xFFFFFFFFull) return -1;
    uint32_t body_len = (uint32_t)(blen + 1);
    memcpy(out + pos, &body_len, 4);
    out[pos + 4] = 0;
    memcpy(out + pos + 5, keys + 32 * i, 32);
    out[pos + 37] = types[i];
    memcpy(out + pos + 38, blobs + offsets[i], blen);
    pos += rec;
  }
  return (int64_t)pos;
}

// scan one segment file from byte offset `start`, inserting every valid
// record into the index with loc = (seg_id << 44) | record_offset
// (later records overwrite earlier ones — ascending replay order makes
// the newest location win). Stops at the first torn record. Returns the
// clean end offset (callers truncate the ACTIVE segment there), or -1
// when the file cannot be opened. out_records/out_bytes accumulate the
// replay counters the checkpointed-open tests pin.
int64_t segstore_replay(void* h, const char* path, uint32_t seg_id,
                        uint64_t start, uint64_t* out_records,
                        uint64_t* out_bytes) {
  SegIdx* x = (SegIdx*)h;
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  uint64_t end = (uint64_t)ftell(f);
  if (start > end) start = end;
  fseek(f, (long)start, SEEK_SET);
  uint64_t off = start;
  uint64_t recs = 0, bytes = 0;
  for (;;) {
    uint8_t hdr[5];
    if (!read_exact(f, hdr, 5)) break;
    uint32_t body_len;
    memcpy(&body_len, hdr, 4);
    if (body_len < 1 || off + 37 + body_len > end) break;  // torn tail
    uint8_t key[32];
    if (!read_exact(f, key, 32)) break;
    if (fseek(f, (long)body_len, SEEK_CUR) != 0) break;
    segidx_maybe_grow(x, 1);
    segidx_insert(x, key, (((uint64_t)seg_id << 44) | off) + 1);
    off += 37 + body_len;
    recs++;
    bytes += 37 + body_len;
  }
  fclose(f);
  if (out_records) *out_records += recs;
  if (out_bytes) *out_bytes += bytes;
  return (int64_t)off;
}

// scan segment-format records ([u32 body_len LE | u8 flags | 32B key |
// u8 type | blob]) in `path` starting at byte `start`, filling parallel
// arrays: keys_out (32B each), types_out, offs_out (file offset of the
// BLOB), lens_out (blob length). The decode-on-demand seam of the
// out-of-core plane: history-shard opens index a whole file of packed
// records in one C pass (key/type/offset only — blobs stay on disk and
// are pread on fault) instead of one Python struct unpack per record.
// Returns the number of clean records found; fills at most `cap` of
// them (call once with cap=0 to size the arrays); -1 if the file
// cannot be opened. Stops at the first torn record.
int64_t segrecs_scan(const char* path, uint64_t start, uint64_t cap,
                     uint8_t* keys_out, uint8_t* types_out,
                     uint64_t* offs_out, uint64_t* lens_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  uint64_t end = (uint64_t)ftell(f);
  if (start > end) start = end;
  fseek(f, (long)start, SEEK_SET);
  uint64_t off = start;
  uint64_t n = 0;
  for (;;) {
    uint8_t hdr[37];
    if (!read_exact(f, hdr, 37)) break;
    uint32_t body_len;
    memcpy(&body_len, hdr, 4);
    if (body_len < 1 || off + 37 + body_len > end) break;  // torn tail
    if (n < cap) {
      memcpy(keys_out + 32 * n, hdr + 5, 32);
      uint8_t type_byte;
      if (!read_exact(f, &type_byte, 1)) break;
      types_out[n] = type_byte;
      offs_out[n] = off + 38;      // blob starts after header + type
      lens_out[n] = body_len - 1;  // body_len counts the type byte
      if (fseek(f, (long)(body_len - 1), SEEK_CUR) != 0) break;
    } else {
      if (fseek(f, (long)body_len, SEEK_CUR) != 0) break;
    }
    off += 37 + body_len;
    n++;
  }
  fclose(f);
  return (int64_t)n;
}

int cpplog_sync(void* handle) {
  FILE* f = ((Store*)handle)->f;
  if (!f || fflush(f) != 0) return -1;
  return fsync(fileno(f));  // page cache → disk: the durability promise
}

void cpplog_close(void* handle) {
  Store* s = (Store*)handle;
  if (s->f) {
    fflush(s->f);
    fclose(s->f);
  }
  delete s;
}

}  // extern "C"
