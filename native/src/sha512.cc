// Batched SHA-512 / SHA-512-half for the host hashing plane.
//
// Role parity: the reference computes every tree/identity hash with
// OpenSSL SHA-512 one call at a time (Serializer.cpp:342-390). Here the
// batch API hashes N independent messages in one C call (OpenMP-style
// threading left to the caller; the Python side slices batches across a
// thread pool with the GIL released by ctypes).
//
// Implementation is from the FIPS 180-4 specification.

#include <cstdint>
#include <cstring>

namespace {

typedef uint64_t u64;

static const u64 K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

static inline u64 rotr(u64 x, int n) { return (x >> n) | (x << (64 - n)); }
static inline u64 load64(const uint8_t* p) {
  u64 v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}
static inline void store64(uint8_t* p, u64 v) {
  for (int i = 7; i >= 0; i--) {
    p[i] = (uint8_t)(v & 0xff);
    v >>= 8;
  }
}

struct State {
  u64 h[8];
};

static void init(State* s) {
  static const u64 H0[8] = {0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
                            0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
                            0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
                            0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
  memcpy(s->h, H0, sizeof(H0));
}

static void compress(State* s, const uint8_t* block) {
  u64 w[80];
  for (int t = 0; t < 16; t++) w[t] = load64(block + 8 * t);
  for (int t = 16; t < 80; t++) {
    u64 s0 = rotr(w[t - 15], 1) ^ rotr(w[t - 15], 8) ^ (w[t - 15] >> 7);
    u64 s1 = rotr(w[t - 2], 19) ^ rotr(w[t - 2], 61) ^ (w[t - 2] >> 6);
    w[t] = w[t - 16] + s0 + w[t - 7] + s1;
  }
  u64 a = s->h[0], b = s->h[1], c = s->h[2], d = s->h[3];
  u64 e = s->h[4], f = s->h[5], g = s->h[6], h = s->h[7];
  for (int t = 0; t < 80; t++) {
    u64 S1 = rotr(e, 14) ^ rotr(e, 18) ^ rotr(e, 41);
    u64 ch = (e & f) ^ (~e & g);
    u64 t1 = h + S1 + ch + K[t] + w[t];
    u64 S0 = rotr(a, 28) ^ rotr(a, 34) ^ rotr(a, 39);
    u64 maj = (a & b) ^ (a & c) ^ (b & c);
    u64 t2 = S0 + maj;
    h = g;
    g = f;
    f = e;
    e = d + t1;
    d = c;
    c = b;
    b = a;
    a = t1 + t2;
  }
  s->h[0] += a;
  s->h[1] += b;
  s->h[2] += c;
  s->h[3] += d;
  s->h[4] += e;
  s->h[5] += f;
  s->h[6] += g;
  s->h[7] += h;
}

static void sha512_multi(const uint8_t* const* parts, const size_t* lens,
                         int nparts, uint8_t* out, size_t out_len) {
  State s;
  init(&s);
  uint8_t block[128];
  size_t total = 0;
  for (int p = 0; p < nparts; p++) total += lens[p];
  size_t fill = 0;
  for (int p = 0; p < nparts; p++) {
    const uint8_t* data = parts[p];
    size_t n = lens[p];
    while (n > 0) {
      size_t take = 128 - fill;
      if (take > n) take = n;
      memcpy(block + fill, data, take);
      fill += take;
      data += take;
      n -= take;
      if (fill == 128) {
        compress(&s, block);
        fill = 0;
      }
    }
  }
  // padding
  block[fill++] = 0x80;
  if (fill > 112) {
    memset(block + fill, 0, 128 - fill);
    compress(&s, block);
    fill = 0;
  }
  memset(block + fill, 0, 128 - fill);
  store64(block + 112, 0);  // length high (messages < 2^61 bytes)
  store64(block + 120, (u64)total * 8);
  compress(&s, block);
  uint8_t digest[64];
  for (int i = 0; i < 8; i++) store64(digest + 8 * i, s.h[i]);
  memcpy(out, digest, out_len);
}

static void sha512_one(const uint8_t* prefix, size_t prefix_len,
                       const uint8_t* msg, size_t len, uint8_t* out,
                       size_t out_len) {
  const uint8_t* parts[2] = {prefix, msg};
  size_t lens[2] = {prefix_len, len};
  sha512_multi(parts, lens, 2, out, out_len);
}

}  // namespace

extern "C" {

// three-part streaming hash (R || A || M for Ed25519 host prep)
void sha512_parts(const uint8_t* p1, size_t n1, const uint8_t* p2, size_t n2,
                  const uint8_t* p3, size_t n3, uint8_t* out,
                  size_t out_len) {
  const uint8_t* parts[3] = {p1, p2, p3};
  size_t lens[3] = {n1, n2, n3};
  sha512_multi(parts, lens, 3, out, out_len);
}

// Batched prefixed SHA-512-half: for each i, out[i] = first `out_len`
// bytes of SHA512(prefix_i ‖ msg_i). Prefixes are 4-byte big-endian
// values in `prefixes`; pass NULL for unprefixed hashing. A zero prefix
// IS hashed as four zero bytes — identical to the python/tpu backends,
// so the pluggable hashers stay bit-interchangeable.
void sha512h_batch(const uint8_t* data, const uint64_t* offsets,
                   const uint32_t* prefixes, uint8_t* out, uint64_t n,
                   uint64_t out_len) {
  for (uint64_t i = 0; i < n; i++) {
    uint8_t pfx[4];
    size_t pfx_len = 0;
    if (prefixes) {
      uint32_t p = prefixes[i];
      pfx[0] = (uint8_t)(p >> 24);
      pfx[1] = (uint8_t)(p >> 16);
      pfx[2] = (uint8_t)(p >> 8);
      pfx[3] = (uint8_t)p;
      pfx_len = 4;
    }
    sha512_one(pfx, pfx_len, data + offsets[i],
               (size_t)(offsets[i + 1] - offsets[i]), out + i * out_len,
               (size_t)out_len);
  }
}

}  // extern "C"
