// Native STObject serializer (CPython extension).
//
// The reference's Serializer/STObject::getSerializer are compiled C++
// (src/ripple_data/protocol/Serializer.cpp, SerializedObject.cpp:444);
// our protocol layer is Python, and the per-field encode loop was the
// largest app-level cost of the payment-flood apply path after the
// batched verifier went native. This module encodes the VALUE-LIKE
// field kinds in C (uints, hashes, VL, account, amount via a memoized
// wire attr) and calls back into Python for container kinds
// (object/array/pathset/vector256), which recurse per level — so a
// nested meta object still runs its flat per-level loops in C.
//
// Contract: byte-identical to stellard_tpu.protocol.stobject's Python
// loop (differential-tested across the protocol corpus). Field
// constants (wire header, kind, width, signing) are registered once at
// import keyed by a small per-field id (SField.cid), so the hot loop
// does ONE attribute fetch per field.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

#include <vector>

namespace {

// mirror of stellard_tpu.protocol.sfields K_* tags
enum Kind {
  K_UINT8 = 0,
  K_UINT16 = 1,
  K_UINT32 = 2,
  K_UINT64 = 3,
  K_HASH = 4,
  K_AMOUNT = 5,
  K_VL = 6,
  K_ACCOUNT = 7,
  K_OBJECT = 8,
  K_ARRAY = 9,
  K_PATHSET = 10,
  K_VECTOR256 = 11,
};

struct FieldConst {
  uint8_t header[4];
  uint8_t header_len;
  int8_t kind;
  uint8_t width;
  uint8_t signing;
  bool present;
};

static std::vector<FieldConst> g_fields;   // indexed by cid
static PyObject *g_container_cb = nullptr;  // Python fallback for containers
static PyObject *g_cid_name = nullptr;      // interned "cid"
static PyObject *g_wire_name = nullptr;     // interned "wire_bytes"
// interned SHAMap node attribute names (pack_nodes)
static PyObject *g_children_name = nullptr;
static PyObject *g_nhash_name = nullptr;  // "_hash"
static PyObject *g_item_name = nullptr;
static PyObject *g_ntype_name = nullptr;  // "type"
static PyObject *g_tag_name = nullptr;
static PyObject *g_data_name = nullptr;

struct Buf {
  std::vector<uint8_t> v;
  void put(const void *p, size_t n) {
    const uint8_t *b = static_cast<const uint8_t *>(p);
    v.insert(v.end(), b, b + n);
  }
  void put1(uint8_t b) { v.push_back(b); }
};

static void put_vl_len(Buf &out, size_t n) {
  // reference Serializer::addEncoded length prefix
  if (n <= 192) {
    out.put1(static_cast<uint8_t>(n));
  } else if (n <= 12480) {
    size_t k = n - 193;
    out.put1(static_cast<uint8_t>(193 + (k >> 8)));
    out.put1(static_cast<uint8_t>(k & 0xFF));
  } else {
    size_t k = n - 12481;
    out.put1(static_cast<uint8_t>(241 + (k >> 16)));
    out.put1(static_cast<uint8_t>((k >> 8) & 0xFF));
    out.put1(static_cast<uint8_t>(k & 0xFF));
  }
}

// -> 0 ok, -1 error (Python exception set)
static int encode_pair(Buf &out, PyObject *f, PyObject *v, int signing) {
  PyObject *cid_obj = PyObject_GetAttr(f, g_cid_name);
  if (cid_obj == nullptr) return -1;
  long cid = PyLong_AsLong(cid_obj);
  Py_DECREF(cid_obj);
  if (cid < 0 || static_cast<size_t>(cid) >= g_fields.size() ||
      !g_fields[cid].present) {
    PyErr_SetString(PyExc_ValueError, "unregistered field in stser");
    return -1;
  }
  const FieldConst &fc = g_fields[cid];
  if (signing && !fc.signing) return 0;  // omitted from signing form
  if (fc.kind < 0) {
    PyErr_SetString(PyExc_ValueError, "cannot serialize non-wire field");
    return -1;
  }
  out.put(fc.header, fc.header_len);

  switch (fc.kind) {
    case K_UINT8:
    case K_UINT16:
    case K_UINT32:
    case K_UINT64: {
      uint64_t x = PyLong_AsUnsignedLongLongMask(v);
      if (PyErr_Occurred()) return -1;
      for (int i = fc.width - 1; i >= 0; --i)
        out.put1(static_cast<uint8_t>((x >> (8 * i)) & 0xFF));
      return 0;
    }
    case K_HASH: {
      char *p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(v, &p, &n) < 0) return -1;
      if (n != fc.width) {
        PyErr_Format(PyExc_ValueError, "expected %d bytes, got %zd",
                     (int)fc.width, n);
        return -1;
      }
      out.put(p, n);
      return 0;
    }
    case K_VL: {
      char *p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(v, &p, &n) < 0) return -1;
      if (n > 918744) {
        PyErr_SetString(PyExc_ValueError, "VL too long");
        return -1;
      }
      put_vl_len(out, static_cast<size_t>(n));
      out.put(p, n);
      return 0;
    }
    case K_ACCOUNT: {
      char *p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(v, &p, &n) < 0) return -1;
      if (n != 20) {
        PyErr_SetString(PyExc_ValueError, "account field must be 20 bytes");
        return -1;
      }
      out.put1(20);
      out.put(p, 20);
      return 0;
    }
    case K_AMOUNT: {
      // STAmount.wire_bytes() memoizes its 8- or 48-byte encoding
      PyObject *w = PyObject_CallMethodNoArgs(v, g_wire_name);
      if (w == nullptr) return -1;
      char *p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(w, &p, &n) < 0) {
        Py_DECREF(w);
        return -1;
      }
      out.put(p, n);
      Py_DECREF(w);
      return 0;
    }
    default: {  // containers: Python encodes (recursing back into C)
      PyObject *chunk =
          PyObject_CallFunctionObjArgs(g_container_cb, f, v, nullptr);
      if (chunk == nullptr) return -1;
      char *p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(chunk, &p, &n) < 0) {
        Py_DECREF(chunk);
        return -1;
      }
      out.put(p, n);
      Py_DECREF(chunk);
      return 0;
    }
  }
}

static PyObject *stser_serialize(PyObject *, PyObject *args) {
  PyObject *pairs;
  int signing = 0;
  if (!PyArg_ParseTuple(args, "Oi", &pairs, &signing)) return nullptr;
  PyObject *seq = PySequence_Fast(pairs, "pairs must be a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  Buf out;
  out.v.reserve(64 + 32 * static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *pair = PySequence_Fast_GET_ITEM(seq, i);  // borrowed
    PyObject *f, *v;
    if (PyTuple_Check(pair) && PyTuple_GET_SIZE(pair) == 2) {
      f = PyTuple_GET_ITEM(pair, 0);
      v = PyTuple_GET_ITEM(pair, 1);
    } else {
      Py_DECREF(seq);
      PyErr_SetString(PyExc_TypeError, "pairs items must be 2-tuples");
      return nullptr;
    }
    if (encode_pair(out, f, v, signing) < 0) {
      Py_DECREF(seq);
      return nullptr;
    }
  }
  Py_DECREF(seq);
  return PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(out.v.data()),
      static_cast<Py_ssize_t>(out.v.size()));
}

static PyObject *stser_register_fields(PyObject *, PyObject *args) {
  // rows: list of (cid, header_bytes, kind, width, signing)
  PyObject *rows;
  PyObject *container_cb;
  if (!PyArg_ParseTuple(args, "OO", &rows, &container_cb)) return nullptr;
  PyObject *seq = PySequence_Fast(rows, "rows must be a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *row = PySequence_Fast_GET_ITEM(seq, i);
    long cid, kind, width, signing;
    const char *hdr;
    Py_ssize_t hdr_len;
    if (!PyArg_ParseTuple(row, "ly#lll", &cid, &hdr, &hdr_len, &kind, &width,
                          &signing)) {
      Py_DECREF(seq);
      return nullptr;
    }
    if (cid < 0 || cid > 1 << 20 || hdr_len > 4) {
      Py_DECREF(seq);
      PyErr_SetString(PyExc_ValueError, "bad field row");
      return nullptr;
    }
    if (static_cast<size_t>(cid) >= g_fields.size())
      g_fields.resize(cid + 1);
    FieldConst &fc = g_fields[cid];
    memcpy(fc.header, hdr, static_cast<size_t>(hdr_len));
    fc.header_len = static_cast<uint8_t>(hdr_len);
    fc.kind = static_cast<int8_t>(kind);
    fc.width = static_cast<uint8_t>(width);
    fc.signing = static_cast<uint8_t>(signing);
    fc.present = true;
  }
  Py_DECREF(seq);
  Py_XDECREF(g_container_cb);
  Py_INCREF(container_cb);
  g_container_cb = container_cb;
  Py_RETURN_NONE;
}

// ---------------------------------------------------------------------------
// bulk_merge: the SHAMap sorted-delta merge, in C. Applies a whole
// close's write set to the persistent radix tree in one DFS pass —
// Leaf objects arrive pre-built from Python and are only referenced;
// this code constructs the dirty INNER nodes (by calling the Inner
// class) and raises KeyError for deletes of missing keys, matching
// state.shamap._bulk_merge byte-for-byte (differential-tested). The
// canonical-tree property makes the result independent of application
// order, so parity with per-key set_item/del_item follows.

namespace {

struct MergeCtx {
  PyObject **keys;        // borrowed 32-byte key objects
  PyObject **leaves;      // borrowed Leaf | Py_None (= delete)
  const char **kbytes;    // raw key bytes
  std::vector<int> dels;  // delete-count prefix array
  PyObject *inner_cls;
  PyTypeObject *leaf_type;
  // out-of-core lazy trees (state/shamap.py Stub): node slots on the
  // op path may be hash-only stubs — resolved (faulted from the store
  // through the hot-node cache) by calling their .resolve() before
  // type dispatch. nullptr = eager tree, no checks.
  PyTypeObject *stub_type;
};

static inline int merge_nib(const char *k, int depth) {
  unsigned char b = static_cast<unsigned char>(k[depth >> 1]);
  return (depth & 1) ? (b & 0xF) : (b >> 4);
}

static void merge_key_error(PyObject *key) {
  PyObject *hx = PyObject_CallMethod(key, "hex", nullptr);
  if (hx != nullptr) {
    PyErr_SetObject(PyExc_KeyError, hx);
    Py_DECREF(hx);
  }
}

// children: 16 NEW references (Py_None for empty slots); consumed.
static PyObject *merge_make_inner(MergeCtx *c, PyObject **children) {
  PyObject *tup = PyTuple_New(16);
  if (tup == nullptr) {
    for (int i = 0; i < 16; i++) Py_XDECREF(children[i]);
    return nullptr;
  }
  for (int i = 0; i < 16; i++) PyTuple_SET_ITEM(tup, i, children[i]);
  PyObject *out = PyObject_CallFunctionObjArgs(c->inner_cls, tup, nullptr);
  Py_DECREF(tup);
  return out;
}

// Canonical subtree for set-only runs (kb/lv arrays, [lo,hi)); -> new ref.
static PyObject *merge_build(MergeCtx *c, const char **kb, PyObject **lv,
                             Py_ssize_t lo, Py_ssize_t hi, int depth) {
  if (hi - lo == 1) {
    Py_INCREF(lv[lo]);
    return lv[lo];
  }
  PyObject *children[16];
  for (int i = 0; i < 16; i++) {
    children[i] = Py_None;
    Py_INCREF(Py_None);
  }
  Py_ssize_t i = lo;
  while (i < hi) {
    int b = merge_nib(kb[i], depth);
    Py_ssize_t j = i + 1;
    while (j < hi && merge_nib(kb[j], depth) == b) j++;
    PyObject *sub = merge_build(c, kb, lv, i, j, depth + 1);
    if (sub == nullptr) {
      for (int k = 0; k < 16; k++) Py_XDECREF(children[k]);
      return nullptr;
    }
    Py_DECREF(children[b]);  // the Py_None placeholder
    children[b] = sub;
    i = j;
  }
  return merge_make_inner(c, children);
}

static PyObject *merge_node(MergeCtx *c, PyObject *node, Py_ssize_t lo,
                            Py_ssize_t hi, int depth);

// Merge ops[lo:hi) into `node` (borrowed; Py_None = empty subtree);
// -> NEW reference (Py_None when the subtree empties), nullptr on error.
static PyObject *merge_node_impl(MergeCtx *c, PyObject *node, Py_ssize_t lo,
                            Py_ssize_t hi, int depth) {
  if (lo >= hi) {
    Py_INCREF(node);
    return node;
  }
  if (node == Py_None) {
    if (c->dels[hi] != c->dels[lo]) {
      for (Py_ssize_t i = lo; i < hi; i++) {
        if (c->leaves[i] == Py_None) {
          merge_key_error(c->keys[i]);
          return nullptr;
        }
      }
    }
    return merge_build(c, c->kbytes, c->leaves, lo, hi, depth);
  }
  if (Py_TYPE(node) == c->leaf_type) {
    PyObject *item = PyObject_GetAttr(node, g_item_name);
    if (item == nullptr) return nullptr;
    PyObject *tag = PyObject_GetAttr(item, g_tag_name);
    Py_DECREF(item);
    if (tag == nullptr) return nullptr;
    char *tb;
    Py_ssize_t tlen;
    if (PyBytes_AsStringAndSize(tag, &tb, &tlen) < 0 || tlen != 32) {
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "bulk_merge: bad leaf tag");
      Py_DECREF(tag);
      return nullptr;
    }
    std::vector<const char *> mk;
    std::vector<PyObject *> ml;  // borrowed
    mk.reserve(hi - lo + 1);
    ml.reserve(hi - lo + 1);
    bool replaced = false, placed = false;
    for (Py_ssize_t i = lo; i < hi; i++) {
      const char *k = c->kbytes[i];
      int cmp = memcmp(tb, k, 32);
      if (!placed && !replaced && cmp < 0) {
        mk.push_back(tb);
        ml.push_back(node);
        placed = true;
      }
      if (cmp == 0) {
        replaced = true;
        if (c->leaves[i] != Py_None) {
          mk.push_back(k);
          ml.push_back(c->leaves[i]);
        }
      } else if (c->leaves[i] == Py_None) {
        merge_key_error(c->keys[i]);
        Py_DECREF(tag);
        return nullptr;
      } else {
        mk.push_back(k);
        ml.push_back(c->leaves[i]);
      }
    }
    if (!replaced && !placed) {
      mk.push_back(tb);
      ml.push_back(node);
    }
    PyObject *out;
    if (ml.empty()) {
      out = Py_None;
      Py_INCREF(out);
    } else if (ml.size() == 1) {
      out = ml[0];
      Py_INCREF(out);
    } else {
      out = merge_build(c, mk.data(), ml.data(), 0,
                        static_cast<Py_ssize_t>(ml.size()), depth);
    }
    Py_DECREF(tag);  // mk/ml borrowed tb/node through this point
    return out;
  }
  // inner node
  PyObject *ch = PyObject_GetAttr(node, g_children_name);
  if (ch == nullptr) return nullptr;
  if (!PyTuple_Check(ch) || PyTuple_GET_SIZE(ch) != 16) {
    PyErr_SetString(PyExc_ValueError, "bulk_merge: bad children tuple");
    Py_DECREF(ch);
    return nullptr;
  }
  PyObject *children[16];
  bool owned[16] = {false};
  for (int b = 0; b < 16; b++) children[b] = PyTuple_GET_ITEM(ch, b);
  Py_ssize_t i = lo;
  bool failed = false;
  while (i < hi) {
    int b = merge_nib(c->kbytes[i], depth);
    Py_ssize_t j = i + 1;
    while (j < hi && merge_nib(c->kbytes[j], depth) == b) j++;
    PyObject *sub = merge_node(c, children[b], i, j, depth + 1);
    if (sub == nullptr) {
      failed = true;
      break;
    }
    if (owned[b]) Py_DECREF(children[b]);
    children[b] = sub;
    owned[b] = true;
    i = j;
  }
  if (failed) {
    for (int b = 0; b < 16; b++)
      if (owned[b]) Py_DECREF(children[b]);
    Py_DECREF(ch);
    return nullptr;
  }
  PyObject *out = nullptr;
  if (c->dels[hi] != c->dels[lo]) {
    int live = 0;
    PyObject *only = nullptr;
    for (int b = 0; b < 16; b++) {
      if (children[b] != Py_None) {
        live++;
        only = children[b];
      }
    }
    if (live == 0) {
      out = Py_None;
      Py_INCREF(out);
    } else if (live == 1 && Py_TYPE(only) == c->leaf_type) {
      out = only;  // single-leaf fold-up (del_item parity)
      Py_INCREF(out);
    } else if (live == 1 && c->stub_type != nullptr &&
               Py_TYPE(only) == c->stub_type) {
      // the fold-up candidate is an unmaterialized stub: fault it to
      // learn whether it is a leaf (fold to the resolved node) or an
      // inner (keep the stub slot — subtree unchanged)
      PyObject *res = PyObject_CallMethod(only, "resolve", nullptr);
      if (res == nullptr) {
        for (int b = 0; b < 16; b++)
          if (owned[b]) Py_DECREF(children[b]);
        Py_DECREF(ch);
        return nullptr;
      }
      if (Py_TYPE(res) == c->leaf_type) {
        out = res;  // fold-up through the fault
      } else {
        Py_DECREF(res);
      }
    }
  }
  if (out == nullptr) {
    PyObject *tup = PyTuple_New(16);
    if (tup == nullptr) {
      for (int b = 0; b < 16; b++)
        if (owned[b]) Py_DECREF(children[b]);
      Py_DECREF(ch);
      return nullptr;
    }
    for (int b = 0; b < 16; b++) {
      if (!owned[b]) Py_INCREF(children[b]);
      PyTuple_SET_ITEM(tup, b, children[b]);  // steals
    }
    out = PyObject_CallFunctionObjArgs(c->inner_cls, tup, nullptr);
    Py_DECREF(tup);
  } else {
    for (int b = 0; b < 16; b++)
      if (owned[b]) Py_DECREF(children[b]);
  }
  Py_DECREF(ch);
  return out;
}

// dispatch shim: fault a stub on the op path (lazy trees) before the
// Leaf/Inner type dispatch in merge_node_impl; identity for everything
// else. The resolved node is only borrowed for the recursion — the new
// tree keeps either fresh dirty inners or the original stub slots.
static PyObject *merge_node(MergeCtx *c, PyObject *node, Py_ssize_t lo,
                            Py_ssize_t hi, int depth) {
  PyObject *resolved = nullptr;
  if (c->stub_type != nullptr && node != Py_None &&
      Py_TYPE(node) == c->stub_type) {
    resolved = PyObject_CallMethod(node, "resolve", nullptr);
    if (resolved == nullptr) return nullptr;
    node = resolved;
  }
  PyObject *out = merge_node_impl(c, node, lo, hi, depth);
  Py_XDECREF(resolved);
  return out;
}

}  // namespace

// bulk_merge(root, ops, leaf_cls, inner_cls[, stub_cls]) -> new root | None
// stub_cls (state.shamap.Stub) enables lazy trees: op-path stubs fault
// through their .resolve() before type dispatch (out-of-core plane).
static PyObject *stser_bulk_merge(PyObject *, PyObject *args) {
  PyObject *root, *ops, *leaf_cls, *inner_cls, *stub_cls = nullptr;
  if (!PyArg_ParseTuple(args, "OOOO|O", &root, &ops, &leaf_cls, &inner_cls,
                        &stub_cls))
    return nullptr;
  if (!PyType_Check(leaf_cls)) {
    PyErr_SetString(PyExc_TypeError, "bulk_merge: leaf_cls must be a type");
    return nullptr;
  }
  if (stub_cls != nullptr && stub_cls != Py_None && !PyType_Check(stub_cls)) {
    PyErr_SetString(PyExc_TypeError, "bulk_merge: stub_cls must be a type");
    return nullptr;
  }
  PyObject *seq = PySequence_Fast(ops, "bulk_merge expects a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (n == 0) {
    Py_DECREF(seq);
    Py_INCREF(root);
    return root;
  }
  MergeCtx c;
  std::vector<PyObject *> keys(n), leaves(n);
  std::vector<const char *> kbytes(n);
  c.dels.assign(n + 1, 0);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *pair = PySequence_Fast_GET_ITEM(seq, i);
    if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
      PyErr_SetString(PyExc_ValueError, "bulk_merge: ops must be pairs");
      Py_DECREF(seq);
      return nullptr;
    }
    keys[i] = PyTuple_GET_ITEM(pair, 0);
    leaves[i] = PyTuple_GET_ITEM(pair, 1);
    char *kb;
    Py_ssize_t klen;
    if (PyBytes_AsStringAndSize(keys[i], &kb, &klen) < 0 || klen != 32) {
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "bulk_merge: bad key length");
      Py_DECREF(seq);
      return nullptr;
    }
    kbytes[i] = kb;
    c.dels[i + 1] = c.dels[i] + (leaves[i] == Py_None ? 1 : 0);
  }
  c.keys = keys.data();
  c.leaves = leaves.data();
  c.kbytes = kbytes.data();
  c.inner_cls = inner_cls;
  c.leaf_type = reinterpret_cast<PyTypeObject *>(leaf_cls);
  c.stub_type = (stub_cls != nullptr && stub_cls != Py_None)
                    ? reinterpret_cast<PyTypeObject *>(stub_cls)
                    : nullptr;
  PyObject *out = merge_node(&c, root, 0, n, 0);
  Py_DECREF(seq);
  return out;
}

// ---------------------------------------------------------------------------
// pack_nodes: the SHAMap flat-buffer node encoder. Packs the
// prefix-format bytes of a list of Leaf/Inner nodes into ONE contiguous
// buffer (the exact bytes the hash plane digests AND the NodeStore
// persists) — replacing the per-node Python payload construction that
// dominated host seal prep. Byte-contract: identical to
// state.shamap._encode_nodes_py (differential-tested).

static PyObject *stser_pack_nodes(PyObject *, PyObject *args) {
  PyObject *nodes;
  unsigned long hp_inner, hp_txn, hp_txmd, hp_leaf;
  if (!PyArg_ParseTuple(args, "Okkkk", &nodes, &hp_inner, &hp_txn, &hp_txmd,
                        &hp_leaf))
    return nullptr;
  PyObject *seq = PySequence_Fast(nodes, "pack_nodes expects a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  PyObject *offsets = PyList_New(n + 1);
  if (offsets == nullptr) {
    Py_DECREF(seq);
    return nullptr;
  }
  std::vector<uint8_t> buf;
  buf.reserve(static_cast<size_t>(n) * 160);
  bool failed = false;
  {
    PyObject *zero = PyLong_FromLong(0);
    if (zero == nullptr) failed = true;
    else PyList_SET_ITEM(offsets, 0, zero);
  }
  auto put32be = [&buf](unsigned long v) {
    buf.push_back(static_cast<uint8_t>((v >> 24) & 0xFF));
    buf.push_back(static_cast<uint8_t>((v >> 16) & 0xFF));
    buf.push_back(static_cast<uint8_t>((v >> 8) & 0xFF));
    buf.push_back(static_cast<uint8_t>(v & 0xFF));
  };
  auto put_fixed = [&buf, &failed](PyObject *owner, PyObject *name,
                                   const char *what) {
    PyObject *b = PyObject_GetAttr(owner, name);
    if (b == nullptr) {
      failed = true;
      return;
    }
    char *p;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(b, &p, &len) < 0 || len != 32) {
      if (!PyErr_Occurred())
        PyErr_Format(PyExc_ValueError, "pack_nodes: bad %s length", what);
      else
        PyErr_Format(PyExc_ValueError, "pack_nodes: %s not bytes", what);
      Py_DECREF(b);
      failed = true;
      return;
    }
    buf.insert(buf.end(), p, p + 32);
    Py_DECREF(b);
  };
  for (Py_ssize_t i = 0; i < n && !failed; i++) {
    PyObject *node = PySequence_Fast_GET_ITEM(seq, i);  // borrowed
    PyObject *children = PyObject_GetAttr(node, g_children_name);
    if (children != nullptr) {
      // inner node: prefix + 16 child hashes (zero for empty branches)
      if (!PyTuple_Check(children) || PyTuple_GET_SIZE(children) != 16) {
        PyErr_SetString(PyExc_ValueError, "pack_nodes: bad children tuple");
        Py_DECREF(children);
        failed = true;
        break;
      }
      put32be(hp_inner);
      for (int b = 0; b < 16 && !failed; b++) {
        PyObject *child = PyTuple_GET_ITEM(children, b);  // borrowed
        if (child == Py_None) {
          buf.insert(buf.end(), 32, 0);
        } else {
          put_fixed(child, g_nhash_name, "child hash (unhashed child?)");
        }
      }
      Py_DECREF(children);
    } else {
      if (!PyErr_ExceptionMatches(PyExc_AttributeError)) {
        failed = true;
        break;
      }
      PyErr_Clear();
      // leaf node: prefix + data (+ tag for tagged leaf kinds)
      PyObject *type_obj = PyObject_GetAttr(node, g_ntype_name);
      if (type_obj == nullptr) {
        failed = true;
        break;
      }
      long t = PyLong_AsLong(type_obj);
      Py_DECREF(type_obj);
      if (PyErr_Occurred()) {
        failed = true;
        break;
      }
      unsigned long pfx;
      bool with_tag;
      if (t == 2) {  // TX_NM
        pfx = hp_txn;
        with_tag = false;
      } else if (t == 3) {  // TX_MD
        pfx = hp_txmd;
        with_tag = true;
      } else if (t == 4) {  // ACCOUNT_STATE
        pfx = hp_leaf;
        with_tag = true;
      } else {
        PyErr_Format(PyExc_ValueError, "pack_nodes: bad leaf type %ld", t);
        failed = true;
        break;
      }
      PyObject *item = PyObject_GetAttr(node, g_item_name);
      if (item == nullptr) {
        failed = true;
        break;
      }
      PyObject *data = PyObject_GetAttr(item, g_data_name);
      if (data == nullptr) {
        Py_DECREF(item);
        failed = true;
        break;
      }
      char *p;
      Py_ssize_t len;
      if (PyBytes_AsStringAndSize(data, &p, &len) < 0) {
        Py_DECREF(data);
        Py_DECREF(item);
        failed = true;
        break;
      }
      put32be(pfx);
      buf.insert(buf.end(), p, p + len);
      Py_DECREF(data);
      if (with_tag) put_fixed(item, g_tag_name, "leaf tag");
      Py_DECREF(item);
    }
    if (failed) break;
    PyObject *off = PyLong_FromSize_t(buf.size());
    if (off == nullptr) {
      failed = true;
      break;
    }
    PyList_SET_ITEM(offsets, i + 1, off);
  }
  Py_DECREF(seq);
  if (failed) {
    Py_DECREF(offsets);
    return nullptr;
  }
  PyObject *payload = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(buf.data()),
      static_cast<Py_ssize_t>(buf.size()));
  if (payload == nullptr) {
    Py_DECREF(offsets);
    return nullptr;
  }
  PyObject *out = PyTuple_New(2);
  if (out == nullptr) {
    Py_DECREF(payload);
    Py_DECREF(offsets);
    return nullptr;
  }
  PyTuple_SET_ITEM(out, 0, payload);
  PyTuple_SET_ITEM(out, 1, offsets);
  return out;
}

static PyObject *stser_parse(PyObject *, PyObject *);
static PyObject *stser_register_parse(PyObject *, PyObject *);

static PyMethodDef Methods[] = {
    {"serialize", stser_serialize, METH_VARARGS,
     "serialize(pairs, signing) -> bytes"},
    {"register_fields", stser_register_fields, METH_VARARGS,
     "register_fields(rows, container_cb)"},
    {"parse", stser_parse, METH_VARARGS,
     "parse(data, pos, inner) -> (STObject, new_pos)"},
    {"pack_nodes", stser_pack_nodes, METH_VARARGS,
     "pack_nodes(nodes, hp_inner, hp_txn, hp_txmd, hp_leaf)"
     " -> (buffer, offsets)"},
    {"bulk_merge", stser_bulk_merge, METH_VARARGS,
     "bulk_merge(root, sorted_ops, leaf_cls, inner_cls[, stub_cls])"
     " -> node | None"},
    {"register_parse", stser_register_parse, METH_VARARGS,
     "register_parse(rows, obj_factory, arr_factory, amount_cb, pathset_cb)"},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef Module = {
    PyModuleDef_HEAD_INIT, "_stser",
    "native STObject field-pair serializer", -1, Methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__stser(void) {
  g_cid_name = PyUnicode_InternFromString("cid");
  g_wire_name = PyUnicode_InternFromString("wire_bytes");
  g_children_name = PyUnicode_InternFromString("children");
  g_nhash_name = PyUnicode_InternFromString("_hash");
  g_item_name = PyUnicode_InternFromString("item");
  g_ntype_name = PyUnicode_InternFromString("type");
  g_tag_name = PyUnicode_InternFromString("tag");
  g_data_name = PyUnicode_InternFromString("data");
  if (g_cid_name == nullptr || g_wire_name == nullptr ||
      g_children_name == nullptr || g_nhash_name == nullptr ||
      g_item_name == nullptr || g_ntype_name == nullptr ||
      g_tag_name == nullptr || g_data_name == nullptr)
    return nullptr;
  PyObject *mod = PyModule_Create(&Module);
  if (mod == nullptr) return nullptr;
  // capability flag probed at bind time (state/shamap.py
  // _resolve_native): a stale prebuilt library without the bulk_merge
  // stub door simply lacks the attribute, so lazy trees take the
  // Python merge instead of discovering a TypeError on every close
  if (PyModule_AddIntConstant(mod, "BULK_MERGE_STUB_DOOR", 1) < 0) {
    Py_DECREF(mod);
    return nullptr;
  }
  return mod;
}

// ---------------------------------------------------------------------------
// Native binary parser: walks the canonical wire form and builds the
// fields dict in C. Consensus-sensitive value decoding (amounts,
// pathsets) goes through registered Python callbacks so validation
// lives in exactly one place; objects/arrays recurse here.

namespace {

struct ParseField {
  PyObject *field;  // owned ref to the SField singleton
  int8_t kind;
  uint8_t width;
};

static std::vector<ParseField> g_bycode;  // indexed by (type<<8)|value? no:
// codes are (type_id<<16)|value with type_id<256, value<256 — use a
// 65536-entry table indexed by (type_id<<8)|value.
static PyObject *g_obj_factory = nullptr;   // (fields_dict, in_order) -> STObject
static PyObject *g_arr_factory = nullptr;   // (items_list) -> STArray
static PyObject *g_amount_cb = nullptr;     // (bytes) -> STAmount
static PyObject *g_pathset_cb = nullptr;    // (bytes) -> STPathSet

struct Rd {
  const uint8_t *p;
  Py_ssize_t n;
  Py_ssize_t pos;
  bool need(Py_ssize_t k) {
    if (pos + k > n) {
      PyErr_SetString(PyExc_ValueError, "parser underflow");
      return false;
    }
    return true;
  }
};

// -> 0 ok / -1 error; (*t, *v) out
static int read_field_id(Rd &rd, int *t, int *v) {
  if (!rd.need(1)) return -1;
  int b1 = rd.p[rd.pos++];
  int type_id = b1 >> 4;
  int name = b1 & 0x0F;
  if (type_id == 0) {
    if (!rd.need(1)) return -1;
    type_id = rd.p[rd.pos++];
    if (type_id == 0 || type_id < 16) {
      PyErr_SetString(PyExc_ValueError, "invalid field id encoding");
      return -1;
    }
    if (name == 0) {
      if (!rd.need(1)) return -1;
      name = rd.p[rd.pos++];
      if (name == 0 || name < 16) {
        PyErr_SetString(PyExc_ValueError, "invalid field id encoding");
        return -1;
      }
    }
  } else if (name == 0) {
    if (!rd.need(1)) return -1;
    name = rd.p[rd.pos++];
    if (name == 0 || name < 16) {
      PyErr_SetString(PyExc_ValueError, "invalid field id encoding");
      return -1;
    }
  }
  *t = type_id;
  *v = name;
  return 0;
}

static int read_vl_len(Rd &rd, Py_ssize_t *out) {
  if (!rd.need(1)) return -1;
  int b1 = rd.p[rd.pos++];
  if (b1 <= 192) {
    *out = b1;
  } else if (b1 <= 240) {
    if (!rd.need(1)) return -1;
    int b2 = rd.p[rd.pos++];
    *out = 193 + ((b1 - 193) << 8) + b2;
  } else if (b1 <= 254) {
    if (!rd.need(2)) return -1;
    int b2 = rd.p[rd.pos++];
    int b3 = rd.p[rd.pos++];
    *out = 12481 + ((b1 - 241) << 16) + (b2 << 8) + b3;
  } else {
    PyErr_SetString(PyExc_ValueError, "invalid VL length byte");
    return -1;
  }
  return 0;
}

static PyObject *parse_object(Rd &rd, bool inner);  // fwd

// parse one value of `kind`; returns new ref or nullptr
static PyObject *parse_value(Rd &rd, const ParseField &fc) {
  switch (fc.kind) {
    case K_UINT8:
    case K_UINT16:
    case K_UINT32:
    case K_UINT64: {
      if (!rd.need(fc.width)) return nullptr;
      uint64_t x = 0;
      for (int i = 0; i < fc.width; ++i) x = (x << 8) | rd.p[rd.pos++];
      return PyLong_FromUnsignedLongLong(x);
    }
    case K_HASH: {
      if (!rd.need(fc.width)) return nullptr;
      PyObject *b = PyBytes_FromStringAndSize(
          reinterpret_cast<const char *>(rd.p + rd.pos), fc.width);
      rd.pos += fc.width;
      return b;
    }
    case K_VL: {
      Py_ssize_t len;
      if (read_vl_len(rd, &len) < 0 || !rd.need(len)) return nullptr;
      PyObject *b = PyBytes_FromStringAndSize(
          reinterpret_cast<const char *>(rd.p + rd.pos), len);
      rd.pos += len;
      return b;
    }
    case K_ACCOUNT: {
      Py_ssize_t len;
      if (read_vl_len(rd, &len) < 0 || !rd.need(len)) return nullptr;
      if (len != 20) {
        PyErr_SetString(PyExc_ValueError, "account field must be 20 bytes");
        return nullptr;
      }
      PyObject *b = PyBytes_FromStringAndSize(
          reinterpret_cast<const char *>(rd.p + rd.pos), 20);
      rd.pos += 20;
      return b;
    }
    case K_AMOUNT: {
      // 8 bytes native; 48 when the not-native bit (MSB) is set
      if (!rd.need(8)) return nullptr;
      Py_ssize_t len = (rd.p[rd.pos] & 0x80) ? 48 : 8;
      if (!rd.need(len)) return nullptr;
      PyObject *slice = PyBytes_FromStringAndSize(
          reinterpret_cast<const char *>(rd.p + rd.pos), len);
      if (slice == nullptr) return nullptr;
      PyObject *a = PyObject_CallFunctionObjArgs(g_amount_cb, slice, nullptr);
      Py_DECREF(slice);
      if (a != nullptr) rd.pos += len;
      return a;
    }
    case K_OBJECT:
      return parse_object(rd, true);
    case K_ARRAY: {
      PyObject *items = PyList_New(0);
      if (items == nullptr) return nullptr;
      for (;;) {
        int t, v;
        if (read_field_id(rd, &t, &v) < 0) {
          Py_DECREF(items);
          return nullptr;
        }
        if (t == 15 && v == 1) break;  // array end marker
        unsigned idx = (static_cast<unsigned>(t) << 8) | v;
        const ParseField *efc =
            (idx < g_bycode.size() && g_bycode[idx].field != nullptr)
                ? &g_bycode[idx]
                : nullptr;
        if (efc == nullptr || efc->kind != K_OBJECT) {
          Py_DECREF(items);
          PyErr_Format(PyExc_ValueError, "bad array element field (%d, %d)",
                       t, v);
          return nullptr;
        }
        PyObject *o = parse_object(rd, true);
        if (o == nullptr) {
          Py_DECREF(items);
          return nullptr;
        }
        PyObject *pair = PyTuple_Pack(2, efc->field, o);
        Py_DECREF(o);
        if (pair == nullptr || PyList_Append(items, pair) < 0) {
          Py_XDECREF(pair);
          Py_DECREF(items);
          return nullptr;
        }
        Py_DECREF(pair);
      }
      PyObject *arr =
          PyObject_CallFunctionObjArgs(g_arr_factory, items, nullptr);
      Py_DECREF(items);
      return arr;
    }
    case K_PATHSET: {
      // scan to the end marker (0x00) to slice the pathset region:
      // per element byte, skip 20 bytes per set bit of {0x01,0x10,0x20};
      // 0xFF is a path boundary
      Py_ssize_t start = rd.pos;
      for (;;) {
        if (!rd.need(1)) return nullptr;
        int k = rd.p[rd.pos++];
        if (k == 0x00) break;
        if (k == 0xFF) continue;
        Py_ssize_t skip = 0;
        if (k & 0x01) skip += 20;
        if (k & 0x10) skip += 20;
        if (k & 0x20) skip += 20;
        if (!rd.need(skip)) return nullptr;
        rd.pos += skip;
      }
      PyObject *slice = PyBytes_FromStringAndSize(
          reinterpret_cast<const char *>(rd.p + start), rd.pos - start);
      if (slice == nullptr) return nullptr;
      PyObject *ps = PyObject_CallFunctionObjArgs(g_pathset_cb, slice, nullptr);
      Py_DECREF(slice);
      return ps;
    }
    case K_VECTOR256: {
      Py_ssize_t len;
      if (read_vl_len(rd, &len) < 0 || !rd.need(len)) return nullptr;
      if (len % 32) {
        PyErr_SetString(PyExc_ValueError, "bad vector256 length");
        return nullptr;
      }
      PyObject *lst = PyList_New(len / 32);
      if (lst == nullptr) return nullptr;
      for (Py_ssize_t i = 0; i < len / 32; ++i) {
        PyObject *b = PyBytes_FromStringAndSize(
            reinterpret_cast<const char *>(rd.p + rd.pos + 32 * i), 32);
        if (b == nullptr) {
          Py_DECREF(lst);
          return nullptr;
        }
        PyList_SET_ITEM(lst, i, b);
      }
      rd.pos += len;
      return lst;
    }
    default:
      PyErr_SetString(PyExc_ValueError, "cannot deserialize field type");
      return nullptr;
  }
}

static PyObject *parse_object(Rd &rd, bool inner) {
  // a crafted deeply-nested blob must raise like the Python loop's
  // RecursionError, never overflow the C stack (peer blobs reach this
  // parser; an unguarded recursion was a remote-crash DoS)
  if (Py_EnterRecursiveCall(" in native STObject parse")) return nullptr;
  PyObject *result = nullptr;
  PyObject *fields = PyDict_New();
  if (fields == nullptr) return nullptr;
  bool in_order = true;
  long prev_key = -1;
  for (;;) {
    if (rd.pos >= rd.n) {
      if (inner) {
        Py_DECREF(fields);
        PyErr_SetString(PyExc_ValueError, "unterminated inner object");
        Py_LeaveRecursiveCall();
        return nullptr;
      }
      break;
    }
    int t, v;
    if (read_field_id(rd, &t, &v) < 0) {
      Py_DECREF(fields);
      Py_LeaveRecursiveCall();
      return nullptr;
    }
    if (inner && t == 14 && v == 1) break;  // object end marker
    unsigned idx = (static_cast<unsigned>(t) << 8) | v;
    const ParseField *fc =
        (idx < g_bycode.size() && g_bycode[idx].field != nullptr)
            ? &g_bycode[idx]
            : nullptr;
    if (fc == nullptr) {
      Py_DECREF(fields);
      PyErr_Format(PyExc_ValueError, "unknown field (%d, %d)", t, v);
      Py_LeaveRecursiveCall();
      return nullptr;
    }
    long key = (static_cast<long>(t) << 8) | v;  // == sort_key order
    if (in_order && prev_key >= 0 && key < prev_key) in_order = false;
    prev_key = key;
    PyObject *val = parse_value(rd, *fc);
    if (val == nullptr) {
      Py_DECREF(fields);
      Py_LeaveRecursiveCall();
      return nullptr;
    }
    int rc = PyDict_SetItem(fields, fc->field, val);
    Py_DECREF(val);
    if (rc < 0) {
      Py_DECREF(fields);
      Py_LeaveRecursiveCall();
      return nullptr;
    }
  }
  PyObject *flag = in_order ? Py_True : Py_False;
  result = PyObject_CallFunctionObjArgs(g_obj_factory, fields, flag, nullptr);
  Py_DECREF(fields);
  Py_LeaveRecursiveCall();
  return result;
}

static PyObject *stser_parse(PyObject *, PyObject *args) {
  Py_buffer buf;
  Py_ssize_t pos;
  int inner;
  if (!PyArg_ParseTuple(args, "y*ni", &buf, &pos, &inner)) return nullptr;
  Rd rd{static_cast<const uint8_t *>(buf.buf), buf.len, pos};
  if (pos < 0 || pos > buf.len) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "bad parse offset");
    return nullptr;
  }
  PyObject *obj = parse_object(rd, inner != 0);
  Py_ssize_t end = rd.pos;
  PyBuffer_Release(&buf);
  if (obj == nullptr) return nullptr;
  PyObject *out = Py_BuildValue("(Nn)", obj, end);
  return out;
}

static PyObject *stser_register_parse(PyObject *, PyObject *args) {
  // rows: list of (code, field_obj, kind, width); plus the factories
  PyObject *rows, *obj_factory, *arr_factory, *amount_cb, *pathset_cb;
  if (!PyArg_ParseTuple(args, "OOOOO", &rows, &obj_factory, &arr_factory,
                        &amount_cb, &pathset_cb))
    return nullptr;
  PyObject *seq = PySequence_Fast(rows, "rows must be a sequence");
  if (seq == nullptr) return nullptr;
  g_bycode.assign(1 << 16, ParseField{nullptr, -1, 0});
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *row = PySequence_Fast_GET_ITEM(seq, i);
    long code, kind, width;
    PyObject *field;
    if (!PyArg_ParseTuple(row, "lOll", &code, &field, &kind, &width)) {
      Py_DECREF(seq);
      return nullptr;
    }
    long t = code >> 16, v = code & 0xFFFF;
    if (t <= 0 || t >= 256 || v <= 0 || v >= 256) continue;  // non-wire
    unsigned idx = (static_cast<unsigned>(t) << 8) | static_cast<unsigned>(v);
    Py_INCREF(field);
    g_bycode[idx] = ParseField{field, static_cast<int8_t>(kind),
                               static_cast<uint8_t>(width)};
  }
  Py_DECREF(seq);
  auto keep = [](PyObject *&slot, PyObject *v) {
    Py_XDECREF(slot);
    Py_INCREF(v);
    slot = v;
  };
  keep(g_obj_factory, obj_factory);
  keep(g_arr_factory, arr_factory);
  keep(g_amount_cb, amount_cb);
  keep(g_pathset_cb, pathset_cb);
  Py_RETURN_NONE;
}

}  // namespace
