// Native STObject serializer (CPython extension).
//
// The reference's Serializer/STObject::getSerializer are compiled C++
// (src/ripple_data/protocol/Serializer.cpp, SerializedObject.cpp:444);
// our protocol layer is Python, and the per-field encode loop was the
// largest app-level cost of the payment-flood apply path after the
// batched verifier went native. This module encodes the VALUE-LIKE
// field kinds in C (uints, hashes, VL, account, amount via a memoized
// wire attr) and calls back into Python for container kinds
// (object/array/pathset/vector256), which recurse per level — so a
// nested meta object still runs its flat per-level loops in C.
//
// Contract: byte-identical to stellard_tpu.protocol.stobject's Python
// loop (differential-tested across the protocol corpus). Field
// constants (wire header, kind, width, signing) are registered once at
// import keyed by a small per-field id (SField.cid), so the hot loop
// does ONE attribute fetch per field.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

#include <vector>

namespace {

// mirror of stellard_tpu.protocol.sfields K_* tags
enum Kind {
  K_UINT8 = 0,
  K_UINT16 = 1,
  K_UINT32 = 2,
  K_UINT64 = 3,
  K_HASH = 4,
  K_AMOUNT = 5,
  K_VL = 6,
  K_ACCOUNT = 7,
  K_OBJECT = 8,
  K_ARRAY = 9,
  K_PATHSET = 10,
  K_VECTOR256 = 11,
};

struct FieldConst {
  uint8_t header[4];
  uint8_t header_len;
  int8_t kind;
  uint8_t width;
  uint8_t signing;
  bool present;
};

static std::vector<FieldConst> g_fields;   // indexed by cid
static PyObject *g_container_cb = nullptr;  // Python fallback for containers
static PyObject *g_cid_name = nullptr;      // interned "cid"
static PyObject *g_wire_name = nullptr;     // interned "wire_bytes"

struct Buf {
  std::vector<uint8_t> v;
  void put(const void *p, size_t n) {
    const uint8_t *b = static_cast<const uint8_t *>(p);
    v.insert(v.end(), b, b + n);
  }
  void put1(uint8_t b) { v.push_back(b); }
};

static void put_vl_len(Buf &out, size_t n) {
  // reference Serializer::addEncoded length prefix
  if (n <= 192) {
    out.put1(static_cast<uint8_t>(n));
  } else if (n <= 12480) {
    size_t k = n - 193;
    out.put1(static_cast<uint8_t>(193 + (k >> 8)));
    out.put1(static_cast<uint8_t>(k & 0xFF));
  } else {
    size_t k = n - 12481;
    out.put1(static_cast<uint8_t>(241 + (k >> 16)));
    out.put1(static_cast<uint8_t>((k >> 8) & 0xFF));
    out.put1(static_cast<uint8_t>(k & 0xFF));
  }
}

// -> 0 ok, -1 error (Python exception set)
static int encode_pair(Buf &out, PyObject *f, PyObject *v, int signing) {
  PyObject *cid_obj = PyObject_GetAttr(f, g_cid_name);
  if (cid_obj == nullptr) return -1;
  long cid = PyLong_AsLong(cid_obj);
  Py_DECREF(cid_obj);
  if (cid < 0 || static_cast<size_t>(cid) >= g_fields.size() ||
      !g_fields[cid].present) {
    PyErr_SetString(PyExc_ValueError, "unregistered field in stser");
    return -1;
  }
  const FieldConst &fc = g_fields[cid];
  if (signing && !fc.signing) return 0;  // omitted from signing form
  if (fc.kind < 0) {
    PyErr_SetString(PyExc_ValueError, "cannot serialize non-wire field");
    return -1;
  }
  out.put(fc.header, fc.header_len);

  switch (fc.kind) {
    case K_UINT8:
    case K_UINT16:
    case K_UINT32:
    case K_UINT64: {
      uint64_t x = PyLong_AsUnsignedLongLongMask(v);
      if (PyErr_Occurred()) return -1;
      for (int i = fc.width - 1; i >= 0; --i)
        out.put1(static_cast<uint8_t>((x >> (8 * i)) & 0xFF));
      return 0;
    }
    case K_HASH: {
      char *p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(v, &p, &n) < 0) return -1;
      if (n != fc.width) {
        PyErr_Format(PyExc_ValueError, "expected %d bytes, got %zd",
                     (int)fc.width, n);
        return -1;
      }
      out.put(p, n);
      return 0;
    }
    case K_VL: {
      char *p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(v, &p, &n) < 0) return -1;
      if (n > 918744) {
        PyErr_SetString(PyExc_ValueError, "VL too long");
        return -1;
      }
      put_vl_len(out, static_cast<size_t>(n));
      out.put(p, n);
      return 0;
    }
    case K_ACCOUNT: {
      char *p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(v, &p, &n) < 0) return -1;
      if (n != 20) {
        PyErr_SetString(PyExc_ValueError, "account field must be 20 bytes");
        return -1;
      }
      out.put1(20);
      out.put(p, 20);
      return 0;
    }
    case K_AMOUNT: {
      // STAmount.wire_bytes() memoizes its 8- or 48-byte encoding
      PyObject *w = PyObject_CallMethodNoArgs(v, g_wire_name);
      if (w == nullptr) return -1;
      char *p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(w, &p, &n) < 0) {
        Py_DECREF(w);
        return -1;
      }
      out.put(p, n);
      Py_DECREF(w);
      return 0;
    }
    default: {  // containers: Python encodes (recursing back into C)
      PyObject *chunk =
          PyObject_CallFunctionObjArgs(g_container_cb, f, v, nullptr);
      if (chunk == nullptr) return -1;
      char *p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(chunk, &p, &n) < 0) {
        Py_DECREF(chunk);
        return -1;
      }
      out.put(p, n);
      Py_DECREF(chunk);
      return 0;
    }
  }
}

static PyObject *stser_serialize(PyObject *, PyObject *args) {
  PyObject *pairs;
  int signing = 0;
  if (!PyArg_ParseTuple(args, "Oi", &pairs, &signing)) return nullptr;
  PyObject *seq = PySequence_Fast(pairs, "pairs must be a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  Buf out;
  out.v.reserve(64 + 32 * static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *pair = PySequence_Fast_GET_ITEM(seq, i);  // borrowed
    PyObject *f, *v;
    if (PyTuple_Check(pair) && PyTuple_GET_SIZE(pair) == 2) {
      f = PyTuple_GET_ITEM(pair, 0);
      v = PyTuple_GET_ITEM(pair, 1);
    } else {
      Py_DECREF(seq);
      PyErr_SetString(PyExc_TypeError, "pairs items must be 2-tuples");
      return nullptr;
    }
    if (encode_pair(out, f, v, signing) < 0) {
      Py_DECREF(seq);
      return nullptr;
    }
  }
  Py_DECREF(seq);
  return PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(out.v.data()),
      static_cast<Py_ssize_t>(out.v.size()));
}

static PyObject *stser_register_fields(PyObject *, PyObject *args) {
  // rows: list of (cid, header_bytes, kind, width, signing)
  PyObject *rows;
  PyObject *container_cb;
  if (!PyArg_ParseTuple(args, "OO", &rows, &container_cb)) return nullptr;
  PyObject *seq = PySequence_Fast(rows, "rows must be a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *row = PySequence_Fast_GET_ITEM(seq, i);
    long cid, kind, width, signing;
    const char *hdr;
    Py_ssize_t hdr_len;
    if (!PyArg_ParseTuple(row, "ly#lll", &cid, &hdr, &hdr_len, &kind, &width,
                          &signing)) {
      Py_DECREF(seq);
      return nullptr;
    }
    if (cid < 0 || cid > 1 << 20 || hdr_len > 4) {
      Py_DECREF(seq);
      PyErr_SetString(PyExc_ValueError, "bad field row");
      return nullptr;
    }
    if (static_cast<size_t>(cid) >= g_fields.size())
      g_fields.resize(cid + 1);
    FieldConst &fc = g_fields[cid];
    memcpy(fc.header, hdr, static_cast<size_t>(hdr_len));
    fc.header_len = static_cast<uint8_t>(hdr_len);
    fc.kind = static_cast<int8_t>(kind);
    fc.width = static_cast<uint8_t>(width);
    fc.signing = static_cast<uint8_t>(signing);
    fc.present = true;
  }
  Py_DECREF(seq);
  Py_XDECREF(g_container_cb);
  Py_INCREF(container_cb);
  g_container_cb = container_cb;
  Py_RETURN_NONE;
}

static PyMethodDef Methods[] = {
    {"serialize", stser_serialize, METH_VARARGS,
     "serialize(pairs, signing) -> bytes"},
    {"register_fields", stser_register_fields, METH_VARARGS,
     "register_fields(rows, container_cb)"},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef Module = {
    PyModuleDef_HEAD_INIT, "_stser",
    "native STObject field-pair serializer", -1, Methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__stser(void) {
  g_cid_name = PyUnicode_InternFromString("cid");
  g_wire_name = PyUnicode_InternFromString("wire_bytes");
  if (g_cid_name == nullptr || g_wire_name == nullptr) return nullptr;
  return PyModule_Create(&Module);
}
