"""stellard_tpu — a TPU-native replicated-ledger framework.

A ground-up reimplementation of the capabilities of hfeeki/stellard
(Stellar's original C++ ledger daemon, a rippled fork): a replicated
Merkle-radix ledger with Ed25519-signed transactions, UNL-quorum consensus,
pluggable content-addressed storage, P2P overlay, and JSON-RPC/WebSocket API.

Architecture (not a port):
- host-side protocol runtime: canonical serialization, SHAMap bookkeeping,
  transaction engine, consensus state machine, overlay, RPC
- device-side crypto/hash plane: batched Ed25519 verification and SHA-512
  tree hashing as JAX/Pallas kernels behind a pluggable backend registry
  (``signature_backend = cpu|tpu``), mirroring the NodeStore factory seam
  of the reference (/root/reference/src/ripple_core/nodestore/api/Factory.h).
"""

__version__ = "0.1.0"
