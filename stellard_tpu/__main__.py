"""CLI entry point: `python -m stellard_tpu [options]`.

Reference: src/ripple_app/main/Main.cpp:157-412 — server mode,
`--standalone`/`-a`, `--conf`, `--start` (fresh genesis), plus an RPC
client mode (`python -m stellard_tpu ping`, Main.cpp:400-405 RPCCall).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def main(argv: list[str] | None = None) -> int:
    from .utils.fatal import install as install_fatal_reporter

    install_fatal_reporter()
    ap = argparse.ArgumentParser(prog="stellard-tpu")
    ap.add_argument("--conf", default="", help="config file (INI sections)")
    ap.add_argument("-a", "--standalone", action="store_true",
                    help="no network; manual ledger closes")
    ap.add_argument("--start", action="store_true", help="fresh genesis")
    ap.add_argument("--rpc_ip", default=None)
    ap.add_argument("--rpc_port", type=int, default=None)
    ap.add_argument("--websocket_port", type=int, default=None)
    ap.add_argument("--dump_ledger", metavar="SEQ", type=int, default=None,
                    help="print stored ledger SEQ as JSON and exit")
    ap.add_argument("--dump_transactions", metavar="FILE", default=None,
                    help="stream stored txns to FILE as JSON lines and exit")
    ap.add_argument("--load_transactions", metavar="FILE", default=None,
                    help="re-drive a transaction dump through a fresh chain")
    ap.add_argument("--ledger", metavar="SEQ", type=int, default=None,
                    help="with --replay: the ledger to re-close")
    ap.add_argument("--import_db", metavar="TYPE[:PATH]", default=None,
                    help="migrate every node object from another NodeStore "
                         "backend into the configured one (reference: "
                         "--import, Application.cpp:320-323,1403)")
    ap.add_argument("--sustain", action="store_true",
                    help="supervisor mode: restart the server if it "
                         "crashes (reference: DoSustain, Main.cpp:261-275)")
    ap.add_argument("--replay", action="store_true",
                    help="replay stored ledger --ledger and verify its hash")
    ap.add_argument("--unittest", metavar="PATTERN", nargs="?", const="",
                    default=None,
                    help="run the test suite (optionally filtered by "
                         "PATTERN) and exit (reference: Main.cpp:293-301)")
    ap.add_argument("command", nargs="*", help="RPC client command")
    args = ap.parse_args(argv)

    if args.unittest is not None:
        # reference: `stellard --unittest [pattern]` runs the in-source
        # suites with a memory NodeStore; here the suite is pytest-driven
        # and pins the 8-device virtual CPU mesh itself (tests/conftest)
        import os
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if not os.path.isdir(os.path.join(repo, "tests")):
            print("--unittest: no tests/ beside the package (installed "
                  "copy?) — run pytest from a source checkout",
                  file=sys.stderr)
            return 1
        cmd = [sys.executable, "-m", "pytest", "tests/", "-q"]
        if args.unittest:
            cmd += ["-k", args.unittest]
        return subprocess.call(cmd, cwd=repo)

    from .node.config import Config

    if args.conf:
        with open(args.conf) as fh:
            cfg = Config.from_ini(fh.read())
    else:
        cfg = Config()
    if args.standalone:
        cfg.standalone = True
    if args.start:
        cfg.start_up = "fresh"
    if args.rpc_ip:
        cfg.rpc_ip = args.rpc_ip
    if args.rpc_port is not None:
        cfg.rpc_port = args.rpc_port
    if args.websocket_port is not None:
        cfg.websocket_port = args.websocket_port

    if args.command:
        # RPC client mode (reference: RPCCall::fromCommandLine)
        method, *rest = args.command
        params: dict = {}
        for arg in rest:
            if "=" in arg:
                k, v = arg.split("=", 1)
                params[k] = v
            else:
                params.setdefault("args", []).append(arg)
        scheme = "https" if cfg.rpc_secure else "http"
        url = f"{scheme}://{cfg.rpc_ip}:{cfg.rpc_port or 5005}/"
        body = json.dumps({"method": method, "params": [params]}).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        ssl_ctx = None
        if cfg.rpc_secure:
            # the server cert is a self-signed transport artifact
            # (reference RPCCall over [rpc_secure] likewise skips
            # verification for the loopback admin connection)
            import ssl as _ssl

            ssl_ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_CLIENT)
            ssl_ctx.check_hostname = False
            ssl_ctx.verify_mode = _ssl.CERT_NONE
        with urllib.request.urlopen(req, context=ssl_ctx) as resp:
            print(json.dumps(json.load(resp), indent=2))
        return 0

    if args.import_db:
        return _import_nodestore(args.import_db, cfg)

    if (
        args.dump_ledger is not None
        or args.dump_transactions
        or args.load_transactions
        or args.replay
    ):
        return _offline_tools(args, cfg)

    if args.sustain:
        return _sustain(argv)

    from .node.node import Node

    if cfg.rpc_port is None:
        cfg.rpc_port = 5005
    if cfg.websocket_port is None:
        cfg.websocket_port = 6006
    node = Node(cfg).setup().serve()
    rpc_scheme = "https" if cfg.rpc_secure else "http"
    ws_scheme = "wss" if cfg.websocket_secure else "ws"
    print(
        f"stellard-tpu: rpc {rpc_scheme}://{cfg.rpc_ip}:{node.http_server.port} "
        f"ws {ws_scheme}://{cfg.websocket_ip}:{node.ws_server.port} "
        f"(standalone={cfg.standalone}, "
        f"signature_backend={cfg.signature_backend})",
        file=sys.stderr,
    )
    # graceful SIGTERM (reference: signalStop wiring): the run loop exits
    # and the finally-teardown drains the ordered persist queue — a
    # supervisor's TERM must not drop ledgers the RPC already reported
    # committed
    import signal

    signal.signal(signal.SIGTERM, lambda _s, _f: node._running.clear())
    try:
        node.run()
    except KeyboardInterrupt:
        pass
    finally:
        node.stop()
    return 0


def _import_nodestore(spec: str, cfg) -> int:
    """Copy every object from another backend into the configured main
    store (reference: --import NodeStore migration)."""
    from .nodestore.core import make_database

    src_type, _, src_path = spec.partition(":")
    if cfg.node_db_type in ("memory", "null"):
        print("import: destination [node_db] is non-persistent "
              f"({cfg.node_db_type!r}) — configure a real backend",
              file=sys.stderr)
        return 1
    if src_type in ("sqlite", "cpplog") and not src_path:
        print(f"import: source {src_type!r} needs a path "
              "(TYPE:PATH)", file=sys.stderr)
        return 1
    source = make_database(
        type=src_type, **({"path": src_path} if src_path else {}),
        async_writes=False,
    )
    dest = make_database(
        type=cfg.node_db_type,
        **({"path": cfg.node_db_path} if cfg.node_db_path else {}),
        async_writes=False,
    )
    n = 0
    chunk = []
    for obj in source.backend.iterate():
        chunk.append(obj)
        n += 1
        if len(chunk) >= 4096:
            dest.backend.store_batch(chunk)  # one commit per chunk
            chunk = []
    if chunk:
        dest.backend.store_batch(chunk)
    dest.close()
    source.close()
    print(f"imported {n} node objects from {spec} "
          f"into {cfg.node_db_type}", file=sys.stderr)
    return 0


def _sustain(argv: list[str] | None) -> int:
    """Supervisor loop: re-exec the server child until it exits cleanly
    (reference: DoSustain — the parent process restarts a crashed child).
    """
    import subprocess
    import time as _time

    child_args = [a for a in (argv if argv is not None else sys.argv[1:])
                  if a != "--sustain"]
    cmd = [sys.executable, "-m", "stellard_tpu"] + child_args
    restarts = 0
    while True:
        rc = subprocess.call(cmd)
        if rc == 0:
            return 0
        restarts += 1
        print(f"sustain: child exited rc={rc}; restart #{restarts}",
              file=sys.stderr)
        _time.sleep(min(30, restarts))


def _offline_tools(args, cfg) -> int:
    """Offline modes (reference: LedgerDump.cpp entry points)."""
    from .node.ledgertools import (
        dump_ledger,
        dump_transactions,
        load_transactions,
        replay_ledger,
    )
    from .node.txdb import TxDatabase
    from .nodestore.core import make_database
    from .state.ledger import Ledger

    db = make_database(
        type=cfg.node_db_type,
        **({"path": cfg.node_db_path} if cfg.node_db_path else {}),
    )
    txdb = TxDatabase(cfg.database_path or ":memory:")

    def ledger_by_seq(seq: int) -> Ledger:
        hdr = txdb.get_ledger_header(seq=seq)
        if hdr is None:
            raise SystemExit(f"no stored ledger {seq}")
        return Ledger.load(db, hdr["hash"])

    if args.dump_ledger is not None:
        print(json.dumps(dump_ledger(ledger_by_seq(args.dump_ledger)), indent=2))
        return 0
    if args.dump_transactions:
        seqs = [s for s in txdb.ledger_seqs() if s >= 2]
        gaps = [
            (a, b) for a, b in zip(seqs, seqs[1:]) if b != a + 1
        ]
        for a, b in gaps:
            print(f"warning: ledger gap {a} → {b} (catch-up switch?)",
                  file=sys.stderr)

        def ledgers():
            for seq in seqs:
                hdr = txdb.get_ledger_header(seq=seq)
                if hdr is not None:
                    yield Ledger.load(db, hdr["hash"])

        with open(args.dump_transactions, "w") as fh:
            n = dump_transactions(ledgers(), fh)
        print(f"dumped {n} transactions from {len(seqs)} ledgers",
              file=sys.stderr)
        return 0
    if args.load_transactions:
        from .node.ledgermaster import LedgerMaster
        from .node.node import MASTER_PASSPHRASE
        from .protocol.keys import KeyPair

        lm = LedgerMaster()
        lm.start_new_ledger(
            KeyPair.from_passphrase(MASTER_PASSPHRASE).account_id
        )
        with open(args.load_transactions) as fh:
            applied, failed = load_transactions(fh, lm)
        print(f"applied {applied}, failed {failed}", file=sys.stderr)
        return 0
    if args.replay:
        if args.ledger is None:
            raise SystemExit("--replay requires --ledger SEQ")
        hdr = txdb.get_ledger_header(seq=args.ledger)
        if hdr is None:
            raise SystemExit(f"no stored ledger {args.ledger}")
        # replay through the CONFIGURED hash/signature backends — this is
        # the BASELINE #5 harness, so it must measure the device pipeline
        # (batched re-verification is the catch-up trust model)
        from .crypto.backend import make_hasher
        from .node.verifyplane import VerifyPlane

        hasher = make_hasher(
            cfg.hash_backend,
            **({"mesh": cfg.hash_mesh} if cfg.hash_backend == "tpu" else {}),
        )
        plane = VerifyPlane(backend=cfg.signature_backend, window_ms=1.0,
                            backend_opts=cfg.verify_backend_opts())
        stats = replay_ledger(db, hdr["hash"], hash_batch=hasher,
                              verify_many=plane.verify_many)
        # routing evidence: without this, latency-aware routing could
        # verify everything on the CPU while the harness claims a
        # device-pipeline measurement
        pj = plane.get_json()
        stats["device_share"] = pj.get("device_share", 0.0)
        stats["device_sigs"] = pj.get("device_sigs", 0)
        plane.stop()
        print(json.dumps(stats, indent=2))
        return 0 if stats["ok"] else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
