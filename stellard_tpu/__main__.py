"""CLI entry point: `python -m stellard_tpu [options]`.

Reference: src/ripple_app/main/Main.cpp:157-412 — server mode,
`--standalone`/`-a`, `--conf`, `--start` (fresh genesis), plus an RPC
client mode (`python -m stellard_tpu ping`, Main.cpp:400-405 RPCCall).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="stellard-tpu")
    ap.add_argument("--conf", default="", help="config file (INI sections)")
    ap.add_argument("-a", "--standalone", action="store_true",
                    help="no network; manual ledger closes")
    ap.add_argument("--start", action="store_true", help="fresh genesis")
    ap.add_argument("--rpc_ip", default=None)
    ap.add_argument("--rpc_port", type=int, default=None)
    ap.add_argument("--websocket_port", type=int, default=None)
    ap.add_argument("command", nargs="*", help="RPC client command")
    args = ap.parse_args(argv)

    from .node.config import Config

    if args.conf:
        with open(args.conf) as fh:
            cfg = Config.from_ini(fh.read())
    else:
        cfg = Config()
    if args.standalone:
        cfg.standalone = True
    if args.start:
        cfg.start_up = "fresh"
    if args.rpc_ip:
        cfg.rpc_ip = args.rpc_ip
    if args.rpc_port is not None:
        cfg.rpc_port = args.rpc_port
    if args.websocket_port is not None:
        cfg.websocket_port = args.websocket_port

    if args.command:
        # RPC client mode (reference: RPCCall::fromCommandLine)
        method, *rest = args.command
        params: dict = {}
        for arg in rest:
            if "=" in arg:
                k, v = arg.split("=", 1)
                params[k] = v
            else:
                params.setdefault("args", []).append(arg)
        url = f"http://{cfg.rpc_ip}:{cfg.rpc_port or 5005}/"
        body = json.dumps({"method": method, "params": [params]}).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req) as resp:
            print(json.dumps(json.load(resp), indent=2))
        return 0

    from .node.node import Node

    if cfg.rpc_port is None:
        cfg.rpc_port = 5005
    if cfg.websocket_port is None:
        cfg.websocket_port = 6006
    node = Node(cfg).setup().serve()
    print(
        f"stellard-tpu: rpc http://{cfg.rpc_ip}:{node.http_server.port} "
        f"ws ws://{cfg.websocket_ip}:{node.ws_server.port} "
        f"(standalone={cfg.standalone}, "
        f"signature_backend={cfg.signature_backend})",
        file=sys.stderr,
    )
    try:
        node.run()
    except KeyboardInterrupt:
        pass
    finally:
        node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
