"""Consensus plane: timing rules, validations, proposals, disputed-tx
voting, and the per-round LedgerConsensus state machine.

Reference: src/ripple_app/consensus/ (LedgerConsensus.cpp, DisputedTx.cpp),
src/ripple_app/ledger/{LedgerTiming,SerializedValidation,LedgerProposal},
src/ripple_app/misc/Validations.cpp.
"""

from .consensus import ConsensusAdapter, ConsensusState, LedgerConsensus
from .disputed import DisputedTx
from .proposal import LedgerProposal
from .timing import (
    have_consensus,
    next_close_resolution,
    should_close,
)
from .txset import TxSet
from .validation import STValidation
from .validations import ValidationsStore

__all__ = [
    "ConsensusAdapter",
    "ConsensusState",
    "DisputedTx",
    "LedgerConsensus",
    "LedgerProposal",
    "STValidation",
    "TxSet",
    "ValidationsStore",
    "have_consensus",
    "next_close_resolution",
    "should_close",
]
