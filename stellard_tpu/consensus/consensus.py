"""LedgerConsensus: one consensus round, driven by a periodic timer.

Reference: src/ripple_app/consensus/LedgerConsensus.cpp — states
(:36-47), timerEntry (:589), statePreClose (:637), stateEstablish
(:713), closeLedger/takeInitialPosition (:1761-1813), peerPosition,
updateOurPositions, accept (:931-1127).

TPU shape: the round's signature work — every peer proposal and every
round of validations — is handed to the VerifyPlane as whole batches
(`verify_many`), one device program per burst, instead of the
reference's one-job-per-signature libsodium calls. Tx-set hashing rides
the same level-batched BatchHasher as the ledger SHAMaps.

The round talks to the outside world only through a `ConsensusAdapter`,
so the deterministic in-process simnet (overlay.simnet) and the real
TCP overlay drive identical logic.
"""

from __future__ import annotations

import time as _time
from enum import IntEnum
from typing import Callable, Optional

from ..node.ledgermaster import LedgerMaster
from ..protocol.keys import KeyPair
from ..state.ledger import Ledger
from .disputed import DisputedTx
from .proposal import LedgerProposal
from .timing import (
    AV_CT_CONSENSUS_PCT,
    LEDGER_IDLE_INTERVAL,
    LEDGER_MIN_CONSENSUS_MS,
    have_consensus,
    next_close_resolution,
    should_close,
)

# keep our proposal fresh / drop stale peer positions, in seconds
# (reference: PROPOSE_INTERVAL / PROPOSE_FRESHNESS, LedgerTiming.h:64-67)
PROPOSE_INTERVAL = 12
PROPOSE_FRESHNESS = 20
from .txset import TxSet
from .validation import STValidation
from .validations import ValidationsStore

__all__ = ["LedgerConsensus", "ConsensusAdapter", "ConsensusState"]


class ConsensusState(IntEnum):
    """reference: LedgerConsensus.cpp:36-47"""

    PRE_CLOSE = 0  # open ledger accumulating txns
    ESTABLISH = 1  # we closed; exchanging positions
    FINISHED = 2  # consensus reached; accept scheduled
    ACCEPTED = 3  # new LCL built and validated


class ConsensusAdapter:
    """Round I/O seam. The simnet and the TCP overlay both implement
    this; LedgerConsensus never touches a socket."""

    def propose(self, proposal: LedgerProposal) -> None:
        raise NotImplementedError

    def share_tx_set(self, txset: TxSet) -> None:
        raise NotImplementedError

    def acquire_tx_set(self, set_hash: bytes) -> Optional[TxSet]:
        """Return the set if already known; else start acquisition and
        deliver later via LedgerConsensus.have_tx_set."""
        raise NotImplementedError

    def send_validation(self, val: STValidation) -> None:
        raise NotImplementedError

    def relay_disputed_tx(self, blob: bytes) -> None:
        """Flood a disputed tx so peers missing it can include it next
        round (reference: DisputedTx creation relays TMTransaction)."""

    def request_ledger_data(self, msg) -> None:
        """Send a GetLedger request toward peers (catch-up acquisition;
        reference: PeerSet::sendRequest)."""

    def on_accepted(self, ledger: Ledger, round_ms: int) -> None:
        """New LCL built; the node should start the next round."""


class LedgerConsensus:
    def __init__(
        self,
        prev_ledger: Ledger,
        ledger_master: LedgerMaster,
        adapter: ConsensusAdapter,
        validations: ValidationsStore,
        key: KeyPair,
        unl: set[bytes],
        network_time: Callable[[], int],
        clock: Callable[[], float] = _time.monotonic,
        prev_proposers: int = 0,
        prev_round_ms: int = LEDGER_MIN_CONSENSUS_MS,
        proposing: bool = True,
        hash_batch: Optional[Callable] = None,
        idle_interval: int = LEDGER_IDLE_INTERVAL,
        voting=None,
        note_byzantine: Optional[Callable] = None,
    ):
        self.lm = ledger_master
        # consensus round events ride the chain's tracing plane (trace
        # id = the ledger under construction)
        self.tracer = ledger_master.tracer
        self.adapter = adapter
        self.validations = validations
        self.key = key
        self.unl = unl  # trusted node public keys (not including us)
        self.network_time = network_time
        self.clock = clock
        self.proposing = proposing
        self.hash_batch = hash_batch
        self.idle_interval = idle_interval
        self.voting = voting  # consensus.voting.VotingBox or None
        # defense sink (ValidatorNode.note_byzantine): recognized hostile
        # proposals are counted, never silently dropped
        self.note_byzantine = note_byzantine or (lambda kind, **kw: None)

        self.prev_ledger = prev_ledger
        self.prev_hash = prev_ledger.hash()
        self.seq = prev_ledger.seq + 1
        self.prev_proposers = prev_proposers
        self.prev_round_ms = max(prev_round_ms, LEDGER_MIN_CONSENSUS_MS)

        # close-time resolution for the ledger being built (reference:
        # getNextLedgerTimeResolution; close_flags bit 0 = no agreement)
        self.resolution = next_close_resolution(
            prev_ledger.close_resolution,
            (prev_ledger.close_flags & 1) == 0,
            self.seq,
        )

        self.state = ConsensusState.PRE_CLOSE
        self.round_start = self.clock()
        self.consensus_start: Optional[float] = None

        self.peer_positions: dict[bytes, LedgerProposal] = {}
        self.position_times: dict[bytes, float] = {}  # peer -> recv clock
        # highest propose_seq ever seen per peer — survives bow-outs and
        # staleness prunes so a replayed old proposal can't re-register a
        # departed proposer
        self.max_seen_seq: dict[bytes, int] = {}
        # (peer, propose_seq) -> (tx_set_hash, close_time): detects a key
        # SIGNING two different positions at one sequence (equivocation)
        # vs a mere duplicate relay of the same position
        self._seen_positions: dict[tuple[bytes, int], tuple[bytes, int]] = {}
        self.last_propose: Optional[float] = None
        self.acquired: dict[bytes, TxSet] = {}
        self.disputes: dict[bytes, DisputedTx] = {}
        self.compared: set[bytes] = set()  # set hashes diffed vs ours
        self.our_position: Optional[LedgerProposal] = None
        self.our_set: Optional[TxSet] = None
        self._pre_close_open_ids: set[bytes] = set()
        self.our_close_time = 0
        self.round_ms = 0  # set on accept

    # -- timer ------------------------------------------------------------

    def timer_entry(self) -> None:
        """reference: LedgerConsensus::timerEntry (:589)"""
        if self.state == ConsensusState.PRE_CLOSE:
            self._state_pre_close()
        elif self.state == ConsensusState.ESTABLISH:
            self._state_establish()

    def _ms_since(self, t0: Optional[float]) -> int:
        return int((self.clock() - (t0 if t0 is not None else 0)) * 1000)

    # -- PRE_CLOSE --------------------------------------------------------

    def _state_pre_close(self) -> None:
        open_ledger = self.lm.current_ledger()
        any_tx = any(True for _ in open_ledger.tx_entries())
        proposers_closed = len(self.peer_positions)
        open_ms = self._ms_since(self.round_start)
        if should_close(
            any_tx,
            max(self.prev_proposers, proposers_closed + 1),
            proposers_closed,
            open_ms,  # since our round began == since prev close
            open_ms,
            self.idle_interval,
        ):
            self.close_ledger()

    def close_ledger(self) -> None:
        """Take our initial position (reference: closeLedger +
        takeInitialPosition :1761-1813)."""
        open_ledger = self.lm.current_ledger()
        self.our_set = TxSet(self.hash_batch)
        for txid, blob, _meta in open_ledger.tx_entries():
            self.our_set.add(txid, blob)
        if self.voting is not None:
            # flag-ledger voting: amendment/fee pseudo-txs join our initial
            # position (reference: takeInitialPosition → doVoting,
            # LedgerConsensus.cpp:1033-1038). Votes are tallied over the
            # validations of the flag ledger's parent, which every honest
            # node has seen, so positions agree.
            parent_vals = self.validations.validations_for(
                self.prev_ledger.parent_hash
            )
            for ptx in self.voting.position_injections(
                self.prev_ledger, parent_vals
            ):
                self.our_set.add(ptx.txid(), ptx.serialize())
        # remembered for accept(): these are re-applied (when left out) by
        # close_with_txset, so the dispute-reapply loop must skip them
        self._pre_close_open_ids = self.our_set.txids()
        self.our_close_time = Ledger.round_close_time(
            self.network_time(), self.resolution
        )
        self.our_position = LedgerProposal(
            self.prev_hash, 0, self.our_set.hash(), self.our_close_time
        )
        if self.proposing:
            self.our_position.sign(self.key)
            self.adapter.propose(self.our_position)
            self.tracer.instant(
                "consensus.propose_out", "consensus", seq=self.seq,
                propose_seq=0, txs=len(self._pre_close_open_ids),
            )
        self.adapter.share_tx_set(self.our_set)
        self.acquired[self.our_set.hash()] = self.our_set
        self.state = ConsensusState.ESTABLISH
        self.tracer.instant(
            "consensus.state", "consensus", seq=self.seq,
            state="ESTABLISH", open_ms=self._ms_since(self.round_start),
        )
        self.consensus_start = self.clock()
        self.last_propose = self.clock()
        # fold in positions that arrived before we closed
        for prop in list(self.peer_positions.values()):
            ts = self.acquired.get(prop.tx_set_hash)
            if ts is None:
                ts = self.adapter.acquire_tx_set(prop.tx_set_hash)
                if ts is not None:
                    self.acquired[prop.tx_set_hash] = ts
            if ts is not None:
                self._compare_set(ts)

    # -- peer input -------------------------------------------------------

    def peer_proposal(self, prop: LedgerProposal) -> bool:
        """A signature-checked proposal from a trusted peer. Returns True
        if it changed our view (and should be relayed)."""
        if prop.prev_ledger != self.prev_hash:
            return False  # different LCL — not our round
        peer = prop.node_public
        if peer not in self.unl or peer == self.key.public:
            return False
        if prop.is_bowout():
            self.peer_positions.pop(peer, None)
            self.max_seen_seq[peer] = prop.propose_seq  # nothing tops this
            for d in self.disputes.values():
                d.unvote(peer)
            self.tracer.instant(
                "consensus.proposal_in", "consensus", seq=self.seq,
                peer=peer.hex()[:16], bowout=True,
            )
            return True
        position = (prop.tx_set_hash, prop.close_time)
        if prop.propose_seq <= self.max_seen_seq.get(peer, -1):
            # stale or replayed. Distinguish a harmless duplicate relay
            # from EQUIVOCATION — the same key signing a DIFFERENT
            # position at a sequence it already used. Either way the
            # first-seen position stands and quorum math never counts a
            # proposer twice (peer_positions is keyed by peer).
            prev = self._seen_positions.get((peer, prop.propose_seq))
            if prev is not None and prev != position:
                self.note_byzantine("conflicting_proposal", peer=peer,
                                    propose_seq=prop.propose_seq)
            else:
                self.note_byzantine("duplicate_proposal", peer=peer,
                                    propose_seq=prop.propose_seq)
            return False
        self._seen_positions[(peer, prop.propose_seq)] = position
        self.max_seen_seq[peer] = prop.propose_seq
        self.peer_positions[peer] = prop
        self.position_times[peer] = self.clock()
        self.tracer.instant(
            "consensus.proposal_in", "consensus", seq=self.seq,
            peer=peer.hex()[:16], propose_seq=prop.propose_seq,
        )
        ts = self.acquired.get(prop.tx_set_hash)
        if ts is None:
            ts = self.adapter.acquire_tx_set(prop.tx_set_hash)
            if ts is not None:
                self.have_tx_set(prop.tx_set_hash, ts)
        if ts is not None:
            self._update_peer_votes(peer, ts)
        return True

    def have_tx_set(self, set_hash: bytes, txset: TxSet) -> None:
        """An acquired peer tx set arrived (reference: mapComplete)."""
        self.acquired[set_hash] = txset
        if self.our_set is not None:
            self._compare_set(txset)

    def _compare_set(self, txset: TxSet) -> None:
        h = txset.hash()
        if h in self.compared or self.our_set is None:
            return
        self.compared.add(h)
        # new disputes from the symmetric difference with our set
        # (reference: createDisputes via SHAMap::compare). SORTED:
        # differences() is a Python set, and iterating it raw leaks the
        # process's string-hash seed into dispute creation and relay
        # ORDER — which reorders wire messages and thus peers' apply
        # order, breaking cross-process reproducibility of a seeded
        # simnet run (found by the scenario smoke's determinism gate)
        for txid in sorted(self.our_set.differences(txset)):
            if txid not in self.disputes:
                blob = self.our_set.get(txid) or txset.get(txid) or b""
                self.disputes[txid] = DisputedTx(
                    txid, blob, our_vote=txid in self.our_set
                )
                if blob:
                    self.adapter.relay_disputed_tx(blob)
        # (re)vote every peer whose position references a known set
        for peer, prop in self.peer_positions.items():
            ts = self.acquired.get(prop.tx_set_hash)
            if ts is not None:
                self._update_peer_votes(peer, ts)

    def _update_peer_votes(self, peer: bytes, txset: TxSet) -> None:
        for d in self.disputes.values():
            d.set_vote(peer, d.txid in txset)

    # -- ESTABLISH --------------------------------------------------------

    def _time_pct(self) -> int:
        return (self._ms_since(self.consensus_start) * 100) // self.prev_round_ms

    def _effective_close_time(self) -> tuple[int, bool]:
        """Close-time consensus: the most-voted rounded close time among
        current proposers (incl. us); agreement requires
        AV_CT_CONSENSUS_PCT percent (reference: updateOurPositions
        close-time buckets)."""
        votes: dict[int, int] = {self.our_close_time: 1}
        for prop in self.peer_positions.values():
            ct = Ledger.round_close_time(prop.close_time, self.resolution)
            votes[ct] = votes.get(ct, 0) + 1
        total = 1 + len(self.peer_positions)
        best_ct, best_n = max(votes.items(), key=lambda kv: (kv[1], kv[0]))
        if best_n * 100 >= AV_CT_CONSENSUS_PCT * total:
            return best_ct, True
        return self.our_close_time, False

    def _state_establish(self) -> None:
        """reference: stateEstablish (:713) → updateOurPositions +
        haveConsensus check."""
        if self._ms_since(self.consensus_start) < LEDGER_MIN_CONSENSUS_MS:
            return  # participation window: collect positions before judging
        self._prune_stale_positions()
        self._update_our_position()
        self._keep_proposal_fresh()
        ct, ct_agree = self._effective_close_time()
        agree = 0
        our_hash = self.our_position.tx_set_hash
        for prop in self.peer_positions.values():
            if prop.tx_set_hash == our_hash:
                agree += 1
        target = max(self.prev_proposers, len(self.peer_positions) + 1)
        if have_consensus(
            target,
            len(self.peer_positions),
            agree,
            self._ms_since(self.consensus_start),
            self.prev_round_ms,
        ):
            self.state = ConsensusState.FINISHED
            self.tracer.instant(
                "consensus.state", "consensus", seq=self.seq,
                state="FINISHED", proposers=len(self.peer_positions),
                agree=agree,
                establish_ms=self._ms_since(self.consensus_start),
            )
            self.accept(ct, ct_agree)

    def _prune_stale_positions(self) -> None:
        """Drop peer positions older than PROPOSE_FRESHNESS so a vanished
        (partitioned/crashed) proposer stops counting toward agreement
        (reference: peerPosition staleness via PROPOSE_FRESHNESS)."""
        now = self.clock()
        for peer in [
            p
            for p, t in self.position_times.items()
            if now - t > PROPOSE_FRESHNESS
        ]:
            self.peer_positions.pop(peer, None)
            self.position_times.pop(peer, None)
            for d in self.disputes.values():
                d.unvote(peer)

    def _keep_proposal_fresh(self) -> None:
        """Re-broadcast (with a bumped position number) every
        PROPOSE_INTERVAL so late-joining or re-connected peers learn our
        position — without this a healed partition can never rejoin a
        stuck round (reference: PROPOSE_INTERVAL forced re-propose)."""
        if not self.proposing or self.our_position is None:
            return
        if (
            self.last_propose is not None
            and self.clock() - self.last_propose < PROPOSE_INTERVAL
        ):
            return
        self.our_position = self.our_position.advanced(
            self.our_position.tx_set_hash, self.our_close_time
        )
        self.our_position.sign(self.key)
        self.adapter.propose(self.our_position)
        if self.our_set is not None:
            self.adapter.share_tx_set(self.our_set)
        self.last_propose = self.clock()

    def _update_our_position(self) -> None:
        """Avalanche vote switching; on any change, advance and re-propose
        (reference: updateOurPositions)."""
        if self.our_set is None:
            return
        time_pct = self._time_pct()
        changed = False
        for d in self.disputes.values():
            if d.update_vote(time_pct, self.proposing):
                changed = True
        ct, _agree = self._effective_close_time()
        if ct != self.our_close_time:
            self.our_close_time = ct
            changed = True
        if changed:
            new_set = self.our_set.copy()
            for d in self.disputes.values():
                if d.our_vote and d.txid not in new_set and d.blob:
                    new_set.add(d.txid, d.blob)
                elif not d.our_vote and d.txid in new_set:
                    new_set.remove(d.txid)
            self.our_set = new_set
            self.acquired[new_set.hash()] = new_set
            self.our_position = self.our_position.advanced(
                new_set.hash(), self.our_close_time
            )
            # avalanche vote switch: our position moved (disputed-tx
            # votes crossed a threshold and/or the close time converged)
            self.tracer.instant(
                "consensus.position_change", "consensus", seq=self.seq,
                propose_seq=self.our_position.propose_seq,
                disputes=len(self.disputes), time_pct=time_pct,
            )
            if self.proposing:
                self.our_position.sign(self.key)
                self.adapter.propose(self.our_position)
                self.last_propose = self.clock()
                self.tracer.instant(
                    "consensus.propose_out", "consensus", seq=self.seq,
                    propose_seq=self.our_position.propose_seq,
                )
            self.adapter.share_tx_set(new_set)
            self._compare_set(new_set)

    # -- accept -----------------------------------------------------------

    def accept(self, close_time: int, ct_agree: bool) -> None:
        """Build the new LCL from the agreed set, sign and broadcast our
        validation (reference: accept :931-1127)."""
        consensus_set = self.acquired.get(
            self.our_position.tx_set_hash if self.our_position else b"",
            self.our_set,
        )
        txs = consensus_set.transactions() if consensus_set else []
        new_lcl, _results = self.lm.close_with_txset(
            txs, close_time, self.resolution, correct_close_time=ct_agree
        )
        # per-tx apply results ride on the ledger for the persistence
        # plane (txdb records real TER tokens, not a blanket tesSUCCESS)
        new_lcl.apply_results = _results
        self.round_ms = self._ms_since(self.consensus_start)

        # disputed txns that lost get another shot in the new open ledger
        # (reference: accept reapply :1050-1127). Skip those that were in
        # our own open ledger — close_with_txset already re-applied them —
        # and never skip signature checking: dispute blobs can come from a
        # peer's tx set, which is only root-hash-verified in transit.
        from ..engine.engine import TxParams
        from ..protocol.sttx import SerializedTransaction
        from ..protocol.ter import TER

        skip = {tx.txid() for tx in txs} | self._pre_close_open_ids
        for d in self.disputes.values():
            if d.txid not in skip and d.blob:
                tx = SerializedTransaction.from_bytes(d.blob)
                ok, _why = tx.passes_local_checks()
                if not ok or not tx.check_sign():
                    continue
                ter, _ = self.lm.do_transaction(
                    tx, TxParams.OPEN_LEDGER | TxParams.RETRY
                )
                if ter == TER.terPRE_SEQ:
                    self.lm.add_held_transaction(tx)

        if self.voting is not None:
            self.voting.on_ledger_closed(new_lcl)
        if self.proposing and self.validations.can_sign(new_lcl.seq):
            # can_sign: never a second validation at a seq we already
            # voted (fork repair abstains; see ValidationsStore)
            extra = (
                self.voting.validation_fields(new_lcl)
                if self.voting is not None
                else {}
            )
            val = STValidation.build(
                ledger_hash=new_lcl.hash(),
                signing_time=self.network_time(),
                full=True,
                ledger_seq=new_lcl.seq,
                **extra,
            )
            val.sign(self.key)
            # count our own validation toward quorum (reference: accept
            # stores its own validation before broadcasting :1023-1045)
            self.validations.add(val, local=True)
            self.adapter.send_validation(val)
            self.tracer.instant(
                "consensus.validation_out", "consensus", seq=new_lcl.seq,
            )
        self.lm.check_accept(
            new_lcl.hash(), self.validations.trusted_count_for(new_lcl.hash())
        )
        self.state = ConsensusState.ACCEPTED
        self.tracer.instant(
            "consensus.state", "consensus", seq=self.seq,
            state="ACCEPTED", round_ms=self.round_ms,
        )
        self.adapter.on_accepted(new_lcl, self.round_ms)

    # -- introspection ----------------------------------------------------

    def get_json(self) -> dict:
        return {
            "state": self.state.name,
            "ledger_seq": self.seq,
            "prev_ledger": self.prev_hash.hex(),
            "proposers": len(self.peer_positions),
            "disputes": len(self.disputes),
            "our_position": (
                self.our_position.tx_set_hash.hex()
                if self.our_position
                else None
            ),
            "close_resolution": self.resolution,
        }
