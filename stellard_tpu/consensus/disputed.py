"""DisputedTx: a transaction that is in some proposers' candidate sets
but not others'; tracks votes and runs the avalanche vote-switching rule.

Reference: src/ripple_app/consensus/DisputedTx.{h,cpp}.
"""

from __future__ import annotations

from .timing import avalanche_threshold

__all__ = ["DisputedTx"]


class DisputedTx:
    def __init__(self, txid: bytes, blob: bytes, our_vote: bool):
        self.txid = txid
        self.blob = blob
        self.our_vote = our_vote
        self.votes: dict[bytes, bool] = {}  # peer node key -> yes/no

    def set_vote(self, peer: bytes, yes: bool) -> None:
        self.votes[peer] = yes

    def unvote(self, peer: bytes) -> None:
        self.votes.pop(peer, None)

    @property
    def yays(self) -> int:
        return sum(1 for v in self.votes.values() if v)

    @property
    def nays(self) -> int:
        return sum(1 for v in self.votes.values() if not v)

    def update_vote(self, time_pct: int, proposing: bool) -> bool:
        """Re-evaluate our vote given round progress; returns True when our
        vote flips (→ we must advance our position)
        (reference: DisputedTx::updateVote — our current vote is weighted
        in with the peers', then compared to the escalating threshold)."""
        if self.our_vote and self.nays == 0:
            new_vote = True  # unanimous agreement with us: keep
        elif not self.our_vote and self.yays == 0:
            new_vote = False  # nobody disagrees with our NO: keep
        elif proposing:
            weight = (self.yays * 100 + (100 if self.our_vote else 0)) // (
                self.yays + self.nays + 1
            )
            new_vote = weight > avalanche_threshold(time_pct)
        else:
            # not proposing: just adopt the majority
            new_vote = self.yays > self.nays
        changed = new_vote != self.our_vote
        self.our_vote = new_vote
        return changed

    def __repr__(self):
        return (
            f"DisputedTx({self.txid.hex()[:8]} our={self.our_vote} "
            f"+{self.yays}/-{self.nays})"
        )
