"""LedgerProposal: a validator's signed consensus position — "building on
ledger P, my proposed tx set is T, close time C, position number N".

Reference: src/ripple_app/ledger/LedgerProposal.{h,cpp} — signing hash is
the PRP-prefixed hash over (proposeSeq, closeTime, previousLedger,
txSetHash); checkSign at LedgerProposal.h:48.
"""

from __future__ import annotations

from typing import Optional

from ..protocol.keys import KeyPair, verify_signature
from ..protocol.serializer import Serializer
from ..utils.hashes import HP_PROPOSAL, prefix_hash

__all__ = ["LedgerProposal", "BOWOUT_SEQ"]

# a proposer that leaves the round broadcasts this sequence
# (reference: LedgerProposal::seqLeave)
BOWOUT_SEQ = 0xFFFFFFFF


class LedgerProposal:
    def __init__(
        self,
        prev_ledger: bytes,
        propose_seq: int,
        tx_set_hash: bytes,
        close_time: int,
        node_public: bytes = b"",
        signature: bytes = b"",
    ):
        self.prev_ledger = prev_ledger
        self.propose_seq = propose_seq
        self.tx_set_hash = tx_set_hash
        self.close_time = close_time
        self.node_public = node_public
        self.signature = signature
        self._sig_good: Optional[bool] = None

    # -- hashing / signing ------------------------------------------------

    def signing_payload(self) -> bytes:
        s = Serializer()
        s.add32(self.propose_seq)
        s.add32(self.close_time)
        s.add_raw(self.prev_ledger)
        s.add_raw(self.tx_set_hash)
        return s.data()

    def signing_hash(self) -> bytes:
        return prefix_hash(HP_PROPOSAL, self.signing_payload())

    def sign(self, key: KeyPair) -> None:
        self.node_public = key.public
        self.signature = key.sign(self.signing_hash())
        self._sig_good = None

    def check_sign(self) -> bool:
        if self._sig_good is None:
            self._sig_good = verify_signature(
                self.node_public, self.signing_hash(), self.signature
            )
        return self._sig_good

    def set_sig_verdict(self, good: bool) -> None:
        self._sig_good = good

    # -- position updates -------------------------------------------------

    def is_bowout(self) -> bool:
        return self.propose_seq == BOWOUT_SEQ

    def advanced(self, tx_set_hash: bytes, close_time: int) -> "LedgerProposal":
        """Our next position in the same round (reference:
        LedgerProposal::changePosition)."""
        return LedgerProposal(
            self.prev_ledger, self.propose_seq + 1, tx_set_hash, close_time
        )

    def bowout(self) -> "LedgerProposal":
        return LedgerProposal(
            self.prev_ledger, BOWOUT_SEQ, self.tx_set_hash, self.close_time
        )

    def suppression_id(self) -> bytes:
        """Relay dedup key: hash over position *and* signer
        (reference: proposal suppression in NetworkOPs::processProposal)."""
        s = Serializer()
        s.add_raw(self.signing_payload())
        s.add_vl(self.node_public)
        s.add_vl(self.signature)
        return prefix_hash(HP_PROPOSAL, s.data())

    def __repr__(self):
        return (
            f"LedgerProposal(prev={self.prev_ledger.hex()[:8]} "
            f"seq={self.propose_seq} set={self.tx_set_hash.hex()[:8]} "
            f"ct={self.close_time})"
        )
