"""Consensus timing rules.

Reference: src/ripple_app/ledger/LedgerTiming.{h,cpp}. The constants are
protocol-level — every validator must make the same close/agree decisions
from the same inputs or the network splits, so they are reproduced
exactly (LedgerTiming.h:26-84, LedgerTiming.cpp:29-165).

All durations here are plain ints: seconds for intervals/resolutions,
milliseconds where the name says `_ms`.
"""

from __future__ import annotations

__all__ = [
    "LEDGER_IDLE_INTERVAL",
    "LEDGER_VAL_INTERVAL",
    "LEDGER_EARLY_INTERVAL",
    "LEDGER_MIN_CONSENSUS_MS",
    "LEDGER_MIN_CLOSE_MS",
    "LEDGER_GRANULARITY_MS",
    "LEDGER_TIME_ACCURACY",
    "CLOSE_RESOLUTIONS",
    "AV_CT_CONSENSUS_PCT",
    "should_close",
    "have_consensus",
    "next_close_resolution",
    "avalanche_threshold",
]

# ledger may sit idle this many seconds before an (empty) close
LEDGER_IDLE_INTERVAL = 15
# a validation stays "current" this long past its signing time
LEDGER_VAL_INTERVAL = 300
# tolerate validations timestamped up to this far in the future
LEDGER_EARLY_INTERVAL = 180
# minimum consensus participation window (ms)
LEDGER_MIN_CONSENSUS_MS = 3000
# minimum open time before a close may be proposed (ms)
LEDGER_MIN_CLOSE_MS = 2000
# cadence of the consensus timer (ms)
LEDGER_GRANULARITY_MS = 1000
# initial close-time resolution (seconds)
LEDGER_TIME_ACCURACY = 30
# resolution is re-examined on these ledger-seq strides
LEDGER_RES_INCREASE = 8
LEDGER_RES_DECREASE = 1

# close-time resolution ladder (seconds); first/last repeated so the
# increase/decrease walk can never run off the end
# (reference: LedgerTimeResolution[], LedgerTiming.cpp:29)
CLOSE_RESOLUTIONS = (10, 10, 20, 30, 60, 90, 120, 120)

# avalanche vote-switching schedule: once `time_pct` (percent of the
# previous round's duration) has elapsed, a disputed tx needs `vote_pct`
# percent of proposers voting yes for us to vote yes
# (reference: AV_* in LedgerTiming.h:70-84)
AV_INIT_CONSENSUS_PCT = 50
AV_MID_CONSENSUS_TIME = 50
AV_MID_CONSENSUS_PCT = 65
AV_LATE_CONSENSUS_TIME = 85
AV_LATE_CONSENSUS_PCT = 70
AV_STUCK_CONSENSUS_TIME = 200
AV_STUCK_CONSENSUS_PCT = 95

# percent of proposers that must agree on a (rounded) close time
AV_CT_CONSENSUS_PCT = 75

# percent agreement (including ourselves) that locks in consensus
CONSENSUS_PCT = 80
# percent of target proposers already closed that forces our close
CLOSE_PROPOSERS_PCT = 75


def should_close(
    any_transactions: bool,
    target_proposers: int,
    proposers_closed: int,
    since_last_close_ms: int,
    open_ms: int,
    idle_interval: int = LEDGER_IDLE_INTERVAL,
) -> bool:
    """Decide whether the open ledger should close now
    (reference: ContinuousLedgerTiming::shouldClose, LedgerTiming.cpp:34-91).

    `target_proposers` is how many proposers we expect this round
    (last round's count); `proposers_closed` is how many have already
    proposed a close for this ledger.
    """
    if target_proposers > 0 and (
        proposers_closed * 100
    ) // target_proposers >= CLOSE_PROPOSERS_PCT:
        return True  # most of the network has closed already — follow
    if open_ms <= LEDGER_MIN_CLOSE_MS:
        return False  # give submitters a minimum window
    if not any_transactions:
        return since_last_close_ms >= idle_interval * 1000
    return True


def have_consensus(
    target_proposers: int,
    current_proposers: int,
    current_agree: int,
    since_consensus_ms: int = 10**9,
    prev_round_ms: int = 0,
) -> bool:
    """Decide whether our position has won
    (reference: ContinuousLedgerTiming::haveConsensus,
    LedgerTiming.cpp:95-141). `current_agree` counts proposers whose
    position matches ours; we count ourselves on top.

    When fewer than 3/4 of last round's proposers are present we only
    *slow down* (wait one previous-round-time plus the minimum window, as
    the reference does) — a hard wait would deadlock the network forever
    after a validator crash, since the straggler count never recovers
    until a round completes.
    """
    # truncating division exactly as the reference: for 3 proposers the
    # bar is 2, so a healthy small net (2 of 3 peers present) does NOT
    # slow down — only a real shortfall does
    if current_proposers < (target_proposers * 3) // 4 and (
        since_consensus_ms < prev_round_ms + LEDGER_MIN_CONSENSUS_MS
    ):
        return False  # give stragglers one extra round-time to appear
    in_consensus = (current_agree * 100 + 100) // (current_proposers + 1)
    return in_consensus >= CONSENSUS_PCT


def next_close_resolution(
    previous_resolution: int, previous_agree: bool, ledger_seq: int
) -> int:
    """Adapt close-time resolution: tighten while the network agrees on
    close times, loosen when it doesn't
    (reference: getNextLedgerTimeResolution, LedgerTiming.cpp:144-165).
    """
    assert ledger_seq > 0
    i = CLOSE_RESOLUTIONS.index(previous_resolution, 1)
    if not previous_agree and ledger_seq % LEDGER_RES_DECREASE == 0:
        return CLOSE_RESOLUTIONS[i + 1]  # coarser
    if previous_agree and ledger_seq % LEDGER_RES_INCREASE == 0:
        return CLOSE_RESOLUTIONS[i - 1]  # finer
    return previous_resolution


def avalanche_threshold(time_pct: int) -> int:
    """Required yes-percentage for a disputed tx given round progress
    (percent of the previous round's converge time)
    (reference: DisputedTx::updateVote, DisputedTx.cpp)."""
    if time_pct < AV_MID_CONSENSUS_TIME:
        return AV_INIT_CONSENSUS_PCT
    if time_pct < AV_LATE_CONSENSUS_TIME:
        return AV_MID_CONSENSUS_PCT
    if time_pct < AV_STUCK_CONSENSUS_TIME:
        return AV_LATE_CONSENSUS_PCT
    return AV_STUCK_CONSENSUS_PCT
