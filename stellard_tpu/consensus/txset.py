"""TxSet: a consensus candidate transaction set.

The reference represents a position's tx set as a SHAMap of raw tx blobs
keyed by txid (LedgerConsensus's mAcquired/mOurPosition maps); the set's
identity is the map's root hash, which is what proposals carry. We reuse
the SHAMap so the set hash is computed by the same level-batched
BatchHasher pipeline as the ledger trees.
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional

from ..protocol.sttx import SerializedTransaction
from ..state.shamap import SHAMap, SHAMapItem, TNType

__all__ = ["TxSet", "MAX_TXSET_BLOBS"]

# defense cap on a relayed candidate set: a byzantine peer must not buy
# unbounded parse/hash work with one TxSetData message. Generous — the
# 4x-overload bench closes ~3k-tx ledgers; an honest set stays far under.
MAX_TXSET_BLOBS = 8192


class TxSet:
    def __init__(self, hash_batch: Optional[Callable] = None):
        if hash_batch is not None:
            self._map = SHAMap(leaf_type=TNType.TX_NM, hash_batch=hash_batch)
        else:
            self._map = SHAMap(leaf_type=TNType.TX_NM)
        self._txs: dict[bytes, bytes] = {}  # txid -> blob

    @classmethod
    def from_transactions(
        cls,
        txs: list[SerializedTransaction],
        hash_batch: Optional[Callable] = None,
    ) -> "TxSet":
        s = cls(hash_batch)
        for tx in txs:
            s.add(tx.txid(), tx.serialize())
        return s

    def add(self, txid: bytes, blob: bytes) -> None:
        self._txs[txid] = blob
        self._map.set_item(SHAMapItem(txid, blob))

    def remove(self, txid: bytes) -> None:
        if txid in self._txs:
            del self._txs[txid]
            self._map.del_item(txid)

    def __contains__(self, txid: bytes) -> bool:
        return txid in self._txs

    def __len__(self) -> int:
        return len(self._txs)

    def get(self, txid: bytes) -> Optional[bytes]:
        return self._txs.get(txid)

    def txids(self) -> set[bytes]:
        return set(self._txs)

    def blobs(self) -> Iterator[tuple[bytes, bytes]]:
        return iter(sorted(self._txs.items()))

    def hash(self) -> bytes:
        return self._map.get_hash()

    def copy(self) -> "TxSet":
        c = TxSet(self._map.hash_batch)
        for txid, blob in self._txs.items():
            c.add(txid, blob)
        return c

    def differences(self, other: "TxSet") -> set[bytes]:
        """Txids present in exactly one of the two sets — each becomes a
        DisputedTx (reference: LedgerConsensus::createDisputes via
        SHAMap::compare)."""
        return self.txids() ^ other.txids()

    def transactions(self) -> list[SerializedTransaction]:
        return [
            SerializedTransaction.from_bytes(blob)
            for _txid, blob in self.blobs()
        ]

    def __repr__(self):
        return f"TxSet(n={len(self)} hash={self.hash().hex()[:8]})"
