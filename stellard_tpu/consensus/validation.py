"""STValidation: a validator's signed statement that it built/accepted a
specific ledger.

Reference: src/ripple_app/ledger/SerializedValidation.{h,cpp} — format
(:134-160), getSigningHash with the VAL prefix (:70-73), sign (:54-68),
isValid Ed25519 verify (:90-108, north-star hot call #2).
"""

from __future__ import annotations

from typing import Optional

from ..protocol.keys import KeyPair, verify_signature
from ..protocol.sfields import (
    sfAmendments,
    sfBaseFee,
    sfFlags,
    sfLedgerHash,
    sfLedgerSequence,
    sfLoadFee,
    sfReserveBase,
    sfReserveIncrement,
    sfSignature,
    sfSigningPubKey,
    sfSigningTime,
)
from ..protocol.stobject import STObject
from ..utils.hashes import HP_VALIDATION, prefix_hash

__all__ = ["STValidation", "VF_FULL"]

# flag: this is a full validation (the signer built the ledger through
# consensus), not a partial/catch-up one (reference:
# SerializedValidation.h kFullFlag)
VF_FULL = 0x0001


class STValidation:
    def __init__(self, obj: STObject):
        self.obj = obj
        self._sig_good: Optional[bool] = None
        # set by the receiver, not the wire: did a trusted UNL key sign it
        self.trusted = False

    @classmethod
    def build(
        cls,
        ledger_hash: bytes,
        signing_time: int,
        full: bool = True,
        ledger_seq: Optional[int] = None,
        load_fee: Optional[int] = None,
        base_fee: Optional[int] = None,
        reserve_base: Optional[int] = None,
        reserve_increment: Optional[int] = None,
        amendments: Optional[list[bytes]] = None,
    ) -> "STValidation":
        obj = STObject()
        obj[sfFlags] = VF_FULL if full else 0
        obj[sfLedgerHash] = ledger_hash
        obj[sfSigningTime] = signing_time
        if ledger_seq is not None:
            obj[sfLedgerSequence] = ledger_seq
        if load_fee is not None:
            obj[sfLoadFee] = load_fee
        if base_fee is not None:
            obj[sfBaseFee] = base_fee
        if reserve_base is not None:
            obj[sfReserveBase] = reserve_base
        if reserve_increment is not None:
            obj[sfReserveIncrement] = reserve_increment
        if amendments:
            obj[sfAmendments] = list(amendments)
        return cls(obj)

    @classmethod
    def from_bytes(cls, blob: bytes) -> "STValidation":
        return cls(STObject.from_bytes(blob))

    # -- accessors --------------------------------------------------------

    @property
    def ledger_hash(self) -> bytes:
        return self.obj[sfLedgerHash]

    @property
    def ledger_seq(self) -> Optional[int]:
        return self.obj.get(sfLedgerSequence)

    @property
    def signing_time(self) -> int:
        return self.obj[sfSigningTime]

    @property
    def flags(self) -> int:
        return self.obj.get(sfFlags, 0)

    @property
    def is_full(self) -> bool:
        return bool(self.flags & VF_FULL)

    @property
    def load_fee(self) -> Optional[int]:
        return self.obj.get(sfLoadFee)

    @property
    def base_fee(self) -> Optional[int]:
        return self.obj.get(sfBaseFee)

    @property
    def reserve_base(self) -> Optional[int]:
        return self.obj.get(sfReserveBase)

    @property
    def reserve_increment(self) -> Optional[int]:
        return self.obj.get(sfReserveIncrement)

    @property
    def amendments(self) -> Optional[list[bytes]]:
        return self.obj.get(sfAmendments)

    @property
    def signer(self) -> bytes:
        """The validator's node public key (raw Ed25519)."""
        return self.obj.get(sfSigningPubKey, b"")

    @property
    def signature(self) -> bytes:
        return self.obj.get(sfSignature, b"")

    # -- signing ----------------------------------------------------------

    def serialize(self) -> bytes:
        return self.obj.serialize()

    def signing_hash(self) -> bytes:
        """VAL-prefixed hash of the signing fields
        (reference: SerializedValidation.cpp:70-73)."""
        return self.obj.signing_hash(HP_VALIDATION)

    def sign(self, key: KeyPair) -> None:
        self.obj[sfSigningPubKey] = key.public
        self.obj[sfSignature] = key.sign(self.signing_hash())
        self._sig_good = None

    def is_valid(self) -> bool:
        """reference: SerializedValidation::isValid (:90-108) — the hot
        Ed25519 verify the VerifyPlane batches per consensus round."""
        if self._sig_good is None:
            self._sig_good = verify_signature(
                self.signer, self.signing_hash(), self.signature
            )
        return self._sig_good

    def set_sig_verdict(self, good: bool) -> None:
        self._sig_good = good

    def validation_id(self) -> bytes:
        """Suppression/dedup key for relay (hash of the full blob)."""
        return prefix_hash(HP_VALIDATION, self.serialize())

    def __repr__(self):
        return (
            f"STValidation(ledger={self.ledger_hash.hex()[:8]} "
            f"seq={self.ledger_seq} signer={self.signer.hex()[:8]} "
            f"full={self.is_full})"
        )
