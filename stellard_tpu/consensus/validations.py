"""ValidationsStore: received validations, indexed by ledger hash and by
signer, with staleness rules and the quorum/election queries consensus
and LedgerMaster need.

Reference: src/ripple_app/misc/Validations.cpp — addValidation (:72),
getTrustedValidationCount (:221), getCurrentValidations (:338).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from .timing import LEDGER_EARLY_INTERVAL, LEDGER_VAL_INTERVAL
from .validation import STValidation

__all__ = ["ValidationsStore"]


class ValidationsStore:
    def __init__(
        self,
        is_trusted: Callable[[bytes], bool],
        now: Callable[[], int],
        max_ledgers: int = 256,
    ):
        self._lock = threading.Lock()
        self.is_trusted = is_trusted  # node pubkey -> on our UNL?
        self.now = now  # network time (seconds since network epoch)
        self.max_ledgers = max_ledgers
        # defense sink (ValidatorNode.note_byzantine, set post-init):
        # equivocating / stale / duplicated validations are counted —
        # they were already harmless to quorum math, now they are visible
        self.note_byzantine = None
        # ledger hash -> {signer -> validation}
        self.by_ledger: dict[bytes, dict[bytes, STValidation]] = {}
        # signer -> its latest current validation
        self.current: dict[bytes, STValidation] = {}
        # highest ledger seq WE have signed a validation for
        # (reference: Validations::canValidateSeq — a validator's issued
        # seqs are strictly increasing, so fork repair can never make an
        # honest key sign two different ledgers at one seq; without this
        # two overlapping "quorums" could validate different ledgers at
        # one seq, a fork the scenario fuzzer actually reached)
        self.local_high_seq = 0

    def _is_current(self, val: STValidation, now: int) -> bool:
        """reference: isCurrent — reject far-future and stale signing
        times (LEDGER_EARLY_INTERVAL / LEDGER_VAL_INTERVAL)."""
        t = val.signing_time
        return (now - LEDGER_VAL_INTERVAL) < t < (now + LEDGER_EARLY_INTERVAL)

    def add(self, val: STValidation, local: bool = False) -> bool:
        """Store a (signature-checked) validation. Returns True when it is
        current and should be relayed (reference: addValidation :72-120).
        ``local`` marks our own just-built validation (never charged to
        the defense counters)."""
        val.trusted = self.is_trusted(val.signer)
        now = self.now()
        current = self._is_current(val, now)
        note = self.note_byzantine if not local else None
        if local and val.ledger_seq is not None:
            self.local_high_seq = max(self.local_high_seq, val.ledger_seq)
        with self._lock:
            per_signer = self.by_ledger.setdefault(val.ledger_hash, {})
            dup = (
                val.signer in per_signer
                and per_signer[val.signer].signing_time == val.signing_time
            )
            per_signer[val.signer] = val
            self._trim()
            if current:
                prev = self.current.get(val.signer)
                conflicting = (
                    prev is not None
                    and prev.ledger_hash != val.ledger_hash
                    and prev.ledger_seq is not None
                    and prev.ledger_seq == val.ledger_seq
                )
                if prev is None or prev.signing_time < val.signing_time:
                    self.current[val.signer] = val
                    # one key signing TWO ledgers at one seq: the newer
                    # statement REPLACES the older in the election (a
                    # signer never holds two current votes) and the
                    # equivocation is counted
                    if conflicting and note is not None:
                        note("conflicting_validation", peer=val.signer,
                             seq=val.ledger_seq)
                    return True
                if note is not None:
                    if conflicting:
                        note("conflicting_validation", peer=val.signer,
                             seq=val.ledger_seq)
                    elif dup:
                        note("duplicate_validation", peer=val.signer)
                return False
        if note is not None:
            # signing time outside the currency window: replayed history
            # or a far-future stamp — stored for the per-hash record,
            # zero electoral weight
            note("stale_validation", peer=val.signer)
        return False

    def can_sign(self, seq: Optional[int]) -> bool:
        """May WE issue a validation for this seq? Strictly increasing
        issued seqs (reference: canValidateSeq) — after fork repair a
        validator abstains at seqs it already voted rather than signing
        a second, conflicting statement."""
        return seq is None or seq > self.local_high_seq

    def _trim(self) -> None:
        while len(self.by_ledger) > self.max_ledgers:
            self.by_ledger.pop(next(iter(self.by_ledger)))

    # -- quorum queries ---------------------------------------------------

    def trusted_count_for(self, ledger_hash: bytes) -> int:
        """How many trusted validators validated this ledger
        (reference: getTrustedValidationCount :221 — feeds
        LedgerMaster::checkAccept)."""
        with self._lock:
            vals = self.by_ledger.get(ledger_hash, {})
            return sum(1 for v in vals.values() if v.trusted)

    def validations_for(self, ledger_hash: bytes) -> list[STValidation]:
        with self._lock:
            return list(self.by_ledger.get(ledger_hash, {}).values())

    def current_trusted(self) -> list[STValidation]:
        """Current validations from trusted signers, dropping expired ones
        (reference: getCurrentValidations :338 — LCL election input)."""
        now = self.now()
        with self._lock:
            out, dead = [], []
            for signer, v in self.current.items():
                if not self._is_current(v, now):
                    dead.append(signer)
                elif v.trusted:
                    out.append(v)
            for signer in dead:
                del self.current[signer]
            return out

    def current_ledger_weights(self) -> dict[bytes, int]:
        """ledger hash -> count of current trusted validations — the
        weighted LCL election (reference: checkLastClosedLedger,
        NetworkOPs.cpp:776)."""
        weights: dict[bytes, int] = {}
        for v in self.current_trusted():
            weights[v.ledger_hash] = weights.get(v.ledger_hash, 0) + 1
        return weights

    def size(self) -> int:
        with self._lock:
            return sum(len(m) for m in self.by_ledger.values())
