"""On-ledger voting: AmendmentTable + FeeVote.

Role parity with the reference's flag-ledger voting machinery
(/root/reference/src/ripple_app/misc/AmendmentTableImpl.cpp:421-470
doValidation/doVoting, misc/FeeVoteImpl.cpp, wired into consensus at
LedgerConsensus.cpp:1033-1038 and takeInitialPosition):

- every validation we sign carries our amendment votes (the supported,
  not-yet-enabled, not-vetoed set) and our fee targets when they differ
  from the closed ledger's schedule;
- when the last closed ledger is a FLAG ledger (seq % flag_interval == 0),
  the next round's initial position gets pseudo-transactions injected:
  ttAMENDMENT for each amendment that has held >= majority_fraction of
  trusted validations for longer than majority_time, and ttFEE when the
  plurality of fee votes disagrees with the current schedule.

The voting inputs are the validations for the flag ledger's PARENT (the
reference reads getValidations(lastClosedLedger->getParentHash())) —
those are the validations every honest node has already seen, so
positions built from them agree byzantine-free.
"""

from __future__ import annotations

import threading
from collections import Counter
from typing import Iterable, Optional

from ..protocol.formats import TxType
from ..protocol.sfields import (
    sfSigningPubKey,
    sfAmendment,
    sfBaseFee,
    sfReferenceFeeUnits,
    sfReserveBase,
    sfReserveIncrement,
)
from ..protocol.stamount import ACCOUNT_ZERO
from ..protocol.sttx import SerializedTransaction
from .validation import STValidation

__all__ = ["AmendmentTable", "FeeVote", "VotingBox", "FLAG_INTERVAL"]

FLAG_INTERVAL = 256
MAJORITY_FRACTION = 204  # of 256 trusted validators (~80%, reference value)
DEFAULT_MAJORITY_TIME = 14 * 24 * 3600  # two weeks (reference weeks(2))


def make_amendment_tx(amendment: bytes) -> SerializedTransaction:
    """ttAMENDMENT pseudo-tx (account zero, empty signing key, no
    fee/seq/signature — reference Change.cpp pseudo-tx shape)."""
    return SerializedTransaction.build(
        TxType.ttAMENDMENT,
        ACCOUNT_ZERO,
        0,
        0,
        {sfAmendment: amendment, sfSigningPubKey: b""},
    )


def make_fee_tx(
    base_fee: int, reference_fee_units: int, reserve_base: int, reserve_increment: int
) -> SerializedTransaction:
    return SerializedTransaction.build(
        TxType.ttFEE,
        ACCOUNT_ZERO,
        0,
        0,
        {
            sfBaseFee: base_fee,
            sfReferenceFeeUnits: reference_fee_units,
            sfReserveBase: reserve_base,
            sfReserveIncrement: reserve_increment,
            sfSigningPubKey: b"",
        },
    )


class AmendmentTable:
    """Supported/enabled/vetoed amendment registry + majority tracking."""

    def __init__(
        self,
        majority_time: int = DEFAULT_MAJORITY_TIME,
        majority_fraction: int = MAJORITY_FRACTION,
    ):
        self.majority_time = majority_time
        self.majority_fraction = majority_fraction
        self._lock = threading.Lock()
        self.names: dict[bytes, str] = {}
        self.supported: set[bytes] = set()
        self.vetoed: set[bytes] = set()
        self.enabled: set[bytes] = set()
        # amendment -> (first_majority_close_time, last_majority_close_time)
        self.majorities: dict[bytes, tuple[int, int]] = {}

    def add_known(self, amendment: bytes, name: str = "", supported: bool = True,
                  vetoed: bool = False) -> None:
        with self._lock:
            self.names[amendment] = name or amendment.hex()[:16]
            if supported:
                self.supported.add(amendment)
            if vetoed:
                self.vetoed.add(amendment)

    def veto(self, amendment: bytes) -> None:
        with self._lock:
            self.vetoed.add(amendment)

    def set_enabled(self, amendments: Iterable[bytes]) -> None:
        """Sync from the closed ledger's ltAMENDMENTS entry."""
        with self._lock:
            self.enabled = set(amendments)

    def desired(self) -> list[bytes]:
        """What we vote for: supported, not enabled, not vetoed (sorted —
        the reference sorts the STVector256 so validations are canonical)."""
        with self._lock:
            return sorted(self.supported - self.enabled - self.vetoed)

    # -- consensus hooks --------------------------------------------------

    def do_validation(self) -> Optional[list[bytes]]:
        """Amendment votes for a validation we are about to sign."""
        des = self.desired()
        return des or None

    def do_voting(
        self, flag_close_time: int, parent_validations: list[STValidation]
    ) -> list[SerializedTransaction]:
        """Called when the LCL is a flag ledger; returns ttAMENDMENT
        pseudo-txs for the next initial position."""
        trusted = [v for v in parent_validations if v.trusted]
        n_voters = len(trusted)
        votes: Counter[bytes] = Counter()
        for val in trusted:
            for amendment in val.amendments or []:
                votes[amendment] += 1
        threshold = max(1, (n_voters * self.majority_fraction + 255) // 256)
        out: list[SerializedTransaction] = []
        with self._lock:
            for amendment in set(votes) | set(self.majorities):
                has_majority = n_voters > 0 and votes.get(amendment, 0) >= threshold
                if not has_majority:
                    self.majorities.pop(amendment, None)
                    continue
                first, _last = self.majorities.get(
                    amendment, (flag_close_time, flag_close_time)
                )
                self.majorities[amendment] = (first, flag_close_time)
                if (
                    flag_close_time - first >= self.majority_time
                    and amendment not in self.enabled
                    and amendment not in self.vetoed
                ):
                    out.append(make_amendment_tx(amendment))
        out.sort(key=lambda tx: tx.txid())
        return out

    def get_json(self) -> dict:
        with self._lock:
            out = {}
            for amendment, name in self.names.items():
                out[amendment.hex().upper()] = {
                    "name": name,
                    "supported": amendment in self.supported,
                    "enabled": amendment in self.enabled,
                    "vetoed": amendment in self.vetoed,
                    "majority": self.majorities.get(amendment),
                }
            return out


class FeeVote:
    """Fee/reserve voting (reference FeeVoteImpl): vote our targets in
    validations; on flag ledgers move the schedule to the plurality."""

    def __init__(
        self,
        target_base_fee: int = 10,
        target_reference_fee_units: int = 10,
        target_reserve_base: int = 200_000_000,
        target_reserve_increment: int = 50_000_000,
    ):
        self.base_fee = target_base_fee
        self.reference_fee_units = target_reference_fee_units
        self.reserve_base = target_reserve_base
        self.reserve_increment = target_reserve_increment

    def do_validation(self, ledger) -> dict:
        """Fee fields to embed in our validation, when our targets differ
        from the schedule of the ledger we validated."""
        fields = {}
        if ledger.base_fee != self.base_fee:
            fields["base_fee"] = self.base_fee
        if ledger.reserve_base != self.reserve_base:
            fields["reserve_base"] = self.reserve_base
        if ledger.reserve_increment != self.reserve_increment:
            fields["reserve_increment"] = self.reserve_increment
        return fields

    def do_voting(
        self, flag_ledger, parent_validations: list[STValidation]
    ) -> list[SerializedTransaction]:
        """Plurality vote per knob (reference VotableInteger: the value
        with the most votes wins; the current value is everyone's default
        vote)."""
        trusted = [v for v in parent_validations if v.trusted]

        def plurality(current: int, votes: list[int]) -> int:
            counts: Counter[int] = Counter()
            for vote in votes:
                counts[vote] += 1
            # unvoiced validators implicitly support the current value
            counts[current] += len(trusted) - len(votes)
            if not counts:
                return current
            # highest count wins; ties prefer the incumbent, then the
            # smallest value — fully deterministic so every node injects
            # the identical ttFEE pseudo-tx regardless of arrival order
            best = max(
                counts.items(), key=lambda kv: (kv[1], kv[0] == current, -kv[0])
            )
            return best[0]

        base_fee = plurality(
            flag_ledger.base_fee,
            [v.base_fee for v in trusted if v.base_fee is not None],
        )
        reserve_base = plurality(
            flag_ledger.reserve_base,
            [v.reserve_base for v in trusted if v.reserve_base is not None],
        )
        reserve_increment = plurality(
            flag_ledger.reserve_increment,
            [v.reserve_increment for v in trusted if v.reserve_increment is not None],
        )
        if (
            base_fee == flag_ledger.base_fee
            and reserve_base == flag_ledger.reserve_base
            and reserve_increment == flag_ledger.reserve_increment
        ):
            return []
        return [
            make_fee_tx(
                base_fee,
                flag_ledger.reference_fee_units,
                reserve_base,
                reserve_increment,
            )
        ]


class VotingBox:
    """The consensus-facing bundle: validation decoration + flag-ledger
    pseudo-tx injection (what LedgerConsensus.cpp:1033-1038 and
    takeInitialPosition call into)."""

    def __init__(
        self,
        amendments: Optional[AmendmentTable] = None,
        fees: Optional[FeeVote] = None,
        flag_interval: int = FLAG_INTERVAL,
    ):
        self.amendments = amendments
        self.fees = fees
        self.flag_interval = flag_interval

    def is_flag_ledger(self, seq: int) -> bool:
        return seq > 0 and seq % self.flag_interval == 0

    def validation_fields(self, ledger) -> dict:
        """Extra STValidation.build kwargs for the ledger we just built."""
        fields: dict = {}
        if self.fees is not None:
            fields.update(self.fees.do_validation(ledger))
        if self.amendments is not None:
            votes = self.amendments.do_validation()
            if votes:
                fields["amendments"] = votes
        return fields

    def position_injections(
        self, prev_ledger, parent_validations: list[STValidation]
    ) -> list[SerializedTransaction]:
        """Pseudo-txs for the initial position when prev is a flag ledger."""
        if not self.is_flag_ledger(prev_ledger.seq):
            return []
        out: list[SerializedTransaction] = []
        if self.amendments is not None:
            out.extend(
                self.amendments.do_voting(
                    prev_ledger.close_time, parent_validations
                )
            )
        if self.fees is not None:
            out.extend(self.fees.do_voting(prev_ledger, parent_validations))
        return out

    def on_ledger_closed(self, ledger) -> None:
        """Sync enabled amendments from the new LCL's state."""
        if self.amendments is None:
            return
        from ..state import indexes
        from ..protocol.sfields import sfAmendments

        sle = ledger.read_entry(indexes.amendment_index())
        self.amendments.set_enabled(
            list(sle.get(sfAmendments, [])) if sle is not None else []
        )
