from .backend import (
    BatchVerifier,
    BatchHasher,
    register_verifier,
    register_hasher,
    make_verifier,
    make_hasher,
    VerifyRequest,
)
