"""The crypto-plane backend seam: pluggable batched verifier/hasher.

This is the factory-registry pattern the reference uses for NodeStore
backends (/root/reference/src/ripple_core/nodestore/api/Factory.h:27-44,
Manager::make_Database), applied to the crypto hot path per the north
star: `signature_backend = cpu|tpu` in the node config selects which
implementation coalesced JobQueue-style verification batches run on.

- ``cpu``: per-signature verification via the host library (the libsodium
  role), threaded over the batch.
- ``tpu``: the batched JAX kernel (ops.ed25519_jax) — one device program
  over the whole batch.
"""

from __future__ import annotations

import logging
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Iterable, Optional, Sequence

import numpy as np

log = logging.getLogger("stellard.device")

# Serializes the FIRST jax import across threads. At node setup the
# verify prewarm thread and the genesis ledger hash (a forced-device
# hash plane) can both trigger jax's first import concurrently, and
# jax's internal circular imports make a concurrent first import crash
# with "partially initialized module jax.numpy has no attribute ..." —
# one thread must complete the whole import chain before any other
# device path touches it.
_JAX_IMPORT_LOCK = threading.Lock()


def ensure_jax():
    """Import (and fully initialize) jax under a process-wide lock;
    returns the module. Every device-backend entry point calls this
    instead of a bare `import jax` so two threads can never interleave
    jax's first partial initialization."""
    with _JAX_IMPORT_LOCK:
        import jax
        import jax.numpy  # noqa: F401 — force the circular tail too

        return jax


def parse_mesh(value) -> str:
    """Canonicalize a ``mesh=`` config value (the multi-chip width axis):
    returns ``"auto"`` or the string form of a non-negative int. ``0``
    means "no mesh requested" — which executes as a width-1 mesh, the
    SAME routed code path as every other width (there is no separate
    single-device fork). Anything else raises: a width toggle must not
    silently fail open into an unintended topology."""
    if value is None:
        return "0"
    s = str(value).strip().lower()
    if s in ("", "off"):
        return "0"
    if s == "auto":
        return "auto"
    try:
        n = int(s)
    except ValueError:
        raise ValueError(
            f"mesh= must be a non-negative integer or 'auto', got {value!r}"
        ) from None
    if n < 0:
        raise ValueError(
            f"mesh= must be a non-negative integer or 'auto', got {value!r}"
        )
    return str(n)


def mesh_wants_width(value) -> bool:
    """True when a ``mesh=`` value asks for MORE than one chip (so the
    three-way host/1-chip/N-chip routing should grow a separate 1-chip
    arm). "auto" counts: its effective width is only known at device
    discovery."""
    m = parse_mesh(value)
    return m == "auto" or int(m) > 1


def resolve_mesh_width(mesh, n_visible: int, pow2: bool = False) -> int:
    """Effective mesh width for a backend: ``auto`` -> every visible
    device, N -> min(N, visible) (clamped with a warning — a config
    asking for more chips than exist must degrade loudly, not die),
    0 -> 1. ``pow2=True`` additionally rounds DOWN to a power of two
    (the hash plane's leaf batcher pads row counts to powers of two, so
    only pow2 widths divide its batches evenly)."""
    m = parse_mesh(mesh)
    n_visible = max(1, n_visible)
    want = n_visible if m == "auto" else max(1, int(m))
    if want > n_visible:
        log.warning(
            "mesh=%s requests %d devices but only %d are visible — "
            "clamping to %d", m, want, n_visible, n_visible,
        )
    width = max(1, min(want, n_visible))
    if pow2:
        width = 1 << (width.bit_length() - 1)
    return width


@dataclass(frozen=True)
class VerifyRequest:
    public: bytes  # 32-byte Ed25519 public key
    signing_hash: bytes  # 32-byte message (prefixed SHA-512-half)
    signature: bytes  # 64-byte detached signature


class BatchVerifier:
    """Interface: verify a batch of Ed25519 signatures."""

    name = "abstract"

    def verify_batch(self, batch: Sequence[VerifyRequest]) -> np.ndarray:
        raise NotImplementedError


class BatchHasher:
    """Interface: batched SHA-512-half with 4-byte domain prefixes.

    Hashers are callable (the SHAMap hash_batch seam); implementations
    may additionally expose ``hash_tree(root)`` for whole-tree device
    pipelines (state.shamap.compute_hashes detects it)."""

    name = "abstract"

    # routing counters (bench legs report a "device share" so a hasher
    # that silently falls back to host cannot look device-accelerated)
    device_nodes = 0
    host_nodes = 0

    def prefix_hash_batch(self, prefixes: Sequence[int], payloads: Sequence[bytes]) -> list[bytes]:
        raise NotImplementedError

    def hash_packed(self, buf: bytes, offsets: Sequence[int]) -> list[bytes]:
        """Hash PACKED messages (state.shamap.encode_nodes layout: every
        message carries its 4-byte domain prefix, `offsets` is the n+1
        boundary list). Default adapter slices back into the
        (prefixes, payloads) shape; real backends override with a
        zero-slicing path."""
        prefixes, payloads = [], []
        for i in range(len(offsets) - 1):
            msg = buf[offsets[i] : offsets[i + 1]]
            prefixes.append(int.from_bytes(msg[:4], "big"))
            payloads.append(msg[4:])
        return self.prefix_hash_batch(prefixes, payloads)

    def __call__(self, prefixes, payloads):
        return self.prefix_hash_batch(prefixes, payloads)


# name -> (factory, accepted-option names or None=accept anything).
# Declared options make the factories fail LOUDLY on unknown keys: the
# config plumbing (Config -> Node -> VerifyPlane/make_watched_hasher ->
# here) hands operator-written kwargs through, and a typo'd or
# unsupported option must raise at node build, never silently no-op.
_VERIFIERS: dict[str, tuple[Callable[..., BatchVerifier],
                            Optional[frozenset]]] = {}
_HASHERS: dict[str, tuple[Callable[..., BatchHasher],
                          Optional[frozenset]]] = {}


def _check_options(kind: str, name: str, accepted: Optional[frozenset],
                   kwargs: dict) -> None:
    if accepted is None:
        return  # undeclared factory (test doubles): accept anything
    unknown = sorted(set(kwargs) - accepted)
    if unknown:
        raise ValueError(
            f"{kind} backend {name!r} does not accept option(s) "
            f"{unknown}; accepted: {sorted(accepted) or '(none)'}"
        )


def register_verifier(name: str, factory: Callable[..., BatchVerifier],
                      options: Optional[Iterable[str]] = None) -> None:
    _VERIFIERS[name] = (
        factory, frozenset(options) if options is not None else None
    )


def register_hasher(name: str, factory: Callable[..., BatchHasher],
                    options: Optional[Iterable[str]] = None) -> None:
    _HASHERS[name] = (
        factory, frozenset(options) if options is not None else None
    )


def make_verifier(name: str, **kwargs) -> BatchVerifier:
    if name not in _VERIFIERS:
        raise KeyError(f"unknown signature backend {name!r}; have {sorted(_VERIFIERS)}")
    factory, accepted = _VERIFIERS[name]
    _check_options("signature", name, accepted, kwargs)
    return factory(**kwargs)


def make_hasher(name: str, **kwargs) -> BatchHasher:
    if name not in _HASHERS:
        raise KeyError(f"unknown hash backend {name!r}; have {sorted(_HASHERS)}")
    factory, accepted = _HASHERS[name]
    _check_options("hash", name, accepted, kwargs)
    return factory(**kwargs)


# --------------------------------------------------------------------------
# cpu backend


class CpuVerifier(BatchVerifier):
    """Host-library per-signature verification (the libsodium role of the
    reference: StellarPublicKey::verifySignature), threaded over the batch."""

    name = "cpu"
    impl = "openssl"

    _shared_pool: ThreadPoolExecutor | None = None

    def __init__(self, threads: int = 4):
        if threads > 1:
            if CpuVerifier._shared_pool is None:
                CpuVerifier._shared_pool = ThreadPoolExecutor(
                    max_workers=threads, thread_name_prefix="cpu-verify"
                )
            self._pool = CpuVerifier._shared_pool
        else:
            self._pool = None

    def verify_batch(self, batch: Sequence[VerifyRequest]) -> np.ndarray:
        from ..protocol.keys import verify_signature

        def one(req: VerifyRequest) -> bool:
            return verify_signature(req.public, req.signing_hash, req.signature)

        if self._pool is None or len(batch) < 64:
            return np.array([one(r) for r in batch], bool)
        return np.array(list(self._pool.map(one, batch)), bool)


class NativeVerifier(BatchVerifier):
    """Batched C++ verification (native/src/ed25519_verify.cc): the whole
    batch crosses into native code in ONE call, so per-signature cost is
    pure curve arithmetic — no per-call interpreter work and no GIL.
    This is the closest analog of the reference's libsodium hot path
    (StellarPublicKey::verifySignature) and the default host side of the
    verify plane when the toolchain is present."""

    name = "cpu"  # fills the host role; .impl says which implementation
    impl = "native"

    def __init__(self, **_):
        from ..native import Ed25519NativeVerify

        self._impl = Ed25519NativeVerify()

    def verify_batch(self, batch: Sequence[VerifyRequest]) -> np.ndarray:
        return self._impl.verify_batch(
            [r.public for r in batch],
            [r.signing_hash for r in batch],
            [r.signature for r in batch],
        )


def _host_verifier_factory(**kwargs) -> BatchVerifier:
    """The ``cpu`` backend resolves to the fastest available host
    implementation: native C++ batch verify, else the per-signature
    host-library path. ``STELLARD_HOST_VERIFY`` overrides: ``python`` /
    ``openssl`` force the host-library path, ``native`` requires the
    C++ kernel (raises if unbuildable), ``auto`` (default) prefers
    native with graceful degradation. Unknown values are rejected — a
    perf/debug toggle must not silently no-op."""
    import os

    choice = os.environ.get("STELLARD_HOST_VERIFY", "auto").lower()
    if choice in ("python", "openssl"):
        return CpuVerifier(**kwargs)
    if choice == "native":
        return NativeVerifier()
    if choice not in ("auto", ""):
        raise ValueError(
            f"STELLARD_HOST_VERIFY={choice!r}: expected auto|native|"
            "python|openssl"
        )
    try:
        return NativeVerifier()
    except Exception:  # noqa: BLE001 — toolchain-less box: degrade
        return CpuVerifier(**kwargs)


class CpuHasher(BatchHasher):
    name = "cpu"

    def prefix_hash_batch(self, prefixes, payloads):
        from ..utils.hashes import prefix_hash

        self.host_nodes += len(prefixes)
        return [prefix_hash(p, d) for p, d in zip(prefixes, payloads)]

    def hash_packed(self, buf, offsets):
        # a packed message == prefix ‖ payload, and
        # prefix_hash(p, d) == sha512_half(p4 ‖ d): hash slices directly
        from ..utils.hashes import sha512_half

        mv = memoryview(buf)
        n = len(offsets) - 1
        self.host_nodes += n
        return [
            sha512_half(mv[offsets[i] : offsets[i + 1]]) for i in range(n)
        ]


# --------------------------------------------------------------------------
# tpu backend


class TransferMeter:
    """Host<->device transfer honesty counter (ISSUE 16): every device
    plane counts its host->device shipments and device->host readbacks
    so residency can't silently regress — a "fused" close that quietly
    round-trips per level shows up as a readback count proportional to
    tree depth instead of the pinned one-per-tree. ``uploads`` counts
    logical shipment SETS (one per dispatched program, however many
    arrays ride it); ``readbacks`` counts host-blocking device->host
    transfers — the residency signal."""

    __slots__ = ("uploads", "readbacks", "bytes_up", "bytes_down")

    def __init__(self):
        self.uploads = 0
        self.readbacks = 0
        self.bytes_up = 0
        self.bytes_down = 0

    def up(self, nbytes: int) -> None:
        self.uploads += 1
        self.bytes_up += int(nbytes)

    def down(self, nbytes: int) -> None:
        self.readbacks += 1
        self.bytes_down += int(nbytes)

    def get_json(self) -> dict:
        return {
            "uploads": self.uploads,
            "readbacks": self.readbacks,
            "bytes_up": self.bytes_up,
            "bytes_down": self.bytes_down,
            "transfers": self.uploads + self.readbacks,
            "bytes_moved": self.bytes_up + self.bytes_down,
        }


class TpuVerifier(BatchVerifier):
    """Batched JAX Ed25519 kernel (ops.ed25519_jax.verify_kernel).

    Batches are padded to power-of-two sizes to bound XLA recompiles.
    ``mesh=`` is the multi-chip width axis (GSPMD stance, Xu et al.
    2021): the batch dimension shards data-parallel over a 1-D device
    mesh of that width (parallel/mesh.py) and XLA splits the whole
    point-arithmetic pipeline across chips over ICI — the production
    integration of SURVEY §2.9 mapping #3 (VERDICT r2 #3). Width 1 and
    width N run the SAME sharded program: there is no separate
    single-device code path, only a narrower mesh.
    """

    name = "tpu"

    def __init__(self, min_batch: int = 256, max_batch: int = 16384,
                 mesh="auto"):
        self.min_batch = min_batch
        self.max_batch = max_batch
        self._kernel = None  # resolved lazily (device discovery)
        self.mesh = parse_mesh(mesh)  # validated at BUILD time, loudly
        self.n_devices = 0  # effective width; set by _resolve_kernel
        self.devices_visible = 0
        self.platform = "unresolved"
        self.kernel_selected = "unresolved"
        # mesh+pallas small-batch bypass (set by _resolve_kernel)
        self._small_kernel = None
        self._mesh_floor = 0
        # Pad policy: "pow2" compiles one XLA program per power-of-two
        # bucket (proportional cost — right when compute scales with the
        # batch, i.e. CPU test backends); "max" pads every chunk to
        # max_batch so exactly ONE program shape ever compiles — right
        # on TPU, where the kernel is latency-flat in batch size (PERF.md
        # round-4 measurements) but every new shape costs a ~60s
        # mid-traffic compile. "auto" (default) picks the platform in
        # _resolve_kernel; until then pow2 is assumed, which only makes
        # the wedge watchdog's first-call deadline conservative.
        env = os.environ.get("STELLARD_PAD_POLICY", "auto")
        if env not in ("auto", "pow2", "max"):
            raise ValueError(
                f"STELLARD_PAD_POLICY={env!r}: expected auto|pow2|max"
            )
        self._pad_policy_env = env
        self.pad_policy = "pow2" if env != "max" else "max"
        self.transfers = TransferMeter()

    def _resolve_kernel(self):
        if self._kernel is not None:
            return self._kernel
        jax = ensure_jax()  # first import may race the hash plane

        from ..parallel.mesh import (
            make_mesh,
            sharded_verify_kernel,
            sharded_verify_kernel_pallas,
        )

        impl = os.environ.get("STELLARD_VERIFY_IMPL", "xla")
        if impl not in ("xla", "pallas"):
            # a perf/debug toggle must not silently no-op (same policy
            # as STELLARD_HOST_VERIFY below)
            raise ValueError(
                f"STELLARD_VERIFY_IMPL={impl!r}: expected 'xla' or 'pallas'"
            )
        devices = jax.devices()
        self.devices_visible = len(devices)
        self.platform = devices[0].platform
        if self._pad_policy_env == "auto":
            self.pad_policy = (
                "max" if devices[0].platform == "tpu" else "pow2"
            )
        # ONE code path at every width (the GSPMD stance): resolve the
        # config axis to an effective width and build the sharded
        # program over a mesh of exactly that many devices — width 1 is
        # a one-device mesh of the same program, not a separate kernel.
        width = resolve_mesh_width(self.mesh, len(devices))
        self.n_devices = width
        mesh = make_mesh(devices[:width])
        if impl == "pallas":
            from ..ops.ed25519_pallas import (
                BLOCK,
                verify_kernel_pallas,
            )

            self._kernel = sharded_verify_kernel_pallas(mesh)
            self.kernel_selected = f"pallas-shardmap@{width}"
            if width > 1:
                # each shard pads itself to a full grid BLOCK, so a
                # batch below width*BLOCK would pay `width` blocks of
                # mostly-zero work for single-block latency; route
                # those to the single-chip kernel instead
                self._small_kernel = verify_kernel_pallas
                self._mesh_floor = width * BLOCK
        else:
            self._kernel = sharded_verify_kernel(mesh)
            self.kernel_selected = f"xla-sharded@{width}"
        # pad floor must divide evenly across the mesh (round UP to a
        # multiple — doubling can never fix an odd device count)
        self.min_batch = ((self.min_batch + width - 1) // width) * width
        return self._kernel

    def describe(self) -> dict:
        """Routing-honesty snapshot: which devices/kernel/width this
        verifier actually resolved to (bench provenance + get_counts
        crypto block)."""
        return {
            "mesh_requested": self.mesh,
            "mesh_width": self.n_devices or None,
            "devices_visible": self.devices_visible or None,
            "platform": self.platform,
            "kernel": self.kernel_selected,
            "pad_policy": self.pad_policy,
            "min_batch": self.min_batch,
            "max_batch": self.max_batch,
        }

    def _pad_size(self, n: int, lo: int, hi: int) -> int:
        if self.pad_policy == "max":
            return hi
        size = lo
        while size < n and size < hi:
            size *= 2
        return size

    def verify_batch(self, batch: Sequence[VerifyRequest]) -> np.ndarray:
        from ..ops.ed25519_jax import prepare_batch

        kernel = self._resolve_kernel()
        starts = list(range(0, len(batch), self.max_batch))

        # double-buffered pipeline: host prep of chunk i+1 overlaps the
        # device execution of chunk i (JAX dispatch is asynchronous)
        out = np.zeros(len(batch), bool)
        pending: list = []  # (start, n, device_future)
        for start in starts:
            chunk = batch[start : start + self.max_batch]
            size = self._pad_size(len(chunk), self.min_batch, self.max_batch)
            nd = self.n_devices
            size = ((size + nd - 1) // nd) * nd  # shardable across the mesh
            pad = size - len(chunk)
            inputs = prepare_batch(
                [r.public for r in chunk] + [b"\x00" * 32] * pad,
                [r.signing_hash for r in chunk] + [b""] * pad,
                [r.signature for r in chunk] + [b"\x00" * 64] * pad,
            )
            k = kernel
            if self._small_kernel is not None and size < self._mesh_floor:
                k = self._small_kernel  # single chip beats 94%-zero shards
            self.transfers.up(sum(v.nbytes for v in inputs.values()))
            res = k(
                inputs["a_words"], inputs["r_words"], inputs["s_windows"],
                inputs["h_digits"], inputs["s_canonical"],
            )
            pending.append((start, len(chunk), res))
            if len(pending) > 1:
                s0, n0, r0 = pending.pop(0)
                got = np.asarray(r0)
                self.transfers.down(got.nbytes)
                out[s0 : s0 + n0] = got[:n0]
        for s0, n0, r0 in pending:
            got = np.asarray(r0)
            self.transfers.down(got.nbytes)
            out[s0 : s0 + n0] = got[:n0]
        return out


class TpuHasher(BatchHasher):
    """Batched JAX SHA-512 (ops.sha512_jax).

    Two paths (VERDICT r2 weak #3):
    - ``prefix_hash_batch``: flat batches, bucketed to a fixed
      block-count ladder and power-of-two batch sizes via the MASKED
      kernel, so the jit cache stays bounded;
    - ``hash_tree``: whole dirty SHAMaps hash level-synchronously with
      device-resident digests — inner payloads are assembled on-device
      by scattering child digests into pre-built templates, every level
      dispatches asynchronously, and the host blocks once at the end.
    """

    name = "tpu"

    def __init__(self, mesh="auto"):
        self.mesh = parse_mesh(mesh)  # validated at BUILD time, loudly
        self.n_devices = 0  # effective width; set on first kernel use
        self.devices_visible = 0
        self.kernel_selected = "unresolved"
        self._masked = None
        # whole-tree pipeline invocations (hash_tree): device work can
        # be real while the SHARDED flat kernel stays unresolved —
        # provenance must say which one ran
        self.tree_calls = 0
        self._tree_k = None  # (leaf, inner) sharded level kernels
        self.tree_width = 0
        self.tree_kernel = "unresolved"
        self.transfers = TransferMeter()
        # separate meter for the whole-tree pipeline: the residency pin
        # is crisp ONLY here — readbacks == tree_calls (one blocking
        # transfer per tree, never one per level), while the flat path
        # legitimately reads back per bucket
        self.tree_transfers = TransferMeter()

    def prefix_hash_batch(self, prefixes, payloads):
        return self._hash_msgs(
            [p.to_bytes(4, "big") + d for p, d in zip(prefixes, payloads)]
        )

    def hash_packed(self, buf, offsets):
        # packed messages (prefix embedded) slice straight into the
        # device prep — the same single-encoding feed the host path gets
        return self._hash_msgs(
            [buf[offsets[i] : offsets[i + 1]] for i in range(len(offsets) - 1)]
        )

    def _hash_msgs(self, msgs):
        ensure_jax()  # first import may race the verify plane
        import jax.numpy as jnp

        from ..ops.sha512_jax import padded_block_count
        from ..ops.treehash_jax import (
            LEAF_BLOCK_LADDER,
            pad_leaf_batch,
            sha512_blocks_masked,
        )
        from ..utils.hashes import sha512_half

        out: list[bytes | None] = [None] * len(msgs)
        buckets: dict[int, list[int]] = {}
        for i, m in enumerate(msgs):
            nb = padded_block_count(len(m))
            ladder = next((l for l in LEAF_BLOCK_LADDER if nb <= l), None)
            if ladder is None:  # oversized: host path (rare)
                out[i] = sha512_half(m)  # == prefix_hash(prefix, payload)
                self.host_nodes += 1
            else:
                buckets.setdefault(ladder, []).append(i)
                self.device_nodes += 1
        results = []  # (idxs, device_state) — dispatched async, read after
        for ladder, idxs in buckets.items():
            blocks, nblocks = pad_leaf_batch([msgs[i] for i in idxs], ladder)
            self.transfers.up(blocks.nbytes + nblocks.nbytes)
            st = self._masked_kernel()(jnp.asarray(blocks), jnp.asarray(nblocks))
            results.append((idxs, st))
        for idxs, st in results:
            arr = np.asarray(st)  # [Mpad, 16] u32
            self.transfers.down(arr.nbytes)
            raw = arr[:, :8].astype(">u4").tobytes()
            for row, i in enumerate(idxs):
                out[i] = raw[row * 32 : row * 32 + 32]
        return out  # type: ignore[return-value]

    # width -> compiled sharded kernel, shared across instances so the
    # 1-chip and N-chip arms of the three-way routing (and repeated
    # test constructions) never recompile an already-built width
    _KERNELS: dict[int, object] = {}

    def _masked_kernel(self):
        if self._masked is None:
            jax = ensure_jax()  # first import may race the verify plane

            from ..parallel.mesh import make_mesh, sharded_masked_sha512

            devices = jax.devices()
            self.devices_visible = len(devices)
            # flat-batch hashing shards data-parallel over the mesh.
            # pow2 widths only, capped at 8: pad_leaf_batch rows are
            # powers of two >= 8, so any power-of-two width up to 8
            # divides them evenly — a non-pow2 mesh= rounds DOWN.
            width = min(
                8, resolve_mesh_width(self.mesh, len(devices), pow2=True)
            )
            self.n_devices = width
            self.kernel_selected = f"masked-sha512-sharded@{width}"
            kern = TpuHasher._KERNELS.get(width)
            if kern is None:
                # one code path at every width: width 1 is a one-device
                # mesh of the same sharded program, not a separate jit
                kern = sharded_masked_sha512(make_mesh(devices[:width]))
                TpuHasher._KERNELS[width] = kern
            self._masked = kern
        return self._masked

    # width -> compiled (leaf, inner) sharded tree-level kernels — the
    # fused close's program set, shared across instances like _KERNELS
    _TREE_KERNELS: dict[int, tuple] = {}

    def _tree_kernels(self):
        if self._tree_k is None:
            jax = ensure_jax()  # first import may race the verify plane

            from ..parallel.mesh import make_mesh, sharded_tree_kernels

            devices = jax.devices()
            self.devices_visible = len(devices)
            # same width discipline as the flat kernel: every level's
            # row count is a power of two >= 8, so pow2 widths up to 8
            # divide them evenly at any tree shape
            width = min(
                8, resolve_mesh_width(self.mesh, len(devices), pow2=True)
            )
            self.tree_width = width
            self.tree_kernel = f"tree-sha512-sharded@{width}"
            pair = TpuHasher._TREE_KERNELS.get(width)
            if pair is None:
                # one code path at every width: width 1 is a one-device
                # mesh of the same sharded+donated programs
                pair = sharded_tree_kernels(make_mesh(devices[:width]))
                TpuHasher._TREE_KERNELS[width] = pair
            self._tree_k = pair
        return self._tree_k

    def describe(self) -> dict:
        """Routing-honesty snapshot (bench provenance / get_counts).
        `kernel`/`mesh_width` describe the SHARDED flat-batch kernel;
        `tree_kernel`/`tree_width` the fused whole-tree program set and
        `tree_pipeline_calls` its run count — either arm can carry the
        device traffic while the other stays unresolved, and provenance
        must say which one ran."""
        return {
            "mesh_requested": self.mesh,
            "mesh_width": self.n_devices or None,
            "devices_visible": self.devices_visible or None,
            "kernel": self.kernel_selected,
            "tree_kernel": self.tree_kernel,
            "tree_width": self.tree_width or None,
            "tree_pipeline_calls": self.tree_calls,
            "transfers": self.transfers.get_json(),
            "tree_transfers": self.tree_transfers.get_json(),
        }

    # -- whole-tree pipeline ----------------------------------------------

    def hash_tree(self, root, cancelled=None, cancel_lock=None) -> int:
        """Fill every missing node hash in a SHAMap with device-resident
        level-synchronous hashing. Returns the number of nodes hashed.

        ``cancelled``/``cancel_lock`` (threading.Event/Lock, optional,
        supplied together by the watchdog — utils.devicewatch): the
        write-back runs check-then-stamp as ONE critical section under
        ``cancel_lock``, and the watchdog sets ``cancelled`` under the
        same lock before it starts any host fallback. Either this call
        stamps the whole tree before the fallback begins, or it stamps
        nothing — an abandoned (zombie) call can never interleave writes
        with the fallback's traversal."""
        ensure_jax()  # first import may race the verify plane
        import jax.numpy as jnp

        from ..ops.sha512_jax import padded_block_count
        from ..ops.treehash_jax import (
            INNER_WORDS,
            LEAF_BLOCK_LADDER,
            build_inner_template,
            pad_leaf_batch,
            _pow2,
        )
        from ..state.shamap import (
            Inner,
            Leaf,
            ZERO256,
            _collect_unhashed,
            encode_nodes,
        )
        from ..utils.hashes import HP_INNER_NODE, sha512_half

        levels = _collect_unhashed(root)
        if not levels:
            return 0

        index_of: dict[int, int] = {}  # id(node) -> digest-buffer row
        plan: list[tuple] = []
        offset = 0
        hashed_host = 0

        for level in reversed(levels):
            leaves_by_bucket: dict[int, list] = {}
            inners: list = []
            leaves: list = []
            for node in level:
                if isinstance(node, Leaf):
                    leaves.append(node)
                elif node.is_empty():
                    node._hash = ZERO256
                    hashed_host += 1
                else:
                    inners.append(node)
            if leaves:
                # one flat-buffer encoding feeds the whole level's device
                # prep (the same encoder the host SHA batch consumes)
                lbuf, loff = encode_nodes(leaves)
                for i, node in enumerate(leaves):
                    msg = lbuf[loff[i] : loff[i + 1]]
                    nb = padded_block_count(len(msg))
                    ladder = next(
                        (l for l in LEAF_BLOCK_LADDER if nb <= l), None
                    )
                    if ladder is None:  # oversized leaf: host hash, known
                        node._hash = sha512_half(msg)
                        hashed_host += 1
                    else:
                        leaves_by_bucket.setdefault(ladder, []).append(
                            (node, msg)
                        )
            for ladder, entries in sorted(leaves_by_bucket.items()):
                for i, (node, _msg) in enumerate(entries):
                    index_of[id(node)] = offset + i
                plan.append(("leaf", ladder, entries, offset))
                offset += _pow2(len(entries))
            if inners:
                for i, node in enumerate(inners):
                    index_of[id(node)] = offset + i
                plan.append(("inner", inners, offset))
                offset += _pow2(len(inners))

        if not plan:
            self.host_nodes += hashed_host
            return hashed_host

        # counted HERE, not at entry: tree_calls must pair 1:1 with the
        # pipeline's single readback (the residency pin readbacks ==
        # tree_calls), so already-hashed / host-only calls don't count
        self.tree_calls += 1
        cap = _pow2(offset)
        # the persistent device buffer: every level kernel takes it
        # DONATED and hands back the same allocation, so the whole
        # chain runs device-resident at any mesh width
        leaf_k, inner_k = self._tree_kernels()
        buf = jnp.zeros((cap, 8), jnp.uint32)
        prefix_words = int(HP_INNER_NODE)

        for step in plan:
            if step[0] == "leaf":
                _k, ladder, entries, off = step
                blocks, nblocks = pad_leaf_batch(
                    [msg for _n, msg in entries], ladder
                )
                self.tree_transfers.up(blocks.nbytes + nblocks.nbytes)
                buf = leaf_k(
                    buf, jnp.asarray(blocks), jnp.asarray(nblocks), off
                )
            else:
                _k, inners, off = step
                n = len(inners)
                template = build_inner_template(n, pow2_rows=True)
                template[:, 0] = prefix_words
                rows, col_base, src_rows = [], [], []
                for i, node in enumerate(inners):
                    for c, child in enumerate(node.children):
                        if child is None:
                            h = ZERO256
                        elif child._hash is not None:
                            h = child._hash
                        else:
                            rows.append(i)
                            col_base.append(1 + 8 * c)
                            src_rows.append(index_of[id(child)])
                            continue
                        template[i, 1 + 8 * c : 9 + 8 * c] = np.frombuffer(
                            h, dtype=">u4"
                        )
                if rows:
                    # quantize the scatter program to a pow2 length by
                    # REPEATING entry 0 — duplicate scatters of one
                    # identical (index, value) are deterministic, so no
                    # scratch row is needed and template rows stay
                    # pow2/mesh-divisible ([0]-length programs when
                    # every child hash is already known)
                    pad = _pow2(len(rows)) - len(rows)
                    rows += [rows[0]] * pad
                    col_base += [col_base[0]] * pad
                    src_rows += [src_rows[0]] * pad
                ra = np.array(rows, np.int32)
                ca = np.array(col_base, np.int32)
                sa = np.array(src_rows, np.int32)
                self.tree_transfers.up(
                    template.nbytes + ra.nbytes + ca.nbytes + sa.nbytes
                )
                buf = inner_k(
                    buf,
                    jnp.asarray(template),
                    jnp.asarray(ra),
                    jnp.asarray(ca),
                    jnp.asarray(sa),
                    off,
                )

        host = np.asarray(buf)  # ONE transfer; blocks on the whole chain
        self.tree_transfers.down(host.nbytes)
        lock = cancel_lock if cancel_lock is not None else threading.Lock()
        with lock:
            if cancelled is not None and cancelled.is_set():
                return 0  # abandoned by the watchdog: tree untouched
            raw = host.astype(">u4").tobytes()
            for level in levels:
                for node in level:
                    if node._hash is None:
                        row = index_of[id(node)]
                        node._hash = raw[row * 32 : row * 32 + 32]
        self.host_nodes += hashed_host
        self.device_nodes += len(index_of)
        return hashed_host + len(index_of)


register_verifier("cpu", _host_verifier_factory, options=("threads",))
# strict: raises if unbuildable
register_verifier("native", NativeVerifier, options=())
# always-available host library
register_verifier("openssl", CpuVerifier, options=("threads",))
register_verifier("tpu", TpuVerifier,
                  options=("min_batch", "max_batch", "mesh"))
register_hasher("cpu", CpuHasher, options=())
register_hasher("tpu", TpuHasher, options=("mesh",))


class CppHasher(BatchHasher):
    """Native batched SHA-512-half (native/src/sha512.cc) — one C call
    per batch, filling the reference's OpenSSL-hashing role for the host
    path when the device hasher isn't warranted."""

    name = "cpp"

    def __init__(self, **_):
        from ..native import Sha512Native

        self._impl = Sha512Native()

    def prefix_hash_batch(self, prefixes, payloads):
        self.host_nodes += len(prefixes)
        return self._impl.prefix_hash_batch(prefixes, payloads)

    def hash_packed(self, buf, offsets):
        # the flat-buffer seal path: ONE buffer + offsets array into C,
        # no per-node join/slice on the Python side
        self.host_nodes += max(0, len(offsets) - 1)
        return self._impl.hash_packed(buf, offsets)


# registered unconditionally: CppHasher.__init__ raises a clean error on
# a toolchain-less box, and the (one-time) native build cost lands only
# on callers that actually select the cpp backend — never at import
register_hasher("cpp", CppHasher, options=())


class _RoutedFlat:
    """Flat-batch facade over a WatchdogHasher for compute_hashes: the
    routed/watchdogged prefix+packed paths WITHOUT the hash_tree attr
    (which would recurse back into the watchdog's tree dispatch)."""

    __slots__ = ("_wd",)

    def __init__(self, wd: "WatchdogHasher"):
        self._wd = wd

    def __call__(self, prefixes, payloads):
        return self._wd.prefix_hash_batch(prefixes, payloads)

    def prefix_hash_batch(self, prefixes, payloads):
        return self._wd.prefix_hash_batch(prefixes, payloads)

    def hash_packed(self, buf, offsets):
        return self._wd.hash_packed(buf, offsets)


# flat batches below this never route to a device backend: a handful of
# residual nodes can never amortize a device round-trip (the incremental
# seal's drain leftovers are the motivating case). Env-overridable via
# STELLARD_HASH_MIN_DEVICE_NODES on the watchdog.
DEVICE_HASH_FLOOR = 64


def make_watched_hasher(backend: str,
                        min_device_nodes: Optional[int] = None,
                        mesh=None,
                        routing: Optional[str] = None,
                        first_timeout: Optional[float] = None,
                        ) -> BatchHasher:
    """The ONE wiring for a possibly-device hasher: the tpu backend is
    wrapped in the wedge watchdog with a cpu fallback (a hung tunnel
    must degrade, not freeze) and the small-batch device floor; host
    backends pass through untouched. Used by the node and the bench
    legs so both always measure/run the identical construction.

    ``mesh`` is the [hash_backend] width axis (parse_mesh values). When
    it requests more than one chip, the watchdog gets BOTH a wide inner
    and a width-1 inner — the N-chip and 1-chip arms of the three-way
    measured-cost routing (host / 1-chip / N-chip), so small batches
    stay on host, medium batches on one chip, and only batches that
    amortize the collective go wide. ``routing`` ("cost"/"device")
    overrides STELLARD_HASH_ROUTING; ``first_timeout`` the wedge
    deadline."""
    opts = {}
    if backend == "tpu" and mesh is not None:
        opts["mesh"] = mesh
    hasher = make_hasher(backend, **opts)
    if backend == "tpu":
        floor = min_device_nodes
        if floor is None:  # explicit arg > env > device-backend default
            floor = int(os.environ.get(
                "STELLARD_HASH_MIN_DEVICE_NODES", str(DEVICE_HASH_FLOOR)
            ))
        inner_one = None
        if mesh_wants_width(mesh if mesh is not None else "auto"):
            # the 1-chip arm: the SAME sharded program at width 1
            inner_one = make_hasher("tpu", mesh="0")
        hasher = WatchdogHasher(
            hasher, make_hasher("cpu"), min_device_nodes=floor,
            inner_one=inner_one, routing=routing,
            first_timeout=first_timeout,
        )
    return hasher


def apply_kernel_tuning(path: str) -> Optional[dict]:
    """Apply an on-chip sweep's winning kernel configuration
    (tools/kernel_sweep.py writes KERNEL_TUNING.json) as env defaults,
    BEFORE any kernel module reads them. Explicit env settings win —
    which also means the values are process-global and first-writer-
    wins: a second tuning file applied in the same process is silently
    inert (the kernel knobs are read once at module import, so env is
    the only channel). Returns the parsed tuning dict when applied
    (callers also use its 'batch'), else None — malformed or
    unreadable files apply NOTHING (never a half-tuned combination).
    Used by bench.py (repo root) and the node ([kernel_tuning] config
    knob) so a daemon run honors the measured winner, not a hardcoded
    default."""
    import json

    try:
        with open(path) as f:
            t = json.load(f)
        # read every value BEFORE setting any env var: a partial file
        # must not apply a half-tuned (never-measured) combination
        values = {
            "STELLARD_VERIFY_UNROLL": str(int(t["unroll"])),
            "STELLARD_COMB_SELECT": str(t["comb"]),
            "STELLARD_HOIST_SELECT": str(int(t.get("hoist", 0))),
            "STELLARD_GROUP_OPS": str(int(t.get("group", 0))),
            "STELLARD_VERIFY_IMPL": str(t.get("impl", "xla")),
            "STELLARD_PALLAS_BLOCK": str(int(t.get("block", 512))),
        }
        # wire format is semantics-neutral (identical verdicts, pinned
        # by tests) so a measured winner auto-applies — but a tuning row
        # from before the wire field existed carries NO opinion, and
        # must not drag the bench back to the fatter digits wire
        if "wire" in t:
            values["STELLARD_WIRE"] = str(t["wire"])
        if values.get("STELLARD_WIRE", "raw") not in ("raw", "digits"):
            raise ValueError(values["STELLARD_WIRE"])
        if values["STELLARD_VERIFY_IMPL"] not in ("xla", "pallas"):
            # a hand-edited file must not park a crash at the first
            # device batch (_resolve_kernel validates the same set)
            raise ValueError(values["STELLARD_VERIFY_IMPL"])
        # NOTE: "check" (STELLARD_VERIFY_CHECK) is deliberately NOT
        # auto-applied. Unlike the knobs above it changes the computed
        # verify FUNCTION (byte-compare vs projective equality) — a
        # consensus-semantics choice that must be an explicit operator
        # decision (env var), never a perf-sweep side effect.
        int(t["batch"])  # validated for callers
    except (OSError, ValueError, KeyError, TypeError):
        return None
    for k, v in values.items():
        os.environ.setdefault(k, v)
    return t


class _HashCostModel:
    """Measured-cost routing for the hash plane (the VerifyPlane
    stance), generalized from host-vs-device to host + N device ARMS
    (the three-way host / 1-chip / N-chip split): per-pow2-bucket
    EWMAs per arm, first (compile-laden) sample discarded per
    (arm, bucket), one host measurement enables the comparison, and a
    losing arm re-explores per (arm, bucket) after `reexplore_every`
    eligible losses (a counter, not a global modulo — a bucket whose
    calls never align with a global stride must not be starved),
    bounded to within 4x of the winning cost. Thread-safe: the hasher
    is shared across node threads."""

    EWMA = 0.3
    REEXPLORE_BOUND = 4.0

    def __init__(self, reexplore_every: int, min_device_nodes: int = 0,
                 arms: Sequence[str] = ("device",)):
        self._lock = threading.Lock()
        self._reexplore = reexplore_every
        # floor knob: batches below this size NEVER route to (or explore)
        # the device — the incremental seal's residual batches are a few
        # nodes, far below any plausible device win, and without the
        # floor every tiny residual would re-trigger per-bucket
        # exploration (a device round-trip per close)
        self.min_device_nodes = max(0, int(min_device_nodes))
        self.arms = tuple(arms)
        # arm -> bucket -> [n_samples, ewma]
        self._dev: dict[str, dict[int, list]] = {a: {} for a in self.arms}
        self._host_unit_ms: Optional[float] = None
        self._losses: dict[tuple[str, int], int] = {}

    @staticmethod
    def _bucket(n: int) -> int:
        return 1 << max(0, n - 1).bit_length()

    def _ewma(self, cur: Optional[float], ms: float) -> float:
        return ms if cur is None else (1 - self.EWMA) * cur + self.EWMA * ms

    def get_json(self) -> dict:
        """Routing-model snapshot (bench provenance / BENCH_DETAIL /
        the get_counts crypto block). `buckets` keeps the legacy
        single-arm view (the primary device arm); `arms` is the full
        three-way snapshot."""
        with self._lock:
            arms = {
                arm: {
                    str(b): {"samples": s[0], "ewma_ms": s[1]}
                    for b, s in sorted(slots.items())
                }
                for arm, slots in self._dev.items()
            }
            return {
                "min_device_nodes": self.min_device_nodes,
                "host_unit_ms": self._host_unit_ms,
                "arms": arms,
                # legacy single-arm view: the PRIMARY (widest) arm —
                # the one that keeps accumulating after arm collapse,
                # matching _LatencyModel's device_bucket_ms view
                "buckets": arms[self.arms[-1]],
                "losses": {
                    f"{a}:{b}": v
                    for (a, b), v in sorted(self._losses.items())
                },
            }

    def choose(self, n: int, arms: Optional[Sequence[str]] = None) -> str:
        """Pick the arm for an n-node batch: ``"host"`` or a device arm
        name. Unmeasured device arms are explored first (in declared
        order); the host is measured once before any comparison; after
        that the cheapest measured arm wins, with bounded per-(arm,
        bucket) re-exploration of close losers."""
        avail = [a for a in (arms if arms is not None else self.arms)
                 if a in self._dev]
        with self._lock:
            if n < self.min_device_nodes or not avail:
                return "host"  # below any plausible win size
            b = self._bucket(n)
            costs: dict[str, float] = {}
            for a in avail:
                slot = self._dev[a].setdefault(b, [0, None])
                if slot[1] is None:
                    return a  # unmeasured (or compile sample): explore
                costs[a] = slot[1]
            if self._host_unit_ms is None:
                return "host"  # measure the host side once
            exp_host = self._host_unit_ms * n
            best_arm = min(costs, key=lambda a: costs[a])
            if costs[best_arm] <= exp_host:
                self._losses.pop((best_arm, b), None)
                winner, best = best_arm, costs[best_arm]
            else:
                winner, best = "host", exp_host
            # losing device arms within striking distance of the winner
            # accrue losses and periodically re-explore; hopeless arms
            # (beyond the 4x band) never do
            for a in avail:
                if a == winner:
                    continue
                if costs[a] > self.REEXPLORE_BOUND * best:
                    continue
                k = (a, b)
                self._losses[k] = self._losses.get(k, 0) + 1
                if self._losses[k] >= self._reexplore:
                    self._losses[k] = 0
                    return a
            return winner

    def use_device(self, n: int) -> bool:
        return self.choose(n) != "host"

    def observe(self, arm: str, n: int, ms: float) -> None:
        if arm == "host":
            with self._lock:
                self._host_unit_ms = self._ewma(self._host_unit_ms, ms / n)
            return
        with self._lock:
            slot = self._dev[arm].setdefault(self._bucket(n), [0, None])
            slot[0] += 1
            if slot[0] <= 1:
                return  # discard the compile-laden first sample
            slot[1] = self._ewma(slot[1], ms)

    # legacy single-arm shims (tests / two-way callers): the primary
    # arm is the WIDEST, same as the get_json "buckets" view
    def observe_device(self, n: int, ms: float) -> None:
        self.observe(self.arms[-1], n, ms)

    def observe_host(self, n: int, ms: float) -> None:
        self.observe("host", n, ms)


class WatchdogHasher(BatchHasher):
    """Run a device hasher's calls under a wedge deadline with a CPU
    fallback (utils.devicewatch): the observed tunnel failure mode is an
    indefinite hang, and a frozen tree-hash would freeze every ledger
    close. One overrun routes hashing to the fallback for the life of
    the process (sticky, shared with the verify plane's verdict).

    Deadlines: every hashing call gets the GENEROUS compile deadline.
    Unlike the verify plane (whose pad-bucket set is enumerable, so
    warmth is provable per shape), the device hasher compiles one
    program per (padded-batch, block-ladder) combination and tree
    hashing per level size — none of which the wrapper can enumerate
    from outside, so no call is provably recompile-free and a tight
    deadline would falsely kill a healthy device mid-compile. Hashing
    sits off the latency-critical path (closes batch it), and the
    verify plane's tight warmed deadline still provides fast wedge
    detection for the shared process-wide verdict.
    """

    # [tree] fused kill-switch surface: node.py stamps cfg.tree_fused
    # here, and shamap.compute_hashes / ledgermaster._drain_loop consult
    # it before taking the whole-tree device pipeline (fused=0 keeps
    # the staged per-level hash_packed path — the identity leg)
    fused_enabled = True

    def __init__(self, inner: BatchHasher, fallback: BatchHasher,
                 first_timeout: Optional[float] = None,
                 warm_timeout: Optional[float] = None,
                 min_device_nodes: Optional[int] = None,
                 inner_one: Optional[BatchHasher] = None,
                 routing: Optional[str] = None):
        from ..utils.devicewatch import resolve_timeouts

        self.inner = inner
        self.fallback = fallback
        # the 1-chip arm of the three-way routing: the same device
        # program at mesh width 1 (make_watched_hasher builds it when
        # [hash_backend] mesh= requests more than one chip). None keeps
        # the classic two-way host/device split.
        self.inner_one = inner_one
        self.name = inner.name
        self._t_first, _ = resolve_timeouts(first_timeout, warm_timeout)
        self.device_wedged = False
        # measured-cost routing (same stance as VerifyPlane's model: the
        # device must EARN traffic; a losing device floors at the host
        # path instead of dragging a leg, and is re-explored bounded).
        # routing="device" (or STELLARD_HASH_ROUTING=device) restores
        # route-everything-device — the widest arm.
        # (A separate small model rather than verifyplane._LatencyModel:
        # the units differ — per-node hash rates vs per-signature verify
        # costs — and the verify model is entangled with pad-bucket
        # warmth bookkeeping this wrapper has no analog for.)
        mode = routing if routing else os.environ.get(
            "STELLARD_HASH_ROUTING", "cost"
        )
        if mode not in ("cost", "device"):
            raise ValueError(
                f"hash routing must be cost|device, got {mode!r}"
            )
        self.routing = mode
        self._route_by_cost = mode != "device"
        # device floor: flat batches below this size never route to the
        # device, and tree hashing with a caller-supplied dirty-count
        # hint below it goes straight to the host level-batcher — the
        # incremental seal's residuals must not burn a device round-trip
        # per close. Explicit arg wins; STELLARD_HASH_MIN_DEVICE_NODES
        # next; default 0 (a watchdog wrapped around a HOST inner — the
        # test harness shape — must not divert its inner's traffic).
        # make_watched_hasher applies the device-backend default.
        if min_device_nodes is None:
            floor = int(os.environ.get("STELLARD_HASH_MIN_DEVICE_NODES", "0"))
        else:
            floor = int(min_device_nodes)
        if floor < 0:
            raise ValueError(
                "STELLARD_HASH_MIN_DEVICE_NODES must be >= 0, got "
                f"{floor}"
            )
        self.min_device_nodes = floor
        self._arm_names = (
            ("dev1", "devN") if inner_one is not None else ("device",)
        )
        self._flat = _HashCostModel(
            reexplore_every=256, min_device_nodes=floor,
            arms=self._arm_names,
        )
        # tree model buckets per-node RATE in the size-independent
        # bucket 1 — the floor applies via the hash_tree hint, not here
        # (the whole-tree device pipeline is a single-program scatter
        # chain, so it stays a two-way host/device decision)
        self._tree = _HashCostModel(reexplore_every=64)

    def _live_arms(self) -> tuple:
        """The device arms currently worth routing between. Once the
        wide inner RESOLVES to a single device (mesh= wider than the
        box), the 1-chip arm is the identical program — collapse it so
        the model stops exploring a duplicate."""
        if (self.inner_one is not None
                and getattr(self.inner, "n_devices", 0) == 1):
            self.inner_one = None
        if self.inner_one is None and len(self._arm_names) > 1:
            return self._arm_names[-1:]
        return self._arm_names

    def _inner_of(self, arm: str) -> BatchHasher:
        if arm == "dev1" and self.inner_one is not None:
            return self.inner_one
        return self.inner

    @property
    def device_nodes(self):  # type: ignore[override]
        one = self.inner_one.device_nodes if self.inner_one is not None else 0
        return self.inner.device_nodes + one

    @device_nodes.setter
    def device_nodes(self, value):  # counter reset (bench legs)
        self.inner.device_nodes = value
        if self.inner_one is not None:
            self.inner_one.device_nodes = 0

    @property
    def host_nodes(self):  # type: ignore[override]
        one = self.inner_one.host_nodes if self.inner_one is not None else 0
        return self.inner.host_nodes + self.fallback.host_nodes + one

    @host_nodes.setter
    def host_nodes(self, value):  # counter reset (bench legs)
        # round-trips: getter sums inner + fallback, so the value goes
        # to inner and the other shares zero
        self.inner.host_nodes = value
        self.fallback.host_nodes = 0
        if self.inner_one is not None:
            self.inner_one.host_nodes = 0

    def _wedge(self, exc: Exception) -> None:
        from ..utils.devicewatch import log as dlog

        self.device_wedged = True
        dlog.error("hash plane: %s — falling back to host hashing", exc)

    def prefix_hash_batch(self, prefixes, payloads):
        return self._routed(
            len(prefixes),
            lambda arm: self._inner_of(arm).prefix_hash_batch(
                prefixes, payloads
            ),
            lambda: self.fallback.prefix_hash_batch(prefixes, payloads),
        )

    def hash_packed(self, buf, offsets):
        """Routed flat-buffer hashing (the seal/flush path): same cost
        model and wedge watchdog as the (prefix, payload) shape."""
        return self._routed(
            len(offsets) - 1,
            lambda arm: self._inner_of(arm).hash_packed(buf, offsets),
            lambda: self.fallback.hash_packed(buf, offsets),
        )

    def _routed(self, n, device_call, host_fn):
        """Three-way measured-cost dispatch: host / 1-chip / N-chip.
        ``device_call(arm)`` runs the batch on that arm's inner hasher;
        cost-mode picks the cheapest measured arm (exploring unmeasured
        ones), device-mode forces the widest arm."""
        import time as _t

        from ..utils.devicewatch import DeviceWedged, call_with_deadline

        arm: Optional[str] = None
        if not self.device_wedged and n > 0:
            if not self._route_by_cost:
                arm = self._live_arms()[-1]  # forced: the widest arm
            else:
                choice = self._flat.choose(n, arms=self._live_arms())
                arm = None if choice == "host" else choice
        if arm is not None:
            try:
                t0 = _t.perf_counter()
                out = call_with_deadline(
                    lambda: device_call(arm), self._t_first,
                    label="hash-device",
                )
                self._flat.observe(
                    arm, n, (_t.perf_counter() - t0) * 1000.0
                )
                return out
            except DeviceWedged as exc:
                self._wedge(exc)
        t0 = _t.perf_counter()
        out = host_fn()
        if n > 0:
            self._flat.observe(
                "host", n, (_t.perf_counter() - t0) * 1000.0
            )
        return out

    def get_json(self) -> dict:
        """Hash-plane routing snapshot (bench legs record it next to
        device_share so a routed-out device is self-explaining): mesh
        width/kernel per arm plus the three-arm cost-model state."""
        describe = getattr(self.inner, "describe", None)
        return {
            "backend": self.name,
            "wedged": self.device_wedged,
            "routing": self.routing,
            "arms": list(self._live_arms()),
            "fused": bool(self.fused_enabled),
            "mesh": describe() if describe is not None else None,
            "device_nodes": self.device_nodes,
            "host_nodes": self.host_nodes,
            "min_device_nodes": self.min_device_nodes,
            "transfers": self.transfer_json(),
            "flat_model": self._flat.get_json(),
            "tree_model": self._tree.get_json(),
        }

    def transfer_json(self) -> Optional[dict]:
        """Transfer-honesty aggregate over both device arms (the N-chip
        inner and the 1-chip arm when present): per-close deltas of this
        block are the residency proof — a fused close moves ONE readback
        per tree, not one per level."""
        agg: Optional[dict] = None
        for h in (self.inner, self.inner_one):
            if h is None:
                continue
            # both meters per arm: the flat hash_packed meter AND the
            # whole-tree pipeline meter (split so the one-readback pin
            # stays crisp on tree_transfers alone)
            for meter in (getattr(h, "transfers", None),
                          getattr(h, "tree_transfers", None)):
                if meter is None:
                    continue
                j = meter.get_json()
                if agg is None:
                    agg = dict(j)
                else:
                    for k, v in j.items():
                        agg[k] = agg.get(k, 0) + v
        return agg

    def flat_hasher(self) -> "_RoutedFlat":
        """This hasher's routed FLAT facade (no hash_tree attr): tree
        hashing through it level-batches per-level pack_nodes buffers
        into the routed hash_packed path — the sharded masked-SHA
        kernel under device routing. The scenario plane uses it so
        chaos runs exercise the SHARDED flat plane, not the unsharded
        whole-tree scatter pipeline."""
        return _RoutedFlat(self)

    def _host_tree(self, root) -> int:
        """Level-batched host hashing. When the device is healthy this
        still routes through the WATCHED flat path (so e.g. a native
        cpp inner without hash_tree is used, watchdogged, for the
        dominant tree workload); once wedged it goes straight to the
        fallback."""
        from ..state.shamap import compute_hashes

        if self.device_wedged:
            return compute_hashes(root, self.fallback)
        return compute_hashes(root, _RoutedFlat(self))

    def hash_tree(self, root, hint_nodes: Optional[int] = None) -> int:
        import time as _t

        from ..utils.devicewatch import DeviceWedged, call_with_deadline

        inner_tree = getattr(self.inner, "hash_tree", None)
        if inner_tree is None:
            return self._host_tree(root)
        if (
            hint_nodes is not None
            and hint_nodes < self.min_device_nodes
            and self._route_by_cost
        ):
            # caller-declared small dirty set (incremental-seal residual
            # drains): below any plausible device win, and exploring the
            # device per tiny batch would burn a round-trip per close
            return self._host_tree(root)
        if not self.device_wedged and self._route_by_cost and (
            not self._tree.use_device(1)
        ):
            from ..state.shamap import compute_hashes

            t0 = _t.perf_counter()
            count = compute_hashes(root, self.fallback)
            if count:
                self._tree.observe_host(
                    count, (_t.perf_counter() - t0) * 1000.0
                )
            return count
        if not self.device_wedged:
            import inspect

            params = inspect.signature(inner_tree).parameters
            cancel = threading.Event() if "cancelled" in params else None
            lock = threading.Lock() if "cancel_lock" in params else None
            kwargs = {}
            if cancel is not None:
                kwargs["cancelled"] = cancel
            if lock is not None:
                kwargs["cancel_lock"] = lock
            try:
                t0 = _t.perf_counter()
                count = call_with_deadline(
                    lambda: inner_tree(root, **kwargs), self._t_first,
                    label="hash-device",
                )
                if count:
                    # per-node rate in the size-independent bucket 1
                    self._tree.observe_device(
                        1, (_t.perf_counter() - t0) * 1000.0 / count
                    )
                return count
            except DeviceWedged as exc:
                # Close the zombie race BEFORE any host work touches the
                # tree: setting cancelled under the shared lock means the
                # abandoned call either already stamped the whole tree
                # (its critical section completed first — the fallback
                # then finds nothing to hash) or will stamp nothing.
                if cancel is not None:
                    if lock is not None:
                        with lock:
                            cancel.set()
                    else:
                        cancel.set()
                self._wedge(exc)
        return self._host_tree(root)


# --------------------------------------------------------------------------
# path-quality plane: measured-cost routed Q16.16 candidate evaluation

# candidate batches below this never route to a device: a path_find with
# a handful of candidates can never amortize a dispatch (the sig/hash
# planes' DEVICE_*_FLOOR stance). Env-overridable on the evaluator.
PATHQ_DEVICE_FLOOR = 256


class PathQualityEvaluator:
    """Routed evaluation of flattened candidate-path rate matrices (the
    liquidity plane's device arm — ISSUE 17 tentpole leg 3).

    Same construction as the sig/hash planes: a NumPy host arm
    (ops.pathq_jax.path_quality_host), a 1-chip arm and an optional
    N-chip arm of the SAME sharded jit program
    (parallel.mesh.sharded_path_quality), routed per batch by the
    shared measured-cost model (_HashCostModel: per-pow2-bucket EWMAs,
    compile-sample discard, bounded re-exploration, small-batch host
    floor). Host and device arms are byte-identical at every mesh
    width — pinned by tests/test_path_plane.py and the bench leg.

    ``routing``: "cost" (default) measures; "device" forces the widest
    device arm (identity pinning / bench anti-vacuity); "host" forces
    the host arm.
    """

    def __init__(self, mesh=None, min_device_batch: Optional[int] = None,
                 routing: Optional[str] = None):
        self.mesh = parse_mesh(mesh)
        if min_device_batch is None:
            min_device_batch = int(os.environ.get(
                "STELLARD_PATHQ_MIN_DEVICE_BATCH", str(PATHQ_DEVICE_FLOOR)
            ))
        routing = (routing or os.environ.get(
            "STELLARD_PATHQ_ROUTING", "cost")).strip().lower()
        if routing not in ("cost", "device", "host"):
            raise ValueError(
                f"path evaluator routing must be cost|device|host, "
                f"got {routing!r}"
            )
        self.routing = routing
        arms = ("dev1", "devN") if mesh_wants_width(self.mesh) else ("dev1",)
        self._model = _HashCostModel(
            reexplore_every=64, min_device_nodes=min_device_batch, arms=arms,
        )
        self._lock = threading.Lock()
        self._kernels: dict[str, tuple] = {}  # arm -> (jit fn, width)
        self.host_batches = 0
        self.device_batches = 0
        self.rows_evaluated = 0

    # -- arms -------------------------------------------------------------

    def _kernel(self, arm: str):
        with self._lock:
            hit = self._kernels.get(arm)
            if hit is not None:
                return hit
        jax = ensure_jax()
        from ..parallel.mesh import make_mesh, sharded_path_quality

        devices = jax.devices()
        want = "0" if arm == "dev1" else self.mesh
        width = resolve_mesh_width(want, len(devices), pow2=True)
        fn = sharded_path_quality(make_mesh(devices[:width]))
        with self._lock:
            self._kernels.setdefault(arm, (fn, width))
            return self._kernels[arm]

    def evaluate_host(self, rates: np.ndarray) -> np.ndarray:
        from ..ops.pathq_jax import path_quality_host

        return path_quality_host(rates)

    def _evaluate_device(self, arm: str, rates: np.ndarray) -> np.ndarray:
        from ..ops.pathq_jax import Q16_ONE

        fn, width = self._kernel(arm)
        n = rates.shape[0]
        # pow2 padding (identity rows): one compile per bucket, and any
        # pow2 width divides the padded batch for the sharded program
        padded = max(width, 1 << max(0, n - 1).bit_length())
        if padded != n:
            pad = np.full((padded - n, rates.shape[1]), Q16_ONE,
                          dtype=np.uint32)
            rates = np.concatenate([rates, pad], axis=0)
        out = np.asarray(fn(rates))
        return out[:n]

    # -- routed entry point ----------------------------------------------

    def evaluate(self, rates: np.ndarray) -> np.ndarray:
        """[B, H] uint32 -> [B] uint32 composites, routed host/1-chip/
        N-chip by measured cost (or forced by ``routing``)."""
        import time as _t

        rates = np.ascontiguousarray(rates, dtype=np.uint32)
        n = int(rates.shape[0])
        if n == 0:
            return np.zeros((0,), dtype=np.uint32)
        if self.routing == "host":
            arm = "host"
        elif self.routing == "device":
            arm = self._model.arms[-1]
        else:
            arm = self._model.choose(n)
        t0 = _t.perf_counter()
        if arm == "host":
            out = self.evaluate_host(rates)
        else:
            out = self._evaluate_device(arm, rates)
        self._model.observe(arm, n, (_t.perf_counter() - t0) * 1000.0)
        with self._lock:
            self.rows_evaluated += n
            if arm == "host":
                self.host_batches += 1
            else:
                self.device_batches += 1
        return out

    def device_width(self) -> int:
        """Effective width of the widest device arm (builds it)."""
        return self._kernel(self._model.arms[-1])[1]

    def get_json(self) -> dict:
        with self._lock:
            widths = {a: w for a, (_f, w) in self._kernels.items()}
            counters = {
                "host_batches": self.host_batches,
                "device_batches": self.device_batches,
                "rows_evaluated": self.rows_evaluated,
            }
        return {
            "mesh": self.mesh,
            "routing": self.routing,
            "min_device_batch": self._model.min_device_nodes,
            "arm_widths": widths,
            **counters,
            "model": self._model.get_json(),
        }


def make_path_evaluator(mesh=None, min_device_batch: Optional[int] = None,
                        routing: Optional[str] = None) -> PathQualityEvaluator:
    """The ONE wiring for the path-quality evaluator (node, bench and
    smokes all construct the identical arrangement)."""
    return PathQualityEvaluator(
        mesh=mesh, min_device_batch=min_device_batch, routing=routing,
    )
