"""The crypto-plane backend seam: pluggable batched verifier/hasher.

This is the factory-registry pattern the reference uses for NodeStore
backends (/root/reference/src/ripple_core/nodestore/api/Factory.h:27-44,
Manager::make_Database), applied to the crypto hot path per the north
star: `signature_backend = cpu|tpu` in the node config selects which
implementation coalesced JobQueue-style verification batches run on.

- ``cpu``: per-signature verification via the host library (the libsodium
  role), threaded over the batch.
- ``tpu``: the batched JAX kernel (ops.ed25519_jax) — one device program
  over the whole batch.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Sequence

import numpy as np


@dataclass(frozen=True)
class VerifyRequest:
    public: bytes  # 32-byte Ed25519 public key
    signing_hash: bytes  # 32-byte message (prefixed SHA-512-half)
    signature: bytes  # 64-byte detached signature


class BatchVerifier:
    """Interface: verify a batch of Ed25519 signatures."""

    name = "abstract"

    def verify_batch(self, batch: Sequence[VerifyRequest]) -> np.ndarray:
        raise NotImplementedError


class BatchHasher:
    """Interface: batched SHA-512-half with 4-byte domain prefixes."""

    name = "abstract"

    def prefix_hash_batch(self, prefixes: Sequence[int], payloads: Sequence[bytes]) -> list[bytes]:
        raise NotImplementedError


_VERIFIERS: dict[str, Callable[..., BatchVerifier]] = {}
_HASHERS: dict[str, Callable[..., BatchHasher]] = {}


def register_verifier(name: str, factory: Callable[..., BatchVerifier]) -> None:
    _VERIFIERS[name] = factory


def register_hasher(name: str, factory: Callable[..., BatchHasher]) -> None:
    _HASHERS[name] = factory


def make_verifier(name: str, **kwargs) -> BatchVerifier:
    if name not in _VERIFIERS:
        raise KeyError(f"unknown signature backend {name!r}; have {sorted(_VERIFIERS)}")
    return _VERIFIERS[name](**kwargs)


def make_hasher(name: str, **kwargs) -> BatchHasher:
    if name not in _HASHERS:
        raise KeyError(f"unknown hash backend {name!r}; have {sorted(_HASHERS)}")
    return _HASHERS[name](**kwargs)


# --------------------------------------------------------------------------
# cpu backend


class CpuVerifier(BatchVerifier):
    """Host-library per-signature verification (the libsodium role of the
    reference: StellarPublicKey::verifySignature), threaded over the batch."""

    name = "cpu"

    _shared_pool: ThreadPoolExecutor | None = None

    def __init__(self, threads: int = 4):
        if threads > 1:
            if CpuVerifier._shared_pool is None:
                CpuVerifier._shared_pool = ThreadPoolExecutor(
                    max_workers=threads, thread_name_prefix="cpu-verify"
                )
            self._pool = CpuVerifier._shared_pool
        else:
            self._pool = None

    def verify_batch(self, batch: Sequence[VerifyRequest]) -> np.ndarray:
        from ..protocol.keys import verify_signature

        def one(req: VerifyRequest) -> bool:
            return verify_signature(req.public, req.signing_hash, req.signature)

        if self._pool is None or len(batch) < 64:
            return np.array([one(r) for r in batch], bool)
        return np.array(list(self._pool.map(one, batch)), bool)


class CpuHasher(BatchHasher):
    name = "cpu"

    def prefix_hash_batch(self, prefixes, payloads):
        from ..utils.hashes import prefix_hash

        return [prefix_hash(p, d) for p, d in zip(prefixes, payloads)]


# --------------------------------------------------------------------------
# tpu backend


class TpuVerifier(BatchVerifier):
    """Batched JAX Ed25519 kernel (ops.ed25519_jax.verify_kernel).

    Batches are padded to power-of-two sizes to bound XLA recompiles.
    """

    name = "tpu"

    def __init__(self, min_batch: int = 256, max_batch: int = 16384):
        self.min_batch = min_batch
        self.max_batch = max_batch

    @staticmethod
    def _pad_size(n: int, lo: int, hi: int) -> int:
        size = lo
        while size < n and size < hi:
            size *= 2
        return size

    def verify_batch(self, batch: Sequence[VerifyRequest]) -> np.ndarray:
        from ..ops.ed25519_jax import verify_stream

        starts = list(range(0, len(batch), self.max_batch))

        def chunks():
            for start in starts:
                chunk = batch[start : start + self.max_batch]
                size = self._pad_size(len(chunk), self.min_batch, self.max_batch)
                pad = size - len(chunk)
                yield (
                    [r.public for r in chunk] + [b"\x00" * 32] * pad,
                    [r.signing_hash for r in chunk] + [b""] * pad,
                    [r.signature for r in chunk] + [b"\x00" * 64] * pad,
                )

        out = np.zeros(len(batch), bool)
        # verify_stream double-buffers: host prep of chunk i+1 overlaps the
        # device execution of chunk i — the same pipeline bench.py measures
        for start, res in zip(starts, verify_stream(chunks())):
            n = min(self.max_batch, len(batch) - start)
            out[start : start + n] = res[:n]
        return out


class TpuHasher(BatchHasher):
    """Batched JAX SHA-512 (ops.sha512_jax), bucketed by block count."""

    name = "tpu"

    def prefix_hash_batch(self, prefixes, payloads):
        from ..ops.sha512_jax import padded_block_count, sha512_half_batch

        msgs = [p.to_bytes(4, "big") + d for p, d in zip(prefixes, payloads)]
        # bucket by padded block count to keep shapes static
        buckets: dict[int, list[int]] = {}
        for i, m in enumerate(msgs):
            buckets.setdefault(padded_block_count(len(m)), []).append(i)
        out: list[bytes | None] = [None] * len(msgs)
        for nb, idxs in buckets.items():
            digests = sha512_half_batch([msgs[i] for i in idxs])
            for i, d in zip(idxs, digests):
                out[i] = d
        return out  # type: ignore[return-value]


register_verifier("cpu", CpuVerifier)
register_verifier("tpu", TpuVerifier)
register_hasher("cpu", CpuHasher)
register_hasher("tpu", TpuHasher)


class CppHasher(BatchHasher):
    """Native batched SHA-512-half (native/src/sha512.cc) — one C call
    per batch, filling the reference's OpenSSL-hashing role for the host
    path when the device hasher isn't warranted."""

    name = "cpp"

    def __init__(self, **_):
        from ..native import Sha512Native

        self._impl = Sha512Native()

    def prefix_hash_batch(self, prefixes, payloads):
        return self._impl.prefix_hash_batch(prefixes, payloads)


# registered unconditionally: CppHasher.__init__ raises a clean error on
# a toolchain-less box, and the (one-time) native build cost lands only
# on callers that actually select the cpp backend — never at import
register_hasher("cpp", CppHasher)
