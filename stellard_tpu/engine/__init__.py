"""Transaction engine: typed transactors applying signed transactions to a
ledger through a LedgerEntrySet.

Reference scope: src/ripple_app/tx (TransactionEngine),
src/ripple_app/transactors (Transactor pipeline + per-type transactors).
"""

from .engine import TransactionEngine, TxParams
from .transactor import Transactor, make_transactor
from . import payment, trust, offers, account, inflation, change  # noqa: F401

__all__ = ["TransactionEngine", "TxParams", "Transactor", "make_transactor"]
