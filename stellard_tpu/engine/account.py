"""Account transactors: AccountSet, SetRegularKey, AccountMerge.

Reference: src/ripple_app/transactors/{SetAccount,SetRegularKey,
AccountMergeTransactor}.cpp.
"""

from __future__ import annotations

from ..protocol.formats import LedgerEntryType, TxType
from ..protocol.sfields import (
    sfBalance,
    sfClearFlag,
    sfDestination,
    sfDestinationTag,
    sfFlags,
    sfHighLimit,
    sfInflationDest,
    sfLowLimit,
    sfRegularKey,
    sfSetAuthKey,
    sfSetFlag,
    sfTransferRate,
)
from ..protocol.stamount import ACCOUNT_ZERO, STAmount
from ..protocol.ter import TER
from ..state import indexes
from .flags import (
    asfDisableMaster,
    asfRequireAuth,
    asfRequireDest,
    lsfDisableMaster,
    lsfHighAuth,
    lsfLowAuth,
    lsfRequireAuth,
    lsfRequireDestTag,
    tfAccountSetMask,
    tfOptionalAuth,
    tfOptionalDestTag,
    tfRequireAuth,
    tfRequireDestTag,
    tfUniversalMask,
)
from .transactor import Transactor, register_transactor
from .views import QUALITY_ONE, offer_delete, trust_delete



@register_transactor(TxType.ttACCOUNT_SET)
class AccountSetTransactor(Transactor):
    """reference: SetAccount.cpp"""

    def do_apply(self) -> TER:
        tx = self.tx
        flags = tx.flags
        set_flag = tx.obj.get(sfSetFlag, 0)
        clear_flag = tx.obj.get(sfClearFlag, 0)

        set_require_dest = bool(flags & tfRequireDestTag) or set_flag == asfRequireDest
        clear_require_dest = bool(flags & tfOptionalDestTag) or clear_flag == asfRequireDest
        set_require_auth = bool(flags & tfRequireAuth) or set_flag == asfRequireAuth
        clear_require_auth = bool(flags & tfOptionalAuth) or clear_flag == asfRequireAuth

        if flags & tfAccountSetMask:
            return TER.temINVALID_FLAG

        flags_in = self.account.get(sfFlags, 0)
        flags_out = flags_in

        if set_require_auth and clear_require_auth:
            return TER.temINVALID_FLAG
        if set_require_auth and not (flags_in & lsfRequireAuth):
            # only allowed while the owner directory is empty
            owner_dir = self.les.peek(indexes.owner_dir_index(self.account_id))
            if owner_dir is not None:
                from .engine import TxParams

                return (
                    TER.terOWNERS
                    if self.params & TxParams.RETRY
                    else TER.tecOWNERS
                )
            flags_out |= lsfRequireAuth
        if clear_require_auth and (flags_in & lsfRequireAuth):
            flags_out &= ~lsfRequireAuth

        if set_require_dest and clear_require_dest:
            return TER.temINVALID_FLAG
        if set_require_dest and not (flags_in & lsfRequireDestTag):
            flags_out |= lsfRequireDestTag
        if clear_require_dest and (flags_in & lsfRequireDestTag):
            flags_out &= ~lsfRequireDestTag

        if set_flag == asfDisableMaster and clear_flag == asfDisableMaster:
            return TER.temINVALID_FLAG
        if set_flag == asfDisableMaster and not (flags_in & lsfDisableMaster):
            if sfRegularKey not in self.account:
                return TER.tecNO_REGULAR_KEY
            flags_out |= lsfDisableMaster
        if clear_flag == asfDisableMaster and (flags_in & lsfDisableMaster):
            flags_out &= ~lsfDisableMaster

        # InflationDest (Stellar-specific; reference: SetAccount.cpp:127-148)
        if sfInflationDest in tx.obj:
            dest = tx.obj[sfInflationDest]
            if dest == ACCOUNT_ZERO:
                self.account.pop(sfInflationDest)
            else:
                if self.les.account_root(dest) is None:
                    return TER.tecNO_DST
                self.account[sfInflationDest] = dest

        if sfSetAuthKey in tx.obj:
            auth_key = tx.obj[sfSetAuthKey]
            if auth_key == ACCOUNT_ZERO:
                self.account.pop(sfSetAuthKey)
            else:
                self.account[sfSetAuthKey] = auth_key

        # TransferRate (reference: SetAccount.cpp:175-195)
        if sfTransferRate in tx.obj:
            rate = tx.obj[sfTransferRate]
            if not rate or rate == QUALITY_ONE:
                self.account.pop(sfTransferRate)
            elif rate > QUALITY_ONE:
                self.account[sfTransferRate] = rate
            else:
                return TER.temBAD_TRANSFER_RATE

        if flags_in != flags_out:
            self.account[sfFlags] = flags_out
        return TER.tesSUCCESS


@register_transactor(TxType.ttREGULAR_KEY_SET)
class SetRegularKeyTransactor(Transactor):
    """reference: SetRegularKey.cpp"""

    def do_apply(self) -> TER:
        if self.tx.flags & tfUniversalMask:
            return TER.temINVALID_FLAG
        if sfRegularKey in self.tx.obj:
            self.account[sfRegularKey] = self.tx.obj[sfRegularKey]
        else:
            if self.account.get(sfFlags, 0) & lsfDisableMaster:
                return TER.tecMASTER_DISABLED
            self.account.pop(sfRegularKey)
        return TER.tesSUCCESS


@register_transactor(TxType.ttACCOUNT_MERGE)
class AccountMergeTransactor(Transactor):
    """Stellar-specific: move all balances/IOUs to destination, delete the
    source account (reference: AccountMergeTransactor.cpp)."""

    def precheck_against_ledger(self) -> TER:
        # master signature only (reference: :48-54)
        if not self.sig_master:
            return TER.temBAD_AUTH_MASTER
        if sfDestination not in self.tx.obj:
            return TER.temDST_NEEDED
        dst_id = self.tx.obj[sfDestination]
        if dst_id == self.account_id:
            return TER.temDST_IS_SRC
        dst = self.les.account_root(dst_id)
        if dst is None:
            return TER.tecNO_DST
        if (dst.get(sfFlags, 0) & lsfRequireDestTag) and (
            sfDestinationTag not in self.tx.obj
        ):
            return TER.tefDST_TAG_NEEDED
        return TER.tesSUCCESS

    def do_apply(self) -> TER:
        dst_id = self.tx.obj[sfDestination]
        dst_idx = indexes.account_root_index(dst_id)
        dst = self.les.peek(dst_idx)
        if dst is None:
            return TER.tecNO_DST

        # transfer every trust-line balance (reference: :100-196)
        from ..protocol.sfields import sfLedgerEntryType

        owner_dir = indexes.owner_dir_index(self.account_id)
        lines = []
        offers = []
        for entry_idx in list(self.les.dir_entries(owner_dir)):
            sle = self.les.peek(entry_idx)
            if sle is None:
                continue
            t = sle.get(sfLedgerEntryType)
            if t == int(LedgerEntryType.ltRIPPLE_STATE):
                lines.append(entry_idx)
            elif t == int(LedgerEntryType.ltOFFER):
                offers.append(entry_idx)

        for line_idx in lines:
            line = self.les.peek(line_idx)
            low_limit = line[sfLowLimit]
            high_limit = line[sfHighLimit]
            low_id_is_me = low_limit.issuer == self.account_id
            peer_id = high_limit.issuer if low_id_is_me else low_limit.issuer
            currency = low_limit.currency
            bal = line[sfBalance]
            my_bal = bal if low_id_is_me else -bal  # my perspective

            if my_bal.signum() < 0:
                return TER.temBAD_AMOUNT
            if my_bal.signum() > 0:
                # move to destination's line with the same issuer (:133-178)
                dst_line_idx = indexes.ripple_state_index(dst_id, peer_id, currency)
                dst_line = self.les.peek(dst_line_idx)
                if dst_line is None:
                    return TER.terNO_AUTH
                # auth propagation: if the peer required auth on the source
                # line, the destination line must be authed too (:144-151)
                src_line = self.les.peek(line_idx)
                peer_high_on_src = peer_id > self.account_id
                peer_auth_flag = lsfHighAuth if peer_high_on_src else lsfLowAuth
                if src_line.get(sfFlags, 0) & peer_auth_flag:
                    peer_high_on_dst = peer_id > dst_id
                    dst_auth_flag = (
                        lsfHighAuth if peer_high_on_dst else lsfLowAuth
                    )
                    if not (dst_line.get(sfFlags, 0) & dst_auth_flag):
                        return TER.terNO_AUTH
                dst_high = dst_id > peer_id
                dst_bal = dst_line[sfBalance]
                final = dst_bal - my_bal if dst_high else dst_bal + my_bal
                limit = dst_line[sfHighLimit if dst_high else sfLowLimit]
                # limit check in the destination's perspective (:160-166)
                if (dst_high and final < -limit) or (
                    not dst_high and final > limit
                ):
                    return TER.terNO_AUTH
                dst_line[sfBalance] = final
                self.les.modify(dst_line_idx)

            low_id = self.account_id if low_id_is_me else peer_id
            high_id = peer_id if low_id_is_me else self.account_id
            ter = trust_delete(self.les, line_idx, low_id, high_id)
            if ter != TER.tesSUCCESS:
                return TER.tefINTERNAL

        # delete offers (reference: :212-227)
        for offer_idx in offers:
            ter = offer_delete(self.les, offer_idx)
            if ter != TER.tesSUCCESS:
                return TER.tefINTERNAL

        # move native balance, delete source account (reference: :199-231)
        move = self.source_balance
        self.account[sfBalance] = STAmount.from_drops(0)
        dst[sfBalance] = dst[sfBalance] + move
        self.les.modify(dst_idx)
        self.les.erase(indexes.account_root_index(self.account_id))
        return TER.tesSUCCESS
