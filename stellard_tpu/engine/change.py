"""Change pseudo-transactions: EnableAmendment, SetFee.

Reference: src/ripple_app/transactors/Change.cpp — only valid in a closing
ledger, source account zero, no fee, no signature; applies amendment and
fee-settings ledger entries.
"""

from __future__ import annotations

from ..protocol.formats import LedgerEntryType, TxType
from ..protocol.sfields import (
    sfAmendment,
    sfAmendments,
    sfBaseFee,
    sfReferenceFeeUnits,
    sfReserveBase,
    sfReserveIncrement,
)
from ..protocol.ter import TER
from ..protocol.stamount import ACCOUNT_ZERO
from ..state import indexes
from .transactor import Transactor, register_transactor


class _ChangeBase(Transactor):
    """Shared pseudo-tx pipeline overrides (reference: Change.cpp
    applyChange — skips account/seq/fee/sig machinery)."""

    def must_have_valid_account(self) -> bool:
        return False

    def pre_check(self) -> TER:
        from .engine import TxParams

        if self.params & TxParams.OPEN_LEDGER:
            return TER.temINVALID  # only in closing ledgers
        if self.tx.account != ACCOUNT_ZERO:
            return TER.temBAD_SRC_ACCOUNT
        self.account_id = self.tx.account
        return TER.tesSUCCESS

    def check_seq(self) -> TER:
        return TER.tesSUCCESS

    def pay_fee(self) -> TER:
        return TER.tesSUCCESS

    def check_sig(self) -> TER:
        return TER.tesSUCCESS

    def apply(self) -> TER:
        ter = self.pre_check()
        if ter != TER.tesSUCCESS:
            return ter
        return self.do_apply()


@register_transactor(TxType.ttAMENDMENT)
class EnableAmendmentTransactor(_ChangeBase):
    def do_apply(self) -> TER:
        """Append the amendment hash to the ltAMENDMENTS singleton
        (reference: Change.cpp applyAmendment)."""
        idx = indexes.amendment_index()
        sle = self.les.peek(idx)
        created = False
        if sle is None:
            sle = self.les.create(LedgerEntryType.ltAMENDMENTS, idx)
            sle[sfAmendments] = []
            created = True
        amendments = list(sle.get(sfAmendments, []))
        amendment = self.tx.obj[sfAmendment]
        if amendment in amendments:
            return TER.tefALREADY
        amendments.append(amendment)
        sle[sfAmendments] = amendments
        if not created:
            self.les.modify(idx)
        return TER.tesSUCCESS


@register_transactor(TxType.ttFEE)
class SetFeeTransactor(_ChangeBase):
    def do_apply(self) -> TER:
        """Write the ltFEE_SETTINGS singleton and update the ledger's fee
        schedule (reference: Change.cpp applyFee)."""
        idx = indexes.fee_index()
        sle = self.les.peek(idx)
        created = False
        if sle is None:
            sle = self.les.create(LedgerEntryType.ltFEE_SETTINGS, idx)
            created = True
        tx = self.tx.obj
        sle[sfBaseFee] = tx[sfBaseFee]
        sle[sfReferenceFeeUnits] = tx[sfReferenceFeeUnits]
        sle[sfReserveBase] = tx[sfReserveBase]
        sle[sfReserveIncrement] = tx[sfReserveIncrement]
        if not created:
            self.les.modify(idx)
        # fee-schedule switch is deferred to the engine's header_changes
        # application (post-invariants) like Inflation's header writes
        self.header_changes = {
            "base_fee": tx[sfBaseFee],
            "reference_fee_units": tx[sfReferenceFeeUnits],
            "reserve_base": tx[sfReserveBase],
            "reserve_increment": tx[sfReserveIncrement],
        }
        return TER.tesSUCCESS
