"""Speculative delta-replay for the ledger close.

Every accepted transaction used to run twice: a checks-only pass against
the open ledger at submit, then the full transactor again inside the
close window (LedgerConsensus::applyTransactions). PERF.md r5/r6 shows
that close apply pass is the dominant serial cost of a close. The
Block-STM answer (Gelashvili et al., 2022; Solana's Sealevel is the same
idea): execute speculatively once, record read/write sets, and at commit
time VALIDATE the reads instead of re-executing.

Shape here:

- submit time (``SpecState.speculate``, called by LedgerMaster after the
  open-ledger accept): run the tx once in CLOSE mode against a
  state/specview.SpecView — the parent state plus all earlier
  speculative writes, which is exactly the state the serial close would
  present when the canonical order matches the submission order. Record
  reads (key -> writer id), succ walks, the final write set, the built
  metadata, and both the raw transactor TER and the post-claim TER.

- close time (``CloseReplay.try_splice``, consulted by
  LedgerMaster._apply_transactions before each full apply): a record
  whose parent matches, whose entry reads all resolve to the same
  writers in the close's own writer map, and whose succ reads reproduce
  against the closing state map is SPLICED — recorded SLEs written
  straight into the ledger, metadata re-indexed and inserted, fee
  burned — with no transactor run. Any mismatch falls back to the full
  serial re-apply for that tx, which then poisons its written keys so
  dependent records also fall back. The serial path stays byte-identical
  and always available ([close] delta_replay=0).

Pass semantics mirror applyTransactions exactly: on non-final (RETRY)
passes a tec record defers (reports the raw tec, no state change, gets
requeued) because the serial path only claims fees on the final pass —
splicing the claim early would renumber TransactionIndex for every later
tx and break byte identity.

Transaction types that read or write ledger-header state the read set
cannot see (SetFee, EnableAmendment, Inflation) are never speculated,
and their close-time application marks the whole replay header-dirty so
every later record falls back too.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..protocol.formats import TxType
from ..protocol.sfields import sfTransactionIndex
from ..protocol.sttx import SerializedTransaction
from ..state.entryset import Action
from ..state.ledger import Ledger
from ..state.specview import PARENT, SpecView
from .engine import TransactionEngine, TxParams, _is_tec

__all__ = ["SpecState", "CloseReplay", "HEADER_TYPES"]

log = logging.getLogger("stellard.deltareplay")

# header-coupled types: excluded from speculation, and close-time
# application of one dirties the replay (fee/reserve schedule and
# inflation header state are invisible to the entry read set)
HEADER_TYPES = frozenset(
    {TxType.ttFEE, TxType.ttAMENDMENT, TxType.ttINFLATION}
)


class SpecRecord:
    __slots__ = (
        "raw_ter", "ter", "did_apply", "reads", "succs", "writes",
        "meta", "fee",
    )

    def __init__(self, raw_ter, ter, did_apply, reads, succs, writes,
                 meta, fee):
        self.raw_ter = raw_ter  # transactor outcome, pre fee-claim
        self.ter = ter  # final outcome (post claim reprocess)
        self.did_apply = did_apply
        self.reads = reads  # key -> writer id (txid or PARENT)
        self.succs = succs  # [(cursor, next key or None)]
        self.writes = writes  # [(key, SLE or None=delete)] in apply order
        self.meta = meta  # threaded meta STObject (tes/claim), else None
        self.fee = fee  # drops burned when did_apply


class SpecState:
    """Per-open-ledger speculation: the shared overlay view plus one
    record per open-accepted txid. Consumed by at most one close."""

    def __init__(self, ledger: Ledger):
        self.parent_hash = ledger.parent_hash
        self.view = SpecView(ledger)
        self.records: dict[bytes, SpecRecord] = {}
        self.disabled = False  # poisoned overlay -> all-fallback close

    def speculate(self, tx: SerializedTransaction) -> None:
        """Close-mode dry run of an open-accepted tx; records the outcome
        and folds its writes into the overlay for successors."""
        if self.disabled or tx.tx_type in HEADER_TYPES:
            return
        txid = tx.txid()
        self.view.begin_tx(txid)
        try:
            engine = TransactionEngine(self.view)
            ter, did_apply = engine.apply_transaction(tx, TxParams.NONE)
            reads, succs, writes = self.view.end_tx()
            meta = self.view.parsed_metas.pop(txid, None)
            if did_apply and meta is None:
                return  # commit tail didn't complete; keep no record
            self.records[txid] = SpecRecord(
                raw_ter=engine.last_raw_ter if engine.last_raw_ter
                is not None else ter,
                ter=ter,
                did_apply=did_apply,
                reads=reads,
                succs=succs,
                writes=writes,
                meta=meta,
                fee=tx.fee.mantissa if did_apply else 0,
            )
        except Exception:  # noqa: BLE001 — a half-applied overlay can't
            # be trusted for ANY later record; the close falls back whole
            log.exception(
                "speculation failed for %s; disabling delta replay for "
                "this ledger", txid.hex()[:16],
            )
            self.disabled = True


class CloseReplay:
    """One close's splice-or-fallback context over a SpecState."""

    def __init__(self, spec: Optional[SpecState], ledger: Ledger,
                 tracer=None):
        from ..node.tracer import get_tracer

        self.spec = spec
        self.ledger = ledger
        self.tracer = tracer if tracer is not None else get_tracer()
        # why the NEXT fallback runs (set by try_splice on each miss,
        # consumed by note_fallback's trace mark)
        self._fallback_reason = "not_attempted"
        self.parent_ok = (
            spec is not None
            and not spec.disabled
            and spec.parent_hash == ledger.parent_hash
        )
        # key -> provenance: txid for spliced writers, a unique non-txid
        # marker for fallback writers (their values may differ from the
        # speculative run, so they must never validate a recorded read)
        self.writers: dict[bytes, object] = {}
        self.header_dirty = False
        self._dirty_seq = 0
        # per-TX final classification (a retried tx may be attempted on
        # several passes — the last attempt's outcome wins, so
        # spliced+fallback always sums to the distinct tx count)
        self._class: dict[bytes, str] = {}
        self.invalidated = 0  # validation failures, counted PER ATTEMPT
        # (a retried record re-validates each pass; the churn is the
        # diagnostic, so attempts are the honest unit here)

    def try_splice(self, engine: TransactionEngine,
                   tx: SerializedTransaction, final: bool):
        """-> (ter, did_apply) when the recorded outcome stands in for
        this pass, else None (caller runs the full serial apply)."""
        if not self.parent_ok or self.header_dirty:
            self._fallback_reason = (
                "header_dirty" if self.header_dirty else "parent_mismatch"
            )
            return None
        txid = tx.txid()
        rec = self.spec.records.get(txid)
        if rec is None:
            self._fallback_reason = "no_record"
            return None
        writers = self.writers
        for k, wid in rec.reads.items():
            if writers.get(k, PARENT) != wid:
                self.invalidated += 1
                self._fallback_reason = "read_invalidated"
                return None
        st = self.ledger.state_map
        for cursor, tag in rec.succs:
            item = st.succ(cursor)
            if (item.tag if item is not None else None) != tag:
                self.invalidated += 1
                self._fallback_reason = "succ_invalidated"
                return None

        if not rec.did_apply:
            # no state effect either way; on non-final passes the serial
            # path reports the RAW tec (the claim only runs under NONE)
            self._class[txid] = "spliced"
            ter = rec.raw_ter if not final and _is_tec(rec.raw_ter) else rec.ter
            self._mark(txid, "spliced", int(ter))
            return ter, False
        if not final and _is_tec(rec.raw_ter):
            # defer the recorded fee claim to final-pass semantics, like
            # the serial path; the caller's tec branch requeues it
            self._class[txid] = "spliced"
            self._mark(txid, "spliced", int(rec.raw_ter))
            return rec.raw_ter, False

        ledger = self.ledger
        meta = rec.meta
        meta[sfTransactionIndex] = engine.tx_seq
        engine.tx_seq += 1
        ledger.add_transaction(tx.serialize(), meta.serialize())
        ledger.parsed_metas[txid] = meta
        ledger.tot_coins -= rec.fee
        ledger.fee_pool += rec.fee
        for k, sle in rec.writes:
            if sle is None:
                ledger.delete_entry(k)
            else:
                ledger.write_entry(k, sle)
            writers[k] = txid
        self._class[txid] = "spliced"
        self._mark(txid, "spliced", int(rec.ter))
        return rec.ter, True

    def _mark(self, txid: bytes, mode: str, ter: Optional[int] = None,
              reason: Optional[str] = None) -> None:
        """Per-tx splice/fallback trace mark (sampled): the close-stage
        node of the transaction's causal span tree, with the fallback
        reason when the record could not be spliced."""
        tr = self.tracer
        if not tr.enabled or not tr.sampled(txid):
            return
        attrs = {"mode": mode, "ledger_seq": self.ledger.seq}
        if ter is not None:
            attrs["ter"] = ter
        if reason is not None:
            attrs["reason"] = reason
        tr.instant("close.tx", "close", txid=txid, **attrs)

    def note_fallback(self, tx: SerializedTransaction,
                      engine: TransactionEngine, did_apply: bool) -> None:
        """A full serial apply ran: poison its written keys so records
        that read them can never splice against diverged values."""
        txid = tx.txid()
        self._class[txid] = "fallback"
        self._mark(txid, "fallback", reason=self._fallback_reason)
        self._fallback_reason = "not_attempted"
        if not did_apply:
            return
        if tx.tx_type in HEADER_TYPES:
            self.header_dirty = True
        les = engine.les
        if les is None:
            return
        self._dirty_seq += 1
        marker = ("fallback", self._dirty_seq)
        for idx, _sle, action in les.entries():
            if action != Action.CACHED:
                self.writers[idx] = marker

    def counts(self) -> dict:
        cls = self._class.values()
        return {
            "spliced": sum(1 for c in cls if c == "spliced"),
            "fallback": sum(1 for c in cls if c == "fallback"),
            "invalidated": self.invalidated,
            "parent_ok": self.parent_ok,
        }
