"""Speculative delta-replay for the ledger close.

Every accepted transaction used to run twice: a checks-only pass against
the open ledger at submit, then the full transactor again inside the
close window (LedgerConsensus::applyTransactions). PERF.md r5/r6 shows
that close apply pass is the dominant serial cost of a close. The
Block-STM answer (Gelashvili et al., 2022; Solana's Sealevel is the same
idea): execute speculatively once, record read/write sets, and at commit
time VALIDATE the reads instead of re-executing.

Shape here:

- submit time (``SpecState.speculate``, called by LedgerMaster after the
  open-ledger accept): run the tx once in CLOSE mode against a
  state/specview.SpecView — the parent state plus all earlier
  speculative writes, which is exactly the state the serial close would
  present when the canonical order matches the submission order. Record
  reads (key -> writer id), succ walks, the final write set, the built
  metadata, and both the raw transactor TER and the post-claim TER.

- close time (``CloseReplay.try_splice``, consulted by
  LedgerMaster._apply_transactions before each full apply): a record
  whose parent matches, whose entry reads all resolve to the same
  writers in the close's own writer map, and whose succ reads reproduce
  against the closing state map is SPLICED — recorded SLEs written
  straight into the ledger, metadata re-indexed and inserted, fee
  burned — with no transactor run. Any mismatch falls back to the full
  serial re-apply for that tx, which then poisons its written keys so
  dependent records also fall back. The serial path stays byte-identical
  and always available ([close] delta_replay=0).

Pass semantics mirror applyTransactions exactly: on non-final (RETRY)
passes a tec record defers (reports the raw tec, no state change, gets
requeued) because the serial path only claims fees on the final pass —
splicing the claim early would renumber TransactionIndex for every later
tx and break byte identity.

Transaction types that read or write ledger-header state the read set
cannot see (SetFee, EnableAmendment, Inflation) are never speculated,
and their close-time application marks the whole replay header-dirty so
every later record falls back too.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..protocol.formats import TxType
from ..protocol.sfields import sfTransactionIndex
from ..protocol.sttx import SerializedTransaction
from ..state.entryset import Action
from ..state.ledger import Ledger
from ..state.shamap import SHAMapItem, TNType
from ..state.specview import PARENT, SpecView
from .engine import TransactionEngine, TxParams, _is_tec

__all__ = ["SpecState", "CloseReplay", "HEADER_TYPES", "execute_record"]

log = logging.getLogger("stellard.deltareplay")

# header-coupled types: excluded from speculation, and close-time
# application of one dirties the replay (fee/reserve schedule and
# inflation header state are invisible to the entry read set)
HEADER_TYPES = frozenset(
    {TxType.ttFEE, TxType.ttAMENDMENT, TxType.ttINFLATION}
)


class SpecRecord:
    __slots__ = (
        "raw_ter", "ter", "did_apply", "reads", "succs", "write_items",
        "meta", "fee", "meta_blob", "meta_index_off", "net_deletes",
        "origin", "index",
    )

    def __init__(self, raw_ter, ter, did_apply, reads, succs, write_items,
                 meta, fee):
        self.raw_ter = raw_ter  # transactor outcome, pre fee-claim
        self.ter = ter  # final outcome (post claim reprocess)
        self.did_apply = did_apply
        self.reads = reads  # key -> writer id (txid or PARENT)
        self.succs = succs  # [(cursor, next key or None)]
        # [(key, SHAMapItem or None=delete)], compacted one entry per
        # key (last write wins), serialized at SPECULATION time — the
        # splice and the pre-seal building tree share these exact item
        # objects, so the close window re-serializes nothing
        self.write_items = write_items
        self.meta = meta  # threaded meta STObject (tes/claim), else None
        self.fee = fee  # drops burned when did_apply
        # speculation-time meta serialization: the ONLY close-dependent
        # meta bytes are the sfTransactionIndex u32, so the blob is
        # serialized once at submit with index 0 and the close patches
        # the 4 bytes at `meta_index_off` in place of a full re-serialize
        # (None when the two-serialization diff could not pin the span —
        # the splice then re-serializes, the always-correct path)
        self.meta_blob: Optional[bytes] = None
        self.meta_index_off = -1
        # keys whose compacted op is a DELETE but which this tx also
        # CREATED earlier in its own apply order: against a state that
        # never held the key, the pair nets to nothing (the serial
        # path's set_item/del_item). A delete key NOT in this set with
        # no prior state is a genuine missing-key delete and must keep
        # del_item's KeyError.
        self.net_deletes: frozenset = frozenset()
        # where the speculation ran: "submit" (open-ledger accept) or
        # "promote" (queue-aware deferred speculation after a TxQ
        # promotion) — splice marks carry it so the admission plane's
        # promote_spliced counters stay honest
        self.origin = "submit"
        # speculation index within the open window: the canonical fold
        # order for the pre-seal building tree and the Block-STM commit
        # order of the parallel executor (engine/specexec.py). None
        # until assigned by SpecState.speculate / the executor.
        self.index: Optional[int] = None


def execute_record(view, tx: SerializedTransaction,
                   origin: str = "submit") -> SpecRecord:
    """Run the close-mode engine over ``view`` (which must be inside a
    ``begin_tx`` bracket) and build the SpecRecord: compacted write set
    serialized NOW (the splice and the pre-seal building tree share
    these exact item objects), net-delete classification, and the
    metadata index-span pin.

    The ONE record builder: the serial submit-path speculation, the
    parallel executor's in-process workers, and its process workers all
    run this exact code, which is what makes their records byte-equal.
    Exceptions propagate — the caller decides whether a failure poisons
    the whole overlay (serial) or just retries the task (parallel)."""
    txid = tx.txid()
    engine = TransactionEngine(view)
    ter, did_apply = engine.apply_transaction(tx, TxParams.NONE)
    reads, succs, writes = view.end_tx()
    meta = view.parsed_metas.pop(txid, None)
    # compact + serialize the write set NOW (the submit window),
    # pinning each SLE as its item's parsed mirror — the close
    # splices these exact objects, moving the per-write
    # serialization cost out of the close window entirely
    compact: dict[bytes, Optional[object]] = {}
    ever_set: set[bytes] = set()
    for k, sle in writes:
        compact[k] = sle
        if sle is not None:
            ever_set.add(k)
    write_items = []
    net_deletes = set()
    for k, sle in compact.items():
        if sle is None:
            write_items.append((k, None))
            if k in ever_set:
                net_deletes.add(k)
        else:
            item = SHAMapItem(k, sle.serialize())
            item.parsed = sle
            write_items.append((k, item))
    rec = SpecRecord(
        raw_ter=engine.last_raw_ter if engine.last_raw_ter
        is not None else ter,
        ter=ter,
        did_apply=did_apply,
        reads=reads,
        succs=succs,
        write_items=write_items,
        meta=meta,
        fee=tx.fee.mantissa if did_apply else 0,
    )
    if meta is not None:
        # pin the index span: serialize with index 0 then 1 and
        # require the diff to be EXACTLY the u32's low byte —
        # anything else keeps the re-serialize slow path
        meta[sfTransactionIndex] = 0
        b0 = meta.serialize()
        meta[sfTransactionIndex] = 1
        b1 = meta.serialize()
        if len(b0) == len(b1):
            diffs = [i for i, (a, b) in enumerate(zip(b0, b1))
                     if a != b]
            if (len(diffs) == 1 and diffs[0] >= 3
                    and b0[diffs[0] - 3 : diffs[0] + 1]
                    == b"\x00\x00\x00\x00"
                    and b1[diffs[0]] == 1):
                rec.meta_blob = b0
                rec.meta_index_off = diffs[0] - 3
    rec.net_deletes = frozenset(net_deletes)
    rec.origin = origin
    return rec


class SpecState:
    """Per-open-ledger speculation: the shared overlay view plus one
    record per open-accepted txid. Consumed by at most one close."""

    def __init__(self, ledger: Ledger):
        self.parent_hash = ledger.parent_hash
        self.view = SpecView(ledger)
        self.records: dict[bytes, SpecRecord] = {}
        self.disabled = False  # poisoned overlay -> all-fallback close
        # incremental-seal building tree ([tree] incremental=1): the
        # parent state plus every speculated write folded in as it
        # records, hashed in background batches between closes so the
        # close's seal only hashes the residual. None = feature off or
        # fold failure (the close then runs the full seal — never forked)
        self.building = None
        self.absorbed: dict[bytes, object] = {}  # key -> item|None folded
        # speculation-index authority for this open window: the serial
        # path and the parallel executor's dispatch both allocate from
        # it (under the chain lock), so fold/commit order is one total
        # order however the records were produced
        self.next_index = 0
        self._folded_max = -1

    def alloc_index(self) -> int:
        """Next speculation index (caller holds the chain lock)."""
        i = self.next_index
        self.next_index += 1
        return i

    def attach_building(self, state_root, hash_batch) -> None:
        """Arm the pre-seal building tree over the parent state root."""
        from ..state.shamap import SHAMap, TNType

        kw = {"hash_batch": hash_batch} if hash_batch is not None else {}
        self.building = SHAMap(TNType.ACCOUNT_STATE, state_root, **kw)
        self.absorbed = {}

    def fold_building(self, rec: "SpecRecord") -> int:
        """Merge one record's write items into the building tree; -> ops
        folded (0 when the tree is unarmed or the record wrote nothing).
        Any fold failure disarms the building tree for this open window
        — the close simply runs its normal full seal.

        Ordering contract: folds must arrive in strictly increasing
        speculation-index order — the building tree is "parent state
        plus speculated writes IN ORDER", and an out-of-order fold
        (a parallel-scheduler bug) would silently bake a stale value
        into the pre-seal tree. That bug class must fail LOUDLY here,
        before the bulk merge, not surface as a close-time hash
        divergence."""
        if self.building is None or not rec.did_apply or not rec.write_items:
            return 0
        if rec.index is not None and rec.index <= self._folded_max:
            raise AssertionError(
                f"fold_building out of order: index {rec.index} after "
                f"{self._folded_max} — scheduler commit-order bug"
            )
        try:
            self.building.bulk_update(
                [it for _k, it in rec.write_items if it is not None],
                [k for k, it in rec.write_items if it is None],
                missing_ok=True,  # a tx creating+deleting one key
                # compacts to a bare delete; the building tree nets it
            )
        except Exception:  # noqa: BLE001 — never let pre-hashing break
            # the open window; the full seal remains the fallback
            log.exception("building-tree fold failed; disabling "
                          "incremental seal for this open ledger")
            self.building = None
            self.absorbed = {}
            return 0
        if rec.index is not None:
            self._folded_max = rec.index
        for k, it in rec.write_items:
            self.absorbed[k] = it
        return len(rec.write_items)

    def speculate(self, tx: SerializedTransaction, origin: str = "submit",
                  index: Optional[int] = None) -> Optional["SpecRecord"]:
        """Close-mode dry run of an open-accepted tx; records the outcome
        and folds its writes into the overlay for successors. `origin`
        is "submit" for the open-accept path and "promote" for the
        TxQ's deferred queue-aware speculation. `index` pins the
        speculation index (the parallel executor's serial-fallback path
        commits out-of-band and already holds the task's index); serial
        callers let it allocate. Returns the record that executed (also
        when it was not retained) so the executor's commit thread can
        ship its write set to process workers — serial callers ignore
        it."""
        if self.disabled or tx.tx_type in HEADER_TYPES:
            return None
        txid = tx.txid()
        self.view.begin_tx(txid)
        try:
            rec = execute_record(self.view, tx, origin)
            if rec.did_apply and rec.meta is None:
                return rec  # commit tail didn't complete; keep no record
            rec.index = self.alloc_index() if index is None else index
            self.records[txid] = rec
            return rec
        except Exception:  # noqa: BLE001 — a half-applied overlay can't
            # be trusted for ANY later record; the close falls back whole
            log.exception(
                "speculation failed for %s; disabling delta replay for "
                "this ledger", txid.hex()[:16],
            )
            self.disabled = True
            return None


class CloseReplay:
    """One close's splice-or-fallback context over a SpecState."""

    def __init__(self, spec: Optional[SpecState], ledger: Ledger,
                 tracer=None):
        from ..node.tracer import get_tracer

        self.spec = spec
        self.ledger = ledger
        self.tracer = tracer if tracer is not None else get_tracer()
        # why the NEXT fallback runs (set by try_splice on each miss,
        # consumed by note_fallback's trace mark)
        self._fallback_reason = "not_attempted"
        self.parent_ok = (
            spec is not None
            and not spec.disabled
            and spec.parent_hash == ledger.parent_hash
        )
        # key -> provenance: txid for spliced writers, a unique non-txid
        # marker for fallback writers (their values may differ from the
        # speculative run, so they must never validate a recorded read)
        self.writers: dict[bytes, object] = {}
        self.header_dirty = False
        self._dirty_seq = 0
        # per-TX final classification (a retried tx may be attempted on
        # several passes — the last attempt's outcome wins, so
        # spliced+fallback always sums to the distinct tx count)
        self._class: dict[bytes, str] = {}
        self.invalidated = 0  # validation failures, counted PER ATTEMPT
        # (a retried record re-validates each pass; the churn is the
        # diagnostic, so attempts are the honest unit here)
        # batched splice writes: spliced deltas accumulate here and land
        # through ONE sorted bulk merge (SHAMap.bulk_update) instead of a
        # per-key nibble walk per write — flushed before anything reads
        # the trees (a serial fallback apply, a succ validation, or the
        # end of the apply pass), so reads are always current
        self._pending_state: dict[bytes, Optional[SHAMapItem]] = {}
        self._pending_tx: list[SHAMapItem] = []
        self.bulk_merges = 0
        self.bulk_merged_keys = 0
        # incremental-seal adoption outcome (maybe_adopt_prehashed)
        self.seal_adopt = "off"
        self.seal_residual = 0

    def try_splice(self, engine: TransactionEngine,
                   tx: SerializedTransaction, final: bool):
        """-> (ter, did_apply) when the recorded outcome stands in for
        this pass, else None (caller runs the full serial apply)."""
        if not self.parent_ok or self.header_dirty:
            self._fallback_reason = (
                "header_dirty" if self.header_dirty else "parent_mismatch"
            )
            return None
        txid = tx.txid()
        rec = self.spec.records.get(txid)
        if rec is None:
            self._fallback_reason = "no_record"
            return None
        writers = self.writers
        for k, wid in rec.reads.items():
            if writers.get(k, PARENT) != wid:
                self.invalidated += 1
                self._fallback_reason = "read_invalidated"
                return None
        if rec.succs and self._pending_state:
            # succ cursors walk the REAL tree: pending spliced writes
            # must land before the range reads validate against it
            self._flush_state()
        st = self.ledger.state_map
        for cursor, tag in rec.succs:
            item = st.succ(cursor)
            if (item.tag if item is not None else None) != tag:
                self.invalidated += 1
                self._fallback_reason = "succ_invalidated"
                return None

        if not rec.did_apply:
            # no state effect either way; on non-final passes the serial
            # path reports the RAW tec (the claim only runs under NONE)
            self._class[txid] = "spliced"
            ter = rec.raw_ter if not final and _is_tec(rec.raw_ter) else rec.ter
            self._mark(txid, "spliced", int(ter))
            return ter, False
        if not final and _is_tec(rec.raw_ter):
            # defer the recorded fee claim to final-pass semantics, like
            # the serial path; the caller's tec branch requeues it
            self._class[txid] = "spliced"
            self._mark(txid, "spliced", int(rec.raw_ter))
            return rec.raw_ter, False

        ledger = self.ledger
        meta = rec.meta
        idx = engine.tx_seq
        meta[sfTransactionIndex] = idx
        engine.tx_seq += 1
        # meta bytes: patch the pinned index span of the speculation-time
        # serialization; re-serialize only when the span wasn't pinned
        if rec.meta_blob is not None:
            p = rec.meta_index_off
            mb = rec.meta_blob
            meta_bytes = mb[:p] + idx.to_bytes(4, "big") + mb[p + 4:]
        else:
            meta_bytes = meta.serialize()
        # tx-map insert rides the pending batch (Ledger.tx_item_data is
        # the one owner of the TX_MD item layout)
        self._pending_tx.append(
            SHAMapItem(txid, Ledger.tx_item_data(tx.serialize(), meta_bytes))
        )
        ledger.parsed_metas[txid] = meta
        ledger.tot_coins -= rec.fee
        ledger.fee_pool += rec.fee
        pending = self._pending_state
        for k, item in rec.write_items:
            if (item is None
                    and (pending.get(k) is not None
                         or k in rec.net_deletes)
                    and self.ledger.state_map.get(k) is None):
                # the key was created by this batch (an earlier splice)
                # or by this very tx, and the tree never saw it:
                # create-then-delete nets to NOTHING (the serial path's
                # set_item/del_item pair), not a bare delete
                pending.pop(k, None)
            else:
                pending[k] = item  # speculation-time item: no re-serialize
            writers[k] = txid
        self._class[txid] = "spliced"
        self._mark(txid, "spliced", int(rec.ter), origin=rec.origin)
        return rec.ter, True

    # -- batched tree merge ------------------------------------------------

    def _flush_state(self) -> None:
        pending = self._pending_state
        if not pending:
            return
        import time as _t

        t0 = _t.perf_counter()
        self.ledger.state_map.bulk_update(
            [it for it in pending.values() if it is not None],
            [k for k, it in pending.items() if it is None],
        )
        self.bulk_merges += 1
        self.bulk_merged_keys += len(pending)
        self.tracer.complete(
            "tree.bulk_merge", "close", t0, _t.perf_counter(),
            seq=self.ledger.seq, map="state", n=len(pending),
        )
        pending.clear()

    def _flush_tx(self) -> None:
        if not self._pending_tx:
            return
        import time as _t

        t0 = _t.perf_counter()
        self.ledger.tx_map.bulk_update(
            self._pending_tx, leaf_type=TNType.TX_MD
        )
        self.bulk_merges += 1
        self.bulk_merged_keys += len(self._pending_tx)
        self.tracer.complete(
            "tree.bulk_merge", "close", t0, _t.perf_counter(),
            seq=self.ledger.seq, map="tx", n=len(self._pending_tx),
        )
        self._pending_tx.clear()

    def flush_pending(self) -> None:
        """Land every queued spliced write in one sorted bulk merge per
        map. Called before any serial fallback apply (which reads the
        trees) and at the end of the apply passes."""
        self._flush_state()
        self._flush_tx()

    def maybe_adopt_prehashed(self) -> None:
        """Swap the close's state root for the pre-hashed building tree
        when they agree (incremental seal, [tree] incremental=1).

        The building tree is parent-state + all speculated writes,
        hashed in background batches during the open window. The close's
        final state map is parent-state + the close's ACTUAL write set —
        both canonical radix trees, so equality of the per-key final
        values implies byte-identical roots. This scans every key either
        side touched, corrects the (usually empty) residual through one
        bulk merge, and adopts the building root: the seal then hashes
        only the residual paths. Heavy divergence (mass fallbacks)
        rejects the swap — re-merging everything would cost more than
        the full seal it saves. Pure optimization: any failure keeps the
        normally-built tree and the full seal."""
        spec = self.spec
        if spec is None or not self.parent_ok or spec.building is None:
            self.seal_adopt = "unarmed"
            return
        try:
            building = spec.building
            final = self.ledger.state_map
            keys = set(spec.absorbed)
            keys.update(self.writers)
            sets, deletes = [], []
            for k in keys:
                cur = building.get(k)
                fin = final.get(k)
                if cur is fin:  # the splice/fold shared item object
                    continue
                if fin is None:
                    if cur is not None:
                        deletes.append(k)
                elif cur is None or cur.data != fin.data:
                    sets.append(fin)
            residual = len(sets) + len(deletes)
            if residual > max(64, len(keys) // 4):
                self.seal_adopt = "rejected"
                self.seal_residual = residual
                return
            if residual:
                building.bulk_update(sets, deletes)
            final.root = building.root
            self.seal_adopt = "adopted"
            self.seal_residual = residual
        except Exception:  # noqa: BLE001 — optimization only: the
            # normally-built tree + full seal is always correct
            log.exception("incremental-seal adoption failed; "
                          "falling back to the full seal")
            self.seal_adopt = "error"

    def _mark(self, txid: bytes, mode: str, ter: Optional[int] = None,
              reason: Optional[str] = None,
              origin: Optional[str] = None) -> None:
        """Per-tx splice/fallback trace mark (sampled): the close-stage
        node of the transaction's causal span tree, with the fallback
        reason when the record could not be spliced."""
        tr = self.tracer
        if not tr.enabled or not tr.sampled(txid):
            return
        attrs = {"mode": mode, "ledger_seq": self.ledger.seq}
        if ter is not None:
            attrs["ter"] = ter
        if reason is not None:
            attrs["reason"] = reason
        if origin is not None and origin != "submit":
            attrs["origin"] = origin
        tr.instant("close.tx", "close", txid=txid, **attrs)

    def note_fallback(self, tx: SerializedTransaction,
                      engine: TransactionEngine, did_apply: bool) -> None:
        """A full serial apply ran: poison its written keys so records
        that read them can never splice against diverged values."""
        txid = tx.txid()
        self._class[txid] = "fallback"
        self._mark(txid, "fallback", reason=self._fallback_reason)
        self._fallback_reason = "not_attempted"
        if not did_apply:
            return
        if tx.tx_type in HEADER_TYPES:
            self.header_dirty = True
        les = engine.les
        if les is None:
            return
        self._dirty_seq += 1
        marker = ("fallback", self._dirty_seq)
        for idx, _sle, action in les.entries():
            if action != Action.CACHED:
                self.writers[idx] = marker

    def classes(self) -> dict[bytes, str]:
        """Per-tx final splice/fallback classification — consumed by the
        admission plane's queue-aware-speculation counters."""
        return dict(self._class)

    def counts(self) -> dict:
        cls = self._class.values()
        return {
            "spliced": sum(1 for c in cls if c == "spliced"),
            "fallback": sum(1 for c in cls if c == "fallback"),
            "invalidated": self.invalidated,
            "parent_ok": self.parent_ok,
            "bulk_merges": self.bulk_merges,
            "bulk_merged_keys": self.bulk_merged_keys,
            "seal_adopt": self.seal_adopt,
            "seal_residual": self.seal_residual,
        }
