"""TransactionEngine: applies one transaction to a ledger.

Reference: src/ripple_app/tx/TransactionEngine.cpp:94-253 —
applyTransaction dispatches to a transactor, handles the tec
claim-fee-only reprocess, checks invariants, records the tx into the
ledger's tx map (open: blob only; closing: blob + metadata + fee burn).
"""

from __future__ import annotations

from enum import IntFlag

from ..protocol.formats import TxType
from ..protocol.sfields import sfBalance, sfSequence
from ..protocol.stamount import STAmount
from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from ..state import LedgerEntrySet, indexes
from ..state.ledger import Ledger

__all__ = ["TransactionEngine", "TxParams"]


class TxParams(IntFlag):
    """reference: TransactionEngineParams (TransactionEngine.h)"""

    NONE = 0
    OPEN_LEDGER = 0x10  # tapOPEN_LEDGER
    RETRY = 0x20  # tapRETRY
    ADMIN = 0x400  # tapADMIN
    NO_CHECK_SIGN = 0x01  # tapNO_CHECK_SIGN


# int mirrors of TxParams (enum & is slow in the apply hot path); derived
# from the enum so they can never drift from it
_OPEN_LEDGER_I = int(TxParams.OPEN_LEDGER)
_RETRY_I = int(TxParams.RETRY)


def _is_tec(ter: TER) -> bool:
    return 100 <= int(ter) < 300


class TransactionEngine:
    def __init__(self, ledger: Ledger):
        self.ledger = ledger
        self.les: LedgerEntrySet | None = None
        self.tx_seq = 0  # metadata TransactionIndex within the closing ledger
        # raw transactor outcome of the last apply, BEFORE the tec
        # claim-fee reprocess may replace it — the delta-replay close
        # needs it to mirror non-final-pass (RETRY) semantics exactly
        self.last_raw_ter: TER | None = None

    def apply_transaction(
        self, tx: SerializedTransaction, params: TxParams
    ) -> tuple[TER, bool]:
        """-> (TER, did_apply). reference: applyTransaction
        (TransactionEngine.cpp:94-253)."""
        from .transactor import make_transactor

        # plain int from here down: IntFlag.__and__ builds a new enum
        # member per test, which is measurable at flood rates; int &
        # IntFlag stays on the C fast path
        params = int(params)
        self.les = LedgerEntrySet(self.ledger)

        # pseudo-transactions (zero account, no fee/signature) only enter
        # through a consensus set; their own pre_check enforces the
        # closing-ledger + zero-account rules, but the required-field
        # template must still hold or do_apply would crash the close.
        # Client/peer intake paths call passes_local_checks themselves and
        # still reject pseudo-txs (reference: passesLocalChecks runs in
        # Transaction::checkCoherent, not TransactionEngine::applyTransaction).
        if tx.tx_type in (TxType.ttAMENDMENT, TxType.ttFEE):
            from ..protocol.formats import TX_FORMATS, validate_against

            fmt = TX_FORMATS.get(tx.tx_type)
            if fmt is None or validate_against(tx.obj, fmt):
                return TER.temINVALID, False
        else:
            ok, _why = tx.passes_local_checks()
            if not ok:
                return TER.temINVALID, False

        transactor = make_transactor(tx, params, self)
        if transactor is None:
            return TER.temUNKNOWN, False

        ter = transactor.apply()
        self.last_raw_ter = ter
        did_apply = False

        if ter == TER.tesSUCCESS:
            did_apply = True
        elif _is_tec(ter) and not (params & _RETRY_I):
            # claim only the fee (reference: TransactionEngine.cpp:146-185)
            self.les = LedgerEntrySet(self.ledger)
            idx = indexes.account_root_index(tx.account)
            acct = self.les.peek(idx)
            if acct is None:
                ter = TER.terNO_ACCOUNT
            else:
                t_seq, a_seq = tx.sequence, acct[sfSequence]
                if a_seq < t_seq:
                    ter = TER.terPRE_SEQ
                elif a_seq > t_seq:
                    ter = TER.tefPAST_SEQ
                else:
                    fee = tx.fee
                    balance = acct[sfBalance]
                    if balance < fee:
                        ter = TER.terINSUF_FEE_B
                    else:
                        acct[sfBalance] = balance - fee
                        acct[sfSequence] = t_seq + 1
                        self.les.modify(idx)
                        did_apply = True

        if did_apply:
            minted = getattr(transactor, "minted_coins", 0)
            if not self._check_invariants(tx, params, minted):
                return TER.tefINTERNAL, False
            blob = tx.serialize()
            if params & _OPEN_LEDGER_I:
                txid, added = self.ledger.add_open_transaction(blob)
                if not added:
                    return TER.tefALREADY, False
                # open ledger records the tx only; no state write
                # (the transactor returned before do_apply)
                self.ledger.note_open_tx(tx.account, tx.sequence)
            else:
                meta = self.les.calc_meta(ter, self.tx_seq, self.ledger.seq, tx.txid())
                self.tx_seq += 1
                self.ledger.record_transaction(blob, meta)
                # deferred header mutations (Inflation/SetFee), applied
                # only now that the invariant gate has passed
                hc = getattr(transactor, "header_changes", {})
                if hc and ter == TER.tesSUCCESS:
                    self.ledger.tot_coins += hc.get("tot_coins_delta", 0)
                    self.ledger.inflation_seq += hc.get("inflation_seq_delta", 0)
                    if "fee_pool" in hc:
                        self.ledger.fee_pool = hc["fee_pool"]
                    for k in ("base_fee", "reference_fee_units",
                              "reserve_base", "reserve_increment"):
                        if k in hc:
                            setattr(self.ledger, k, hc[k])
                # burn the fee (reference: destroyCoins)
                self.ledger.tot_coins -= tx.fee.mantissa
                self.ledger.fee_pool += tx.fee.mantissa
                self.les.apply()

        return ter, did_apply

    def _check_invariants(self, tx: SerializedTransaction, params: TxParams,
                          minted: int = 0) -> bool:
        """Native-coin conservation across the entry set: total STR balance
        change must equal minted coins minus the fee. The reference's
        checkInvariants is an empty stub (TransactionCheck.cpp:26-32); this
        enforces the conservation law it gestures at."""
        if params & _OPEN_LEDGER_I:
            return True
        from ..protocol.sfields import sfBalance as _bal
        from ..state.entryset import Action

        delta = 0
        for idx, sle, action in self.les.entries():
            cur = sle.get(_bal) if sle is not None else None
            e = self.les._entries[idx]
            old = e.orig.get(_bal) if e.orig is not None else None

            def drops(v):
                if v is None or not isinstance(v, STAmount) or not v.is_native:
                    return 0
                return -v.mantissa if v.negative else v.mantissa

            if action == Action.CREATED:
                delta += drops(cur)
            elif action == Action.DELETED:
                delta -= drops(old)
            elif action == Action.MODIFIED:
                delta += drops(cur) - drops(old)
        return delta == minted - tx.fee.mantissa
