"""Transaction and ledger-entry flag constants.

Reference: src/ripple_data/protocol/TxFlags.h:39-83 and
LedgerFormats.h:100-118. Exact values are protocol constants.
"""

# universal
tfFullyCanonicalSig = 0x80000000
tfUniversal = tfFullyCanonicalSig
tfUniversalMask = ~tfUniversal & 0xFFFFFFFF

# AccountSet
tfRequireDestTag = 0x00010000
tfOptionalDestTag = 0x00020000
tfRequireAuth = 0x00040000
tfOptionalAuth = 0x00080000
tfDisallowSTR = 0x00100000
tfAllowSTR = 0x00200000
tfAccountSetMask = ~(
    tfUniversal | tfRequireDestTag | tfOptionalDestTag | tfRequireAuth
    | tfOptionalAuth | tfDisallowSTR | tfAllowSTR
) & 0xFFFFFFFF

# AccountSet SetFlag/ClearFlag values
asfRequireDest = 1
asfRequireAuth = 2
asfDisableMaster = 4

# OfferCreate
tfPassive = 0x00010000
tfImmediateOrCancel = 0x00020000
tfFillOrKill = 0x00040000
tfSell = 0x00080000
tfOfferCreateMask = ~(
    tfUniversal | tfPassive | tfImmediateOrCancel | tfFillOrKill | tfSell
) & 0xFFFFFFFF

# Payment
tfNoRippleDirect = 0x00010000
tfPartialPayment = 0x00020000
tfLimitQuality = 0x00040000
tfPaymentMask = ~(
    tfUniversal | tfPartialPayment | tfLimitQuality | tfNoRippleDirect
) & 0xFFFFFFFF

# TrustSet
tfSetfAuth = 0x00010000
tfSetNoRipple = 0x00020000
tfClearNoRipple = 0x00040000
tfClearAuth = 0x00080000
tfTrustSetMask = ~(
    tfUniversal | tfSetfAuth | tfSetNoRipple | tfClearNoRipple | tfClearAuth
) & 0xFFFFFFFF

# AccountRoot ledger flags
lsfPasswordSpent = 0x00010000
lsfRequireDestTag = 0x00020000
lsfRequireAuth = 0x00040000
lsfDisallowSTR = 0x00080000
lsfDisableMaster = 0x00100000

# Offer ledger flags
lsfPassive = 0x00010000
lsfSell = 0x00020000

# RippleState ledger flags
lsfLowReserve = 0x00010000
lsfHighReserve = 0x00020000
lsfLowAuth = 0x00040000
lsfHighAuth = 0x00080000
lsfLowNoRipple = 0x00100000
lsfHighNoRipple = 0x00200000
