"""Inflation transactor (Stellar-specific).

Reference: src/ripple_app/transactors/InflationTransactor.cpp — weekly
dole: tally sfInflationDest votes weighted by voter balance (only voters
with > 1e9 drops, per the reference's SQL filter), pick up to 50 winners
above 1.5% of the vote (or top 50 if nobody crosses), and distribute
totCoins * 190721/1e9 (≈1% APR weekly) + the accumulated fee pool,
proportionally to votes. Constants at InflationTransactor.cpp:32-38.

The reference tallies via a SQL query over its Accounts mirror table; here
the tally walks the state SHAMap directly (one pass, no SQL dependency).
"""

from __future__ import annotations

from collections import defaultdict

from ..protocol.formats import TxType
from ..protocol.sfields import sfBalance, sfInflateSeq, sfInflationDest
from ..protocol.stobject import STObject
from ..protocol.ter import TER
from ..state import indexes
from .transactor import Transactor, register_transactor

INFLATION_FREQUENCY = 60 * 60 * 24 * 7  # seconds
INFLATION_RATE_TRILLIONTHS = 190_721_000
TRILLION = 1_000_000_000_000
INFLATION_WIN_MIN_TRILLIONTHS = 15_000_000_000  # 1.5%
INFLATION_NUM_WINNERS = 50
INFLATION_START_TIME = 1403900503 - 946684800  # seconds since 1/1/2000
MIN_VOTER_BALANCE = 1_000_000_000  # reference SQL: balance > 1000000000


@register_transactor(TxType.ttINFLATION)
class InflationTransactor(Transactor):
    def check_sig(self) -> TER:
        # anyone may submit inflation; no account authority needed
        # (reference: InflationTransactor::checkSig -> tesSUCCESS)
        return TER.tesSUCCESS

    def pay_fee(self) -> TER:
        # inflation transactions must carry no fee (reference: :63-72)
        if self.tx.fee.is_zero():
            return TER.tesSUCCESS
        return TER.temBAD_FEE

    def precheck_against_ledger(self) -> TER:
        """reference: :74-96 — right sequence, and it must be time."""
        seq = self.tx.obj[sfInflateSeq]
        if seq != self.engine.ledger.inflation_seq:
            return TER.telNOT_TIME
        close_time = self.engine.ledger.parent_close_time
        next_time = INFLATION_START_TIME + seq * INFLATION_FREQUENCY
        if close_time < next_time:
            return TER.telNOT_TIME
        return TER.tesSUCCESS

    def do_apply(self) -> TER:
        ledger = self.engine.ledger

        # 1. tally votes (balance-weighted, big accounts only)
        votes: dict[bytes, int] = defaultdict(int)
        for item in ledger.state_map.items():
            sle = STObject.from_bytes(item.data)
            dest = sle.get(sfInflationDest)
            if dest is None:
                continue
            bal = sle.get(sfBalance)
            if bal is None or not bal.is_native or bal.mantissa <= MIN_VOTER_BALANCE:
                continue
            votes[dest] += bal.mantissa

        if not votes:
            self.header_changes = {"inflation_seq_delta": 1, "fee_pool": 0}
            return TER.tesSUCCESS

        ranked = sorted(votes.items(), key=lambda kv: kv[1], reverse=True)
        min_win = ledger.tot_coins * INFLATION_WIN_MIN_TRILLIONTHS // TRILLION
        if ranked[0][1] <= min_win:
            min_win = 0  # nobody crossed: take the top 50 (reference :148-151)
        winners = [
            (dest, v)
            for dest, v in ranked[:INFLATION_NUM_WINNERS]
            if v > min_win or min_win == 0
        ][:INFLATION_NUM_WINNERS]
        total_voted = sum(v for _, v in winners)

        # 2. coinsToDole = totCoins * rate + feePool (reference :173-181)
        to_dole = (
            ledger.tot_coins * INFLATION_RATE_TRILLIONTHS // TRILLION
            + ledger.fee_pool
        )

        # 3. distribute proportionally (reference :185-215)
        minted = 0
        from ..protocol.stamount import STAmount

        for dest, v in winners:
            doled = to_dole * v // total_voted
            idx = indexes.account_root_index(dest)
            acct = self.les.peek(idx)
            if acct is None:
                continue  # vanished dest: skip (reference logs an error)
            acct[sfBalance] = acct[sfBalance] + STAmount.from_drops(doled)
            self.les.modify(idx)
            minted += doled

        # header mutations are deferred to the engine until after the
        # invariant gate passes (header_changes convention) so a
        # tefINTERNAL abort can't leave tot_coins/inflation_seq advanced
        # with no matching balance credits
        self.header_changes = {
            "tot_coins_delta": minted,
            "inflation_seq_delta": 1,
            "fee_pool": 0,
        }
        self.minted_coins = minted  # engine invariant hook
        return TER.tesSUCCESS
