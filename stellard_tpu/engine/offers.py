"""OfferCreate / OfferCancel transactors and order-book crossing.

Reference: src/ripple_app/transactors/{CreateOffer,CreateOfferDirect,
CancelOffer}.cpp plus the book machinery (src/ripple_app/book/{BookTip,
OfferStream,Taker,Quality}.h):

- an offer (TakerPays P, TakerGets G) rests in the book directory
  getBookBase(P, G) at quality getRate(G, P)  (quality = P/G, the price a
  future taker pays per unit received; lower = better; dir walk ascending
  = best first),
- creating an offer first CROSSES the reversed book base(G, P) as a taker
  with in=G, out=P (CreateOfferDirect.cpp:480 "Reverse as we are the
  taker"), consuming resting offers while their quality is within the
  taker's threshold (Taker::reject), limited by both sides' funds
  (Taker::fill) with issuer transfer fees,
- the remainder is placed at the ORIGINAL rate
  (CreateOfferDirect.cpp:616-617).
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from typing import Optional

from ..protocol.formats import LedgerEntryType, TxType
from ..protocol.sfields import (
    sfAccount,
    sfBookDirectory,
    sfBookNode,
    sfExchangeRate,
    sfExpiration,
    sfFlags,
    sfOfferSequence,
    sfOwnerCount,
    sfOwnerNode,
    sfRootIndex,
    sfSequence,
    sfTakerGets,
    sfTakerGetsCurrency,
    sfTakerGetsIssuer,
    sfTakerPays,
    sfTakerPaysCurrency,
    sfTakerPaysIssuer,
)
from ..protocol.stamount import ACCOUNT_ZERO, STAmount
from ..protocol.ter import TER
from ..state import indexes
from .flags import (
    lsfPassive,
    lsfRequireAuth,
    lsfSell,
    lsfHighAuth,
    lsfLowAuth,
    tfFillOrKill,
    tfImmediateOrCancel,
    tfOfferCreateMask,
    tfPassive,
    tfSell,
)
from .transactor import Transactor, register_transactor
from . import views

# a non-zero currency marker for rate arithmetic (reference CURRENCY_ONE)
CURRENCY_ONE = (1).to_bytes(20, "big")

# maximal 64-bit quality encoding: accepts any tip price (bridge legs)
PERMISSIVE_RATE = (1 << 64) - 1


def get_rate(offer_out: STAmount, offer_in: STAmount) -> int:
    """64-bit quality encoding of in/out
    (reference: STAmount::getRate, STAmount.cpp:1044-1067)."""
    if offer_out.is_zero():
        return 0
    try:
        r = STAmount.divide(offer_in, offer_out, CURRENCY_ONE, views.ACCOUNT_ONE)
    except (ZeroDivisionError, ValueError, OverflowError):
        return 0
    if r.is_zero():
        return 0
    return ((r.offset + 100) << 56) | r.mantissa


@dataclass
class Amounts:
    """A (in, out) pair flowing through an offer
    (reference: book/Amounts.h)."""

    i: STAmount
    o: STAmount


def _scale_to_out(a: Amounts, limit_out: STAmount) -> Amounts:
    """Clamp .o to limit_out keeping the ratio
    (reference: Quality::ceil_out)."""
    if a.o <= limit_out:
        return a
    new_in = STAmount.multiply(
        STAmount.divide(a.i, a.o, CURRENCY_ONE, views.ACCOUNT_ONE),
        limit_out,
        a.i.currency,
        a.i.issuer,
    )
    return Amounts(new_in, limit_out)


def _scale_to_in(a: Amounts, limit_in: STAmount) -> Amounts:
    """Clamp .i to limit_in keeping the ratio
    (reference: Quality::ceil_in)."""
    if a.i <= limit_in:
        return a
    new_out = STAmount.multiply(
        STAmount.divide(a.o, a.i, CURRENCY_ONE, views.ACCOUNT_ONE),
        limit_in,
        a.o.currency,
        a.o.issuer,
    )
    return Amounts(limit_in, new_out)


def cross_offers(
    les,
    taker_id: bytes,
    taker_pays_in: STAmount,  # what the taker pays into the book (in)
    taker_wants_out: STAmount,  # what the taker wants out
    sell: bool,
    passive: bool,
    parent_close_time: int,
    max_quality_levels: Optional[int] = None,
    threshold_rate: Optional[int] = None,
) -> tuple[TER, STAmount, STAmount]:
    """Cross the book base(in_currency, out_currency) as a taker; returns
    (TER, paid_in_total, got_out_total).

    reference: process_order/Taker loop (CreateOfferDirect.cpp:29-175,
    Taker.h:120-290). Consumed / unfunded / expired / self offers are
    deleted as encountered (BookTip::step deletes stepped-past tips).

    ``max_quality_levels`` bounds how many distinct price levels may be
    consumed — the auto-bridge uses 1 so it can re-compare the direct
    book against the two-leg composite after every level.
    ``threshold_rate`` overrides the worst-acceptable price (the bridge
    legs enforce the COMPOSITE price themselves, so a leg must not be
    capped by the in/out ratio of its bounding amounts).
    """
    book_base = indexes.book_base(
        taker_pays_in.currency, taker_pays_in.issuer,
        taker_wants_out.currency, taker_wants_out.issuer,
    )
    book_end = indexes.quality_next(book_base)
    if threshold_rate is not None:
        threshold = threshold_rate  # caller-enforced price cap
    else:
        threshold = get_rate(taker_wants_out, taker_pays_in)  # in/out price

    paid = STAmount.zero_like(taker_pays_in.currency, taker_pays_in.issuer)
    got = STAmount.zero_like(taker_wants_out.currency, taker_wants_out.issuer)
    if taker_pays_in.is_native:
        paid = STAmount.from_drops(0)
    if taker_wants_out.is_native:
        got = STAmount.from_drops(0)

    in_left = taker_pays_in
    out_left = taker_wants_out

    cursor = book_base
    levels_used = 0
    while True:
        # done? (reference: Taker::done)
        if sell:
            if in_left.signum() <= 0:
                break
        elif got >= taker_wants_out:
            break
        if views.account_funds(les, taker_id, in_left).signum() <= 0:
            break

        item = les.ledger.state_map.succ(cursor)
        if item is None or item.tag >= book_end:
            break
        dir_idx = item.tag
        cursor = dir_idx
        if les.peek(dir_idx) is None:
            continue  # directory deleted within this entry set

        quality = indexes.get_quality(dir_idx)
        # reject: quality worse than my threshold (passive: or equal)
        if quality > threshold or (passive and quality == threshold):
            break
        if max_quality_levels is not None:
            levels_used += 1
            if levels_used > max_quality_levels:
                break

        for offer_idx in list(les.dir_entries(dir_idx)):
            offer = les.peek(offer_idx)
            if offer is None:
                continue
            owner = offer[sfAccount]
            if owner == taker_id:
                # self-crossing offers are removed (reference :116-128)
                views.offer_delete(les, offer_idx)
                continue
            if (
                sfExpiration in offer
                and parent_close_time >= offer[sfExpiration]
            ):
                views.offer_delete(les, offer_idx)
                continue

            rest = Amounts(offer[sfTakerPays], offer[sfTakerGets])
            owner_funds = views.account_funds(les, owner, rest.o)
            if owner_funds.signum() <= 0:
                views.offer_delete(les, offer_idx)  # unfunded
                continue

            # limit by owner funds net of transfer fee (Taker::fill)
            owner_rate = views.ripple_transfer_rate(les, rest.o.issuer)
            if not rest.o.is_native and owner != rest.o.issuer and owner_rate != views.QUALITY_ONE:
                usable = STAmount.divide(
                    owner_funds,
                    STAmount.from_iou(CURRENCY_ONE, views.ACCOUNT_ONE,
                                      owner_rate, -9),
                    owner_funds.currency,
                    owner_funds.issuer,
                )
            else:
                usable = owner_funds
            flow = _scale_to_out(rest, usable)

            # limit by taker funds
            taker_funds = views.account_funds(les, taker_id, in_left)
            taker_rate = views.ripple_transfer_rate(les, in_left.issuer)
            if not in_left.is_native and taker_id != in_left.issuer and taker_rate != views.QUALITY_ONE:
                t_usable = STAmount.divide(
                    taker_funds,
                    STAmount.from_iou(CURRENCY_ONE, views.ACCOUNT_ONE,
                                      taker_rate, -9),
                    taker_funds.currency,
                    taker_funds.issuer,
                )
            else:
                t_usable = taker_funds
            flow = _scale_to_in(flow, t_usable)
            # in sell mode, also cap by remaining input
            flow = _scale_to_in(flow, in_left)
            if not sell:
                flow = _scale_to_out(flow, out_left)

            if flow.i.signum() <= 0 or flow.o.signum() <= 0:
                break

            consumed = flow.o >= rest.o

            # reduce the resting offer (Taker::process)
            offer[sfTakerPays] = rest.i - flow.i
            offer[sfTakerGets] = rest.o - flow.o
            les.modify(offer_idx)

            # owner pays the taker, taker pays the owner
            ter = views.account_send(les, owner, taker_id, flow.o)
            if ter != TER.tesSUCCESS:
                return TER.tecFAILED_PROCESSING, paid, got
            ter = views.account_send(les, taker_id, owner, flow.i)
            if ter != TER.tesSUCCESS:
                return TER.tecFAILED_PROCESSING, paid, got

            paid = paid + flow.i
            got = got + flow.o
            in_left = in_left - flow.i
            if not sell:
                out_left = out_left - flow.o

            if consumed:
                views.offer_delete(les, offer_idx)

            if sell:
                if in_left.signum() <= 0:
                    break
            elif got >= taker_wants_out:
                break

    return TER.tesSUCCESS, paid, got


# --------------------------------------------------------------------------
# auto-bridging (IOU/IOU offers crossing through the two STR books)
#
# The reference planned this seam (transactors/CreateOffer.cpp:21
# "Autobridging is only in effect when an offer does not involve STR")
# but its CreateOfferBridged transactor is an empty placeholder and it
# always falls back to the direct book. Here the bridge is real: each
# step compares the direct tip price against the composite of the
# IN->STR and STR->OUT tips and consumes one price level from the
# cheaper source, which is the modern FlowCross behavior.


def _exact_price(pay: STAmount, get: STAmount) -> Fraction:
    """in-per-out as an exact rational (lower = cheaper for the taker)."""
    p_m, p_off = pay.mantissa, (0 if pay.is_native else pay.offset)
    g_m, g_off = get.mantissa, (0 if get.is_native else get.offset)
    if g_m <= 0:
        return Fraction(0)
    num, den = p_m, g_m
    e = p_off - g_off
    if e >= 0:
        num *= 10**e
    else:
        den *= 10 ** (-e)
    return Fraction(num, den)


def _tip_info(
    les, taker_id: bytes, want_in: STAmount, want_out: STAmount,
    parent_close_time: int,
):
    """Peek the best live, funded, non-self tip of a book WITHOUT mutating:
    -> (price Fraction in-per-out, in_capacity, out_capacity) or None.
    Mirrors the skip rules of the consuming loop (unfunded / expired /
    self offers are ignored here, deleted there)."""
    base = indexes.book_base(
        want_in.currency, want_in.issuer, want_out.currency, want_out.issuer
    )
    end = indexes.quality_next(base)
    cursor = base
    while True:
        item = les.ledger.state_map.succ(cursor)
        if item is None or item.tag >= end:
            return None
        dir_idx = item.tag
        cursor = dir_idx
        if les.peek(dir_idx) is None:
            continue
        for offer_idx in les.dir_entries(dir_idx):
            offer = les.peek(offer_idx)
            if offer is None:
                continue
            if offer[sfAccount] == taker_id:
                continue
            if (
                sfExpiration in offer
                and parent_close_time >= offer[sfExpiration]
            ):
                continue
            rest = Amounts(offer[sfTakerPays], offer[sfTakerGets])
            funds = views.account_funds(les, offer[sfAccount], rest.o)
            if funds.signum() <= 0:
                continue
            flow = _scale_to_out(rest, funds)
            if flow.i.signum() <= 0 or flow.o.signum() <= 0:
                continue
            return (_exact_price(flow.i, flow.o), flow.i, flow.o)


def cross_offers_auto_bridged(
    les,
    taker_id: bytes,
    taker_pays_in: STAmount,  # IOU the taker pays
    taker_wants_out: STAmount,  # IOU the taker wants
    sell: bool,
    passive: bool,
    parent_close_time: int,
    max_steps: int = 64,
) -> tuple[TER, STAmount, STAmount]:
    """Best-execution crossing for an IOU/IOU taker over three books:
    direct IN->OUT, plus the IN->STR / STR->OUT bridge."""
    threshold = _exact_price(taker_pays_in, taker_wants_out)
    # 64-bit encoding of the taker's ORIGINAL limit: sub-steps must use
    # this, not a limit recomputed from the partially-consumed remainders
    # (in sell mode out_left never shrinks, so a recomputed in/out ratio
    # would tighten below the taker's actual limit and refuse good fills)
    threshold_enc = get_rate(taker_wants_out, taker_pays_in)
    xrp_zero = STAmount.from_drops(0)
    paid = STAmount.zero_like(taker_pays_in.currency, taker_pays_in.issuer)
    got = STAmount.zero_like(taker_wants_out.currency, taker_wants_out.issuer)
    in_left = taker_pays_in
    out_left = taker_wants_out

    for _ in range(max_steps):
        if sell:
            if in_left.signum() <= 0:
                break
        elif got >= taker_wants_out:
            break
        if views.account_funds(les, taker_id, in_left).signum() <= 0:
            break

        tip_d = _tip_info(les, taker_id, in_left, out_left, parent_close_time)
        tip_1 = _tip_info(les, taker_id, in_left, xrp_zero, parent_close_time)
        tip_2 = _tip_info(les, taker_id, xrp_zero, out_left, parent_close_time)
        price_d = tip_d[0] if tip_d else None
        price_b = tip_1[0] * tip_2[0] if (tip_1 and tip_2) else None

        def acceptable(p: Optional[Fraction]) -> bool:
            if p is None or p <= 0:
                return False
            return p < threshold or (p == threshold and not passive)

        use_direct = acceptable(price_d) and (
            not acceptable(price_b) or price_d <= price_b
        )
        use_bridge = acceptable(price_b) and not use_direct
        if not use_direct and not use_bridge:
            break

        if use_direct:
            ter, p, g = cross_offers(
                les, taker_id, in_left, out_left, sell, passive,
                parent_close_time, max_quality_levels=1,
                threshold_rate=threshold_enc,
            )
            if ter != TER.tesSUCCESS:
                return ter, paid, got
            if p.signum() <= 0 and g.signum() <= 0:
                # a stale level (all offers unfunded/expired/self) was
                # cleaned out with zero fill; re-peek — the funded tip
                # _tip_info saw sits one level deeper (max_steps bounds us)
                continue
            paid = paid + p
            got = got + g
            in_left = in_left - p
            if not sell:
                out_left = out_left - g
            continue

        # bridge step: one price level on each leg, synchronized through
        # an STR amount both legs can move
        _p1, _i1, x_out = tip_1  # leg1 can sell up to x_out STR
        _p2, x_in, _o2 = tip_2  # leg2 can absorb up to x_in STR
        x_step = min(x_out, x_in)
        if not sell:
            # don't buy more STR than the remaining OUT needs at leg2's
            # price (ceil to a whole drop so the target stays reachable)
            need = out_left
            frac = tip_2[0] * Fraction(need.mantissa) * Fraction(10) ** (
                0 if need.is_native else need.offset
            )
            x_need = STAmount.from_drops(
                int(frac) + (0 if frac.denominator == 1 else 1)
            )
            if x_need < x_step:
                x_step = x_need
        if x_step.signum() <= 0:
            break
        # leg1: buy x_step STR with IN (price capped by the composite
        # acceptance above, not by the in_left/x_step ratio)
        ter, p_a, g_x = cross_offers(
            les, taker_id, in_left, x_step, False, passive,
            parent_close_time, max_quality_levels=1,
            threshold_rate=PERMISSIVE_RATE,
        )
        if ter != TER.tesSUCCESS:
            return ter, paid, got
        if g_x.signum() <= 0:
            continue  # stale leg1 level cleaned; re-peek
        # leg2: spend exactly the STR from leg1 for OUT (or up to the
        # remaining OUT target when buying)
        ter, p_x, g_b = cross_offers(
            les, taker_id, g_x,
            out_left if not sell else STAmount.zero_like(
                taker_wants_out.currency, taker_wants_out.issuer
            ),
            True, passive, parent_close_time, max_quality_levels=1,
            threshold_rate=PERMISSIVE_RATE,
        )
        if ter != TER.tesSUCCESS:
            return ter, paid, got
        if g_b.signum() <= 0:
            continue  # stale leg2 level cleaned; leg1's STR stays banked
        paid = paid + p_a
        got = got + g_b
        in_left = in_left - p_a
        if not sell:
            out_left = out_left - g_b

    return TER.tesSUCCESS, paid, got


@register_transactor(TxType.ttOFFER_CREATE)
class OfferCreateTransactor(Transactor):
    """reference: CreateOfferDirect.cpp DirectOfferCreateTransactor"""

    def do_apply(self) -> TER:
        tx = self.tx
        flags = tx.flags
        passive = bool(flags & tfPassive)
        ioc = bool(flags & tfImmediateOrCancel)
        fok = bool(flags & tfFillOrKill)
        sell = bool(flags & tfSell)

        taker_pays: STAmount = tx.obj[sfTakerPays]
        taker_gets: STAmount = tx.obj[sfTakerGets]

        if flags & tfOfferCreateMask:
            return TER.temINVALID_FLAG
        if ioc and fok:
            return TER.temINVALID_FLAG
        if taker_pays.is_native and taker_gets.is_native:
            return TER.temBAD_OFFER  # STR for STR
        if taker_pays.signum() <= 0 or taker_gets.signum() <= 0:
            return TER.temBAD_OFFER
        if taker_pays.currency == taker_gets.currency and (
            taker_pays.issuer == taker_gets.issuer
        ):
            return TER.temREDUNDANT
        has_expiration = sfExpiration in tx.obj
        if has_expiration and not tx.obj[sfExpiration]:
            return TER.temBAD_EXPIRATION

        sequence = tx.sequence
        offer_idx = indexes.offer_index(self.account_id, sequence)
        rate = get_rate(taker_gets, taker_pays)  # original placement rate

        # cancel companion offer (reference: :386-402)
        if sfOfferSequence in tx.obj:
            cancel_seq = tx.obj[sfOfferSequence]
            if cancel_seq >= sequence:
                return TER.temBAD_SEQUENCE
            cancel_idx = indexes.offer_index(self.account_id, cancel_seq)
            if self.les.peek(cancel_idx) is not None:
                views.offer_delete(self.les, cancel_idx)

        # expired: done, nothing placed (reference: :404-411)
        if has_expiration and (
            self.engine.ledger.parent_close_time >= tx.obj[sfExpiration]
        ):
            return TER.tesSUCCESS

        # must be authorized to hold what we will receive (reference: :413-464)
        if not taker_pays.is_native:
            issuer = self.les.account_root(taker_pays.issuer)
            if issuer is None:
                return TER.tecNO_ISSUER
            if issuer.get(sfFlags, 0) & lsfRequireAuth:
                line = self.les.peek(indexes.ripple_state_index(
                    self.account_id, taker_pays.issuer, taker_pays.currency
                ))
                if line is None:
                    return TER.tecNO_LINE
                my_high = self.account_id > taker_pays.issuer
                auth_flag = lsfHighAuth if my_high else lsfLowAuth
                if not (line.get(sfFlags, 0) & auth_flag):
                    return TER.tecNO_AUTH
        if views.account_funds(self.les, self.account_id, taker_gets).signum() <= 0:
            return TER.tecUNFUNDED_OFFER

        # cross the reversed book (reference: :469-515); IOU/IOU offers
        # also auto-bridge through the two STR books (the seam the
        # reference left unimplemented at CreateOffer.cpp:21)
        crosser = (
            cross_offers_auto_bridged
            if not taker_pays.is_native and not taker_gets.is_native
            else cross_offers
        )
        ter, paid, got = crosser(
            self.les,
            self.account_id,
            taker_gets,  # we pay with what we give
            taker_pays,  # we want what our offer asks
            sell=sell,
            passive=passive,
            parent_close_time=self.engine.ledger.parent_close_time,
        )
        if ter != TER.tesSUCCESS:
            return ter
        taker_pays = taker_pays - got
        taker_gets = taker_gets - paid

        if fok and (taker_pays.signum() > 0 or taker_gets.signum() > 0):
            # unfilled fill-or-kill: the reference restores a checkpoint
            # view with only the fee paid (:541-546); returning a tec makes
            # the engine's claim-fee-only reprocess do exactly that
            return TER.tecFAILED_PROCESSING

        if (
            taker_pays.signum() <= 0
            or taker_gets.signum() <= 0
            or ioc
            or views.account_funds(
                self.les, self.account_id, taker_gets
            ).signum() <= 0
        ):
            return TER.tesSUCCESS  # fully crossed / IoC / now unfunded

        # reserve check (reference: :552-580)
        owner_count = self.account.get(sfOwnerCount, 0)
        if self.prior_balance.mantissa < self.engine.ledger.reserve(owner_count + 1):
            if paid.is_zero() and got.is_zero():
                return TER.tecINSUF_RESERVE_OFFER
            return TER.tesSUCCESS  # partially crossed; remainder dropped

        # place the remainder (reference: :582-660)
        offer = self.les.create(LedgerEntryType.ltOFFER, offer_idx)
        offer[sfAccount] = self.account_id
        offer[sfSequence] = sequence
        offer[sfTakerPays] = taker_pays
        offer[sfTakerGets] = taker_gets
        if has_expiration:
            offer[sfExpiration] = tx.obj[sfExpiration]
        offer_flags = 0
        if passive:
            offer_flags |= lsfPassive
        if sell:
            offer_flags |= lsfSell
        if offer_flags:
            offer[sfFlags] = offer_flags

        ter, owner_node = self.les.dir_add(
            indexes.owner_dir_index(self.account_id), offer_idx
        )
        if ter != TER.tesSUCCESS:
            return ter
        self.les.adjust_owner_count(self.account_id, 1)

        book_root = indexes.quality_index(
            indexes.book_base(
                taker_pays.currency, taker_pays.issuer,
                taker_gets.currency, taker_gets.issuer,
            ),
            rate,
        )

        def describe_book_dir(dir_sle, is_root):
            # reference: Ledger::qualityDirDescriber
            dir_sle[sfExchangeRate] = rate
            dir_sle[sfTakerPaysCurrency] = taker_pays.currency
            dir_sle[sfTakerPaysIssuer] = taker_pays.issuer
            dir_sle[sfTakerGetsCurrency] = taker_gets.currency
            dir_sle[sfTakerGetsIssuer] = taker_gets.issuer

        ter, book_node = self.les.dir_add(book_root, offer_idx, describe_book_dir)
        if ter != TER.tesSUCCESS:
            return ter
        offer[sfOwnerNode] = owner_node
        offer[sfBookDirectory] = book_root
        offer[sfBookNode] = book_node
        return TER.tesSUCCESS


@register_transactor(TxType.ttOFFER_CANCEL)
class OfferCancelTransactor(Transactor):
    """reference: CancelOffer.cpp"""

    def do_apply(self) -> TER:
        offer_seq = self.tx.obj[sfOfferSequence]
        if not offer_seq or offer_seq >= self.tx.sequence:
            return TER.temBAD_SEQUENCE
        offer_idx = indexes.offer_index(self.account_id, offer_seq)
        if self.les.peek(offer_idx) is not None:
            return views.offer_delete(self.les, offer_idx)
        return TER.tesSUCCESS  # not found: not an error
