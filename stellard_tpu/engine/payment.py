"""Payment transactor.

Reference: src/ripple_app/transactors/Payment.cpp (299 LoC) — malformed
checks (:55-140), destination-account creation with reserve minimum
(:141-180), direct STR transfer with reserve floor (:250-280), and
ripple/IOU payments via RippleCalc (:185-248).

IOU scope in this stage: direct rippling through the default path —
sender↔issuer↔receiver (rippleSend semantics). The generalized multi-hop
RippleCalc path engine arrives with the paths subsystem and plugs in at
the same seam (`_ripple_payment`).
"""

from __future__ import annotations

from ..protocol.formats import LedgerEntryType, TxType
from ..protocol.sfields import (
    sfAccount,
    sfAmount,
    sfBalance,
    sfDestination,
    sfDestinationTag,
    sfFlags,
    sfOwnerCount,
    sfPaths,
    sfSendMax,
    sfSequence,
)
from ..protocol.stamount import ACCOUNT_ZERO, STAmount
from ..protocol.ter import TER
from ..state import indexes
from .flags import (
    lsfRequireDestTag,
    tfLimitQuality,
    tfNoRippleDirect,
    tfPartialPayment,
    tfPaymentMask,
)
from .transactor import Transactor, register_transactor
from . import views



@register_transactor(TxType.ttPAYMENT)
class PaymentTransactor(Transactor):
    def do_apply(self) -> TER:
        tx = self.tx
        flags = tx.flags
        dst_id = tx.obj[sfDestination]
        dst_amount: STAmount = tx.obj[sfAmount]
        has_max = sfSendMax in tx.obj
        has_paths = sfPaths in tx.obj and len(tx.obj[sfPaths]) > 0
        if has_max:
            max_amount = tx.obj[sfSendMax]
        elif dst_amount.is_native:
            max_amount = dst_amount
        else:
            max_amount = STAmount.from_iou(
                dst_amount.currency, self.account_id,
                dst_amount.mantissa, dst_amount.offset, dst_amount.negative,
            )
        str_direct = max_amount.is_native and dst_amount.is_native

        # malformed checks (reference: Payment.cpp:55-140)
        if flags & tfPaymentMask:
            return TER.temINVALID_FLAG
        if not dst_id or dst_id == ACCOUNT_ZERO:
            return TER.temDST_NEEDED
        if has_max and max_amount.signum() <= 0:
            return TER.temBAD_AMOUNT
        if dst_amount.signum() <= 0:
            return TER.temBAD_AMOUNT
        if (
            self.account_id == dst_id
            and max_amount.currency == dst_amount.currency
            and not has_paths
        ):
            return TER.temREDUNDANT
        if has_max and max_amount == dst_amount:
            return TER.temREDUNDANT_SEND_MAX
        if str_direct and has_max:
            return TER.temBAD_SEND_STR_MAX
        if str_direct and has_paths:
            return TER.temBAD_SEND_STR_PATHS
        if str_direct and (flags & tfLimitQuality):
            return TER.temBAD_SEND_STR_LIMIT
        if str_direct and (flags & tfNoRippleDirect):
            return TER.temBAD_SEND_STR_NO_DIRECT

        dst_idx = indexes.account_root_index(dst_id)
        dst = self.les.peek(dst_idx)
        if dst is None:
            # destination does not exist (reference: Payment.cpp:141-180)
            if not dst_amount.is_native:
                return TER.tecNO_DST
            if dst_amount.mantissa < self.engine.ledger.reserve(0):
                return TER.tecNO_DST_INSUF_STR
            dst = self.les.create(LedgerEntryType.ltACCOUNT_ROOT, dst_idx)
            dst[sfAccount] = dst_id
            dst[sfSequence] = 1
            dst[sfBalance] = STAmount.from_drops(0)
        else:
            if (dst.get(sfFlags, 0) & lsfRequireDestTag) and (
                sfDestinationTag not in tx.obj
            ):
                return TER.tefDST_TAG_NEEDED
            self.les.modify(dst_idx)

        if has_paths or has_max or not dst_amount.is_native:
            return self._ripple_payment(dst_id, dst_amount, max_amount, flags)

        # direct STR (reference: Payment.cpp:250-280)
        owner_count = self.account.get(sfOwnerCount, 0)
        reserve = self.engine.ledger.reserve(owner_count)
        need = dst_amount + STAmount.from_drops(
            max(reserve, self.tx.fee.mantissa)
        )
        if self.prior_balance < need:
            return TER.tecUNFUNDED_PAYMENT
        self.account[sfBalance] = self.source_balance - dst_amount
        dst[sfBalance] = dst[sfBalance] + dst_amount
        return TER.tesSUCCESS

    def _ripple_payment(self, dst_id: bytes, dst_amount: STAmount,
                        max_amount: STAmount, flags: int) -> TER:
        """IOU / cross-currency delivery. Explicit paths and currency
        conversions run through the flow engine (paths.flow — the
        RippleCalc replacement); the plain same-currency default path
        keeps the direct rippleSend fast path below."""
        has_paths = sfPaths in self.tx.obj and len(self.tx.obj[sfPaths]) > 0
        if (
            self.account_id == dst_id
            and not has_paths
            and max_amount.currency == dst_amount.currency
        ):
            # same-currency self-payment is a no-op; cross-currency
            # self-payment is a legitimate conversion (reference:
            # Payment.cpp redundancy check keys on currency too)
            return TER.temREDUNDANT
        if has_paths or max_amount.currency != dst_amount.currency or (
            self.account_id == dst_id
        ):
            return self._flow_payment(dst_id, dst_amount, max_amount, flags)

        # funds check: what can the sender actually deliver?
        funds = views.account_funds(self.les, self.account_id, max_amount)
        if funds.signum() <= 0:
            return TER.tecUNFUNDED_PAYMENT

        issuer = dst_amount.issuer
        if issuer != self.account_id and issuer != dst_id:
            # third-party issuer: the default path is a real two-hop
            # ripple (sender -> issuer -> destination) whose legality
            # depends on line state BOTH ways — the sender may redeem
            # held IOUs or ISSUE into a line the intermediary trusts,
            # and the intermediary's transfer rate and line qualities
            # apply. That is the flow engine's job (reference: Payment
            # routes every non-direct case through RippleCalc,
            # Payment.cpp:185-248); a held-balance precheck here
            # wrongly rejected issue-along-line deliveries.
            return self._flow_payment(dst_id, dst_amount, max_amount, flags)
        if issuer == self.account_id:
            # issuing own IOUs: delivery must fit the destination's trust
            # limit (the RippleCalc credit-limit rule on the default path)
            line_idx = indexes.ripple_state_index(
                dst_id, self.account_id, dst_amount.currency
            )
            line = self.les.peek(line_idx)
            if line is None:
                return TER.tecPATH_DRY
            held = views.ripple_balance(
                self.les, dst_id, self.account_id, dst_amount.currency
            )
            from ..protocol.sfields import sfHighLimit, sfLowLimit

            dst_high = dst_id > self.account_id
            limit = line[sfHighLimit if dst_high else sfLowLimit]
            new_bal = held + STAmount.from_iou(
                held.currency, held.issuer, dst_amount.mantissa,
                dst_amount.offset, dst_amount.negative,
            )
            if new_bal > STAmount.from_iou(
                new_bal.currency, new_bal.issuer, limit.mantissa,
                limit.offset, limit.negative,
            ):
                return TER.tecPATH_DRY
        elif issuer == dst_id:
            # redemption: sender must hold the destination's IOUs
            held = views.ripple_balance(
                self.les, self.account_id, dst_id, dst_amount.currency
            )
            if held.signum() <= 0 or held < STAmount.from_iou(
                held.currency, held.issuer, dst_amount.mantissa,
                dst_amount.offset, dst_amount.negative,
            ):
                return TER.tecPATH_PARTIAL

        ter, _actual = views.ripple_send(
            self.les, self.account_id, dst_id, dst_amount
        )
        if ter in (TER.terRETRY,):
            ter = TER.tecPATH_DRY
        return ter

    def _flow_payment(self, dst_id: bytes, dst_amount: STAmount,
                      max_amount: STAmount, flags: int) -> TER:
        """Path-engine delivery (reference: Payment.cpp:185-248 calling
        RippleCalc::rippleCalc with the tx's paths/flags)."""
        from ..paths.flow import flow

        tx_paths = (
            self.tx.obj[sfPaths].paths if sfPaths in self.tx.obj else []
        )
        paths = list(tx_paths)
        if not (flags & tfNoRippleDirect):
            # the default path goes FIRST: on equal quality the flow
            # loop keeps the earliest strand, and the reference builds
            # the direct PathState before the explicit ones
            # (RippleCalc.cpp pre-loop addPathState(STPath(), ...)), so
            # ties drain the direct line before any attached path
            paths.insert(0, [])
        partial = bool(flags & tfPartialPayment)
        limit_quality = None
        if flags & tfLimitQuality:
            # the tx's implied quality (Amount out per SendMax in) is the
            # worst rate the sender accepts (reference: uQualityLimit)
            from ..paths.flow import _ratio

            limit_quality = _ratio(dst_amount, max_amount)
        ter, _spent, _delivered = flow(
            self.les,
            self.account_id,
            dst_id,
            dst_amount,
            max_amount,
            paths,
            partial,
            self.engine.ledger.parent_close_time,
            limit_quality=limit_quality,
        )
        return ter
