"""Parallel speculative executor: a multi-worker Block-STM apply plane.

PR 2's delta-replay close executes every accepted transaction once at
submit time and splices the recorded delta at close — but that one
speculative execution still runs serially on the submit thread under the
chain lock, so speculation throughput is pinned to one interpreter core.
This module finishes the Block-STM idea (Gelashvili et al., 2022):
execute transactions optimistically across N workers and validate read
sets at commit, so speculation scales with cores.

Shape:

- ``SpecExecutor`` owns the worker pool ([spec] workers=N). ``workers=1``
  (the default) keeps the executor inert — ``LedgerMaster._speculate_open``
  runs the serial inline path byte-for-byte as before.

- Each open window gets a ``SpecSession``. Dispatch (under the chain
  lock) allocates the transaction's speculation index from the
  SpecState — the one total order that the commit step, the pre-seal
  building-tree folds, and the close's splice all share.

- Workers execute optimistically: a per-task ``_ExecView`` captures
  reads/succs/writes over a *replica* of the committed state (the shared
  ``SpecState.view`` for thread workers; a worker-local mirror built
  from shipped deltas for process workers). The record a worker produces
  is built by ``engine.deltareplay.execute_record`` — the exact code the
  serial path runs, which is what makes records byte-equal.

- Commit is strictly in index order, guarded by one commit lock: the
  record's entry reads must resolve to the same writers in the committed
  view and its succ cursors must reproduce — the SAME validation the
  close's ``try_splice`` applies, run early. A stale record (executed
  before a lower-indexed conflict committed) is re-executed with bounded
  retries, then executed serially on the committing thread against the
  committed view itself — which is literally the serial path and
  therefore always valid. Nothing is ever silently poisoned: an aborted
  execution retries; only an in-execution *exception* on the serial
  fallback disables the overlay (the serial path's own semantics).

- Worker transports: ``thread`` (in-process; optimistic shared-view
  reads — torn reads are caught by commit validation), ``process``
  (fork workers; a worker's state is the picklable scalar snapshot plus
  parent state read through the pipe and cached per window — never a
  full state copy), and ``manual`` (no workers; tests drive execution
  in seeded orders via ``step``/``pump`` so conflict interleavings
  replay deterministically, and ``drain`` completes the window inline).

- Process scheduling is ACCOUNT-AFFINE: a task is assigned to the
  worker its account hashes to, so one account's sequence chain
  executes in order on one worker, chained tentatively through a
  journaled replica (rolled back when a retry re-enters the chain).
  Committed-writer deltas ship only with RETRY chunks — a first
  execution reads its own chain plus the immutable parent, and a
  cross-account conflict surfaces as a validation abort whose retry
  then executes against a fully-current replica (guaranteed valid,
  since retries run at the commit frontier).

Lock order (deadlock audit): commit work takes session.commit_lock →
session.lock → (fold) nothing of the LedgerMaster's — the chain lock is
NEVER taken by commit threads. The close thread holds the chain lock and
waits on the session condition / takes commit_lock, so no inversion is
possible. Building-tree folds race only against the seal drainer's root
*read*, which is safe because ``SHAMap.bulk_update`` builds a new
persistent root and installs it with one attribute store.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from bisect import insort
from collections import deque
from typing import Optional

from ..node.metrics import AtomicCounters
from ..node.tracer import get_tracer
from ..protocol.sfields import sfTransactionIndex
from ..protocol.stobject import STObject
from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from ..state.shamap import SHAMapItem
from ..state.specview import PARENT, SpecView, _ShimItem
from .deltareplay import HEADER_TYPES, SpecRecord, execute_record

__all__ = ["SpecExecutor", "SpecSession"]

log = logging.getLogger("stellard.specexec")

_MISS = object()

# task lifecycle
PENDING = 0    # awaiting a worker
RUNNING = 1    # executing on a worker
READY = 2      # candidate record produced, awaiting ordered commit
COMMITTED = 3  # validated + folded into the committed view
SKIPPED = 4    # consumed its index without a retained record


class _ExecView(SpecView):
    """Per-task capture view over a worker's replica of the committed
    state: reads fall through to the replica WITH its committed-writer
    provenance (``peek``), writes stay local to this view, and the
    spring-into-existence probe asks the replica's merged view instead
    of the raw parent map. The capture a task produces is therefore
    exactly what the serial path would have captured had the committed
    prefix been the overlay it ran on."""

    @classmethod
    def over(cls, replica: SpecView) -> "_ExecView":
        view = cls.from_snapshot(replica.snapshot_scalars(),
                                 replica._parent)
        view._replica = replica
        return view

    def read_entry_pristine(self, index: bytes):
        sle = self._overlay.get(index, _MISS)
        if sle is not _MISS:
            if index not in self._reads:
                self._reads[index] = self._writers.get(index, PARENT)
            return sle
        v, w = self._replica.peek(index)
        if index not in self._reads:
            self._reads[index] = w
        return v

    def resolve_succ(self, key: bytes):
        # the replica's merged succ (parent + committed overlay),
        # re-merged with this task's own created/deleted keys — mirrors
        # SpecView.resolve_succ with the replica in the parent role
        cur = key
        while True:
            item = self._replica.resolve_succ(cur)
            if item is None or self._overlay.get(item.tag, _MISS) is not None:
                break
            cur = item.tag
        created = self._created_after(key)
        if item is not None and (created is None or item.tag < created):
            return item
        if created is not None:
            return _ShimItem(created)
        return None

    def write_entry(self, index: bytes, sle) -> None:
        prev = self._overlay.get(index, _MISS)
        if index not in self._created_set and (prev is _MISS or prev is None):
            # existence probe on the MERGED committed view (not the raw
            # parent map): a key created by a committed predecessor must
            # not re-join this task's created list
            if not self._replica.merged_has(index):
                insort(self._created, index)
                self._created_set.add(index)
        self._overlay[index] = sle
        self._writers[index] = self._txid
        self._writes.append((index, sle))


class _Task:
    __slots__ = (
        "index", "txid", "tx", "blob", "sig_good", "origin", "state",
        "attempts", "rec", "wire", "error", "t_dispatch", "exec_span",
        "owner",
    )

    def __init__(self, index, tx, origin):
        self.index = index
        self.txid = tx.txid()
        self.tx = tx
        # account-affinity key (deterministic, unlike salted hash()):
        # one account's sequence chain always lands on one worker, so
        # dependent neighbors chain tentatively instead of aborting
        self.owner = int.from_bytes(tx.account[:8], "big")
        self.blob = None        # lazily serialized for process transport
        self.sig_good = bool(tx._sig_good)
        self.origin = origin
        self.state = PENDING
        self.attempts = 0
        self.rec: Optional[SpecRecord] = None   # thread/manual candidate
        self.wire = None                        # process-mode payload
        self.error: Optional[str] = None
        self.t_dispatch = time.perf_counter()
        self.exec_span: Optional[tuple] = None  # (t0, t1, worker)


class SpecSession:
    """One open window's scheduling state. Tasks are index-aligned with
    the SpecState's speculation indexes (dispatch allocates them under
    the chain lock, so they are contiguous from 0)."""

    def __init__(self, executor: "SpecExecutor", spec, parent_ledger,
                 window_id: int, on_fold=None):
        self.executor = executor
        self.spec = spec
        self.view = spec.view
        self.parent_ledger = parent_ledger
        self.window_id = window_id
        self.on_fold = on_fold
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.tasks: list[_Task] = []
        self.pending: deque[int] = deque()
        self.next_commit = 0
        self.seen: set[bytes] = set()
        self.commit_lock = threading.Lock()
        self.closed = False
        # committed-writer log for the process workers: one entry per
        # committed record, shipped to each worker PIGGYBACKED on its
        # next exec assignment (an idle worker needs no deltas, and a
        # busy one gets them exactly when they matter — just before it
        # executes). Appended under commit_lock, so it is in commit
        # order; per-worker watermarks live on the _Proc.
        self.delta_log: list[tuple] = []
        # process-mode provenance map: key -> (txid, attempt-epoch) of
        # the committed writer. Worker replicas tag TENTATIVE chained
        # writes with their execution attempt, so a record that read an
        # aborted attempt's value can never validate against the same
        # txid's eventually-committed (different) execution — bare-txid
        # provenance alone could not tell them apart. Normalized back
        # to bare txids at commit, which is what the close's splice
        # validation consumes.
        self.writer_epoch: dict[bytes, object] = {}

    def complete(self) -> bool:
        """Caller holds self.lock."""
        return self.next_commit >= len(self.tasks)


def _wire_record(rec: SpecRecord, retained: bool):
    """Picklable result payload for the process transport."""
    writes = [
        (k, it.data if it is not None else None)
        for k, it in rec.write_items
    ]
    meta_b, off = rec.meta_blob, rec.meta_index_off
    if rec.meta is not None and meta_b is None:
        # index span wasn't pinnable: ship a canonical index-0
        # serialization; the parent re-parses and the splice
        # re-serializes (the always-correct slow path)
        rec.meta[sfTransactionIndex] = 0
        meta_b, off = rec.meta.serialize(), -1
    return (
        int(rec.raw_ter), int(rec.ter), rec.did_apply, rec.reads,
        rec.succs, writes, tuple(rec.net_deletes), meta_b, off, rec.fee,
        rec.origin, retained,
    )


def _unwire_record(payload) -> tuple[SpecRecord, bool]:
    (raw, ter, did, reads, succs, writes, netdel, meta_b, off, fee,
     origin, retained) = payload
    items = []
    for k, data in writes:
        items.append((k, SHAMapItem(k, data) if data is not None else None))
    meta = STObject.from_bytes(meta_b) if meta_b is not None else None
    rec = SpecRecord(TER(raw), TER(ter), did, reads, list(succs), items,
                     meta, fee)
    rec.net_deletes = frozenset(netdel)
    rec.origin = origin
    if meta_b is not None and off >= 0:
        rec.meta_blob = meta_b
        rec.meta_index_off = off
    return rec, retained


# ---------------------------------------------------------------------------
# process-worker side
# ---------------------------------------------------------------------------


class _IPCParent:
    """Worker-side read-through adapter standing in for the parent
    ledger: entry reads and succ walks cross the pipe once and are
    cached for the window (the parent state map is immutable while the
    window is open). Doubles as its own ``state_map`` facade."""

    def __init__(self, sync_read):
        self._sync = sync_read
        self._entries: dict[bytes, Optional[STObject]] = {}
        self._raw: dict[bytes, Optional[bytes]] = {}
        self._succ: dict[bytes, Optional[bytes]] = {}
        self.state_map = self

    def reset(self) -> None:
        self._entries.clear()
        self._raw.clear()
        self._succ.clear()

    def _fetch(self, key: bytes) -> Optional[bytes]:
        if key in self._raw:
            return self._raw[key]
        data = self._sync("r", key)
        self._raw[key] = data
        return data

    def read_entry_pristine(self, key: bytes) -> Optional[STObject]:
        sle = self._entries.get(key, _MISS)
        if sle is not _MISS:
            return sle
        data = self._fetch(key)
        sle = STObject.from_bytes(data) if data is not None else None
        self._entries[key] = sle
        return sle

    # -- state_map facade (get existence probe + succ walks) ---------------

    def get(self, key: bytes):
        return _ShimItem(key) if self._fetch(key) is not None else None

    def succ(self, key: bytes):
        if key in self._succ:
            tag = self._succ[key]
        else:
            tag = self._sync("s", key)
            self._succ[key] = tag
        return _ShimItem(tag) if tag is not None else None


def _chain_tentative(replica, journal, index, txid, rec, attempt,
                     created_set) -> None:
    """Apply one executed record's writes to the worker replica as if
    committed — tagged (txid, attempt) so a read of an aborted attempt
    can never validate — journaling every key's prior state so a later
    retry chunk can roll the speculation back (`_rollback_tentative`).
    The overlay stores the record's SHAMapItems directly: `.parsed` is
    already pinned, so a same-worker dependent pays zero re-parse."""
    for k, it in rec.write_items:
        journal.append((
            index, k, replica._overlay.get(k, _MISS),
            replica._writers.get(k), k in replica._created_set,
        ))
        replica._writers[k] = (txid, attempt)
        if it is None:
            replica._created_remove(k)
            replica._overlay[k] = None
        else:
            if k in created_set and k not in replica._created_set:
                insort(replica._created, k)
                replica._created_set.add(k)
            replica._overlay[k] = it


def _rollback_tentative(replica, journal, min_index) -> None:
    """Undo every journaled tentative write from tasks >= min_index (a
    retry chunk re-executes the commit frontier: speculation chained
    past it on THIS worker is stale and must not be visible). Reversed
    walk so stacked writes to one key unwind to the oldest prior."""
    keep = [e for e in journal if e[0] < min_index]
    for index, k, prior, pw, was_created in reversed(journal):
        if index < min_index:
            continue
        if prior is _MISS:
            replica._overlay.pop(k, None)
        else:
            replica._overlay[k] = prior
        if pw is None:
            replica._writers.pop(k, None)
        else:
            replica._writers[k] = pw
        now = k in replica._created_set
        if was_created and not now:
            replica._created_set.add(k)
            insort(replica._created, k)
        elif not was_created and now:
            replica._created_remove(k)
    journal[:] = keep


def _worker_main(cmd, res) -> None:
    """Process-worker loop. Messages on ``cmd``: win/delta/exec/end/stop
    plus rr/sr read replies; results and read requests go out on ``res``.
    Replies can interleave with proactive sends (deltas, the next exec),
    so non-reply messages arriving while a read is in flight are buffered
    and handled after the current execution finishes."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    for conn in (cmd, res):
        # ring transport: drop this process's inherited copy of the
        # parent-side doorbell fd so parent death surfaces as EOF here
        # (the pipe transport's Connections need no settling)
        settle = getattr(conn, "settle", None)
        if settle is not None:
            settle()
    buffered: deque = deque()
    state = {"wid": None, "replica": None, "adapter": None,
             "journal": [], "committed_max": -1}

    def sync_read(kind, key):
        res.send((kind, state["wid"], key))
        want = "rr" if kind == "r" else "sr"
        while True:
            m = cmd.recv()
            if m[0] == want:
                return m[1]
            if m[0] == "stop":
                # the parent is shutting down: the read server (its
                # committer) is gone and the reply will never come —
                # exit now instead of wedging in recv until stop()'s
                # join timeout expires and SIGTERMs this process
                raise SystemExit(0)
            buffered.append(m)

    adapter = _IPCParent(sync_read)

    def handle(msg) -> bool:
        kind = msg[0]
        if kind == "win":
            _k, wid, scalars = msg
            adapter.reset()
            state["wid"] = wid
            state["replica"] = SpecView.from_snapshot(scalars, adapter)
            state["journal"] = []
            state["committed_max"] = -1
        elif kind == "exec":
            _k, wid, deltas, items = msg
            if wid != state["wid"] or state["replica"] is None:
                res.send(("resb", wid,
                          [(i, 0.0, 0.0, "stale", None, _a)
                           for i, _b, _s, _o, _a in items]))
                return True
            replica = state["replica"]
            journal = state["journal"]
            # a retry chunk re-executes the commit frontier: any
            # tentative speculation this worker chained at or past it
            # is stale — unwind it BEFORE the committed deltas land
            if journal and items and items[0][0] <= journal[-1][0]:
                _rollback_tentative(replica, journal, items[0][0])
            # the committed-writer deltas since this worker's last
            # assignment ride the exec message — apply them first so
            # the replica is current for this chunk. The writer epoch
            # (txid, committed-attempt) is the provenance readers will
            # record and commit validation will compare.
            for index, txid, pairs, added, removed, applied, epoch \
                    in deltas:
                replica.apply_delta(txid, pairs, added, removed, applied,
                                    writer=(txid, epoch))
                if index > state["committed_max"]:
                    state["committed_max"] = index
            if journal:
                # tentative writes the committed deltas superseded can
                # never roll back (the frontier is past them) — prune
                journal[:] = [e for e in journal
                              if e[0] > state["committed_max"]]
            out = []
            for index, blob, sig_good, origin, attempt in items:
                t0 = time.perf_counter()
                try:
                    tx = SerializedTransaction.from_bytes(blob)
                    if sig_good:
                        tx.set_sig_verdict(True)
                    txid = tx.txid()
                    view = _ExecView.over(replica)
                    view.begin_tx(txid)
                    rec = execute_record(view, tx, origin)
                    retained = not (rec.did_apply and rec.meta is None)
                    out.append((index, t0, time.perf_counter(), None,
                                _wire_record(rec, retained), attempt))
                    # chain TENTATIVELY (journaled): apply this record's
                    # writes to the replica as if committed, so
                    # same-chunk dependents execute against their
                    # predecessors. Tagged with THIS attempt's epoch: if
                    # the record aborts and re-executes, a read of this
                    # value can never validate against the committed
                    # epoch.
                    if rec.write_items:
                        _chain_tentative(replica, journal, index, txid,
                                         rec, attempt, view._created_set)
                except Exception as exc:  # noqa: BLE001 — the parent
                    # decides between retry and serial fallback; never
                    # kill the worker
                    out.append((index, t0, time.perf_counter(),
                                repr(exc), None, attempt))
            res.send(("resb", wid, out))
        elif kind in ("rr", "sr"):
            pass  # stale reply after an abandoned read; drop
        elif kind == "end":
            if msg[1] == state["wid"]:
                state["wid"] = state["replica"] = None
                adapter.reset()
        elif kind == "stop":
            return False
        return True

    while True:
        try:
            msg = buffered.popleft() if buffered else cmd.recv()
            alive = handle(msg)
        except (EOFError, OSError):
            # parent gone (or closed our command channel at stop):
            # exit quietly — this IS the shutdown signal when the
            # parent marked this worker dead and skipped its ("stop",)
            return
        if not alive:
            return


class _Proc:
    __slots__ = ("proc", "cmd", "res", "send_lock", "outstanding",
                 "alive", "delta_sent")

    def __init__(self, proc, cmd, res):
        self.proc = proc
        self.cmd = cmd                  # parent -> worker
        self.res = res                  # worker -> parent
        self.send_lock = threading.Lock()
        self.outstanding = 0
        self.alive = True
        self.delta_sent = 0             # session.delta_log watermark

    def send(self, msg) -> bool:
        if not self.alive:
            return False
        try:
            with self.send_lock:
                self.cmd.send(msg)
            return True
        except (OSError, ValueError, BrokenPipeError):
            self.alive = False
            return False



# ---------------------------------------------------------------------------
# the executor
# ---------------------------------------------------------------------------


class SpecExecutor:
    """Multi-worker Block-STM speculative executor ([spec] workers=N).

    ``workers<=1`` → inert (``active`` False): LedgerMaster keeps the
    serial inline path, byte-for-byte. ``mode``: "process" (default,
    real parallelism around the GIL), "thread" (in-process workers —
    races are real, parallelism is GIL-bound; the concurrency-hammer
    configuration), "manual" (tests drive seeded schedules)."""

    def __init__(self, workers: int = 1, mode: str = "process",
                 max_retries: int = 3, tracer=None,
                 drain_timeout_s: float = 10.0, transport: str = "ring"):
        self.workers = int(workers)
        self.mode = mode
        if transport not in ("ring", "pipe"):
            raise ValueError(
                f"[spec] transport must be 'ring' or 'pipe', got "
                f"{transport!r}"
            )
        # process-worker wire: "ring" (shared-memory SPSC rings, pickle-
        # free codec — the default) or "pipe" (the PR 6 pickled
        # multiprocessing.Pipe wire, kept as the comparison/fallback leg)
        self.transport = transport
        self.max_retries = int(max_retries)
        self.drain_timeout_s = float(drain_timeout_s)
        self.tracer = tracer if tracer is not None else get_tracer()
        self.active = self.workers > 1
        # process workers take chunks of up to exec_batch tasks per
        # message (one round trip per chunk, not per task), assigned by
        # ACCOUNT AFFINITY, and chain tentative writes locally — one
        # account's dependent run executes against its predecessors on
        # one worker however the chunks split. Affinity is also why the
        # execution horizon can be generous (classic Block-STM gates
        # execution near the validation frontier because far-ahead
        # executions go wholesale-stale): an execution ahead of the
        # frontier on its OWN chain stays valid, and cross-account
        # staleness is caught by commit validation regardless of
        # distance. The horizon only bounds worst-case wasted work when
        # a window turns out conflict-heavy.
        self.exec_batch = max(8, 64 // max(1, self.workers))
        self.exec_horizon = max(512, 4 * self.workers * self.exec_batch)
        self.counters = AtomicCounters(
            "windows", "dispatched", "executed", "committed", "retries",
            "validation_aborts", "serial_fallbacks", "exec_errors",
            "no_records", "drains_forced", "reads_served", "deltas_sent",
            "worker_deaths", "committer_errors",
        )
        self._started = False
        self._stopping = False
        self._failed = False  # committer crashed: degrade to serial
        self._slock = threading.Lock()   # session/start lifecycle
        # one assigner at a time: the committer loop and a drain/pump
        # caller's retry path can both reach _assign_procs, and
        # interleaved pending-pops would send one worker's chunks out
        # of index order, breaking the account-affine in-order premise
        # the tentative-chain journal relies on
        self._assign_lock = threading.Lock()
        self.session: Optional[SpecSession] = None
        self._window_seq = 0
        self._threads: list[threading.Thread] = []
        self._procs: list[_Proc] = []
        # ONE committer thread multiplexes every worker pipe
        # (multiprocessing.connection.wait): results, parent-state
        # reads, ordered commits, and chunk assignment all run on it,
        # so the steady state has zero cross-thread handoffs — on a
        # small host the GIL ping-pong between per-worker service
        # threads costs more than the work itself. The submit thread
        # wakes it through a self-pipe (one byte, no locks held).
        self._committer: Optional[threading.Thread] = None
        self._wake_r: Optional[int] = None
        self._wake_w: Optional[int] = None

    # -- lifecycle ---------------------------------------------------------

    @property
    def can_accept(self) -> bool:
        """True while dispatch could take new work: not stopping,
        committer alive, and (in process mode, once started) at least
        one live worker. _speculate_open checks this BEFORE opening a
        window so a permanently-failed executor doesn't churn a fresh
        session — snapshot broadcast, windows-counter bump, teardown —
        per transaction on its way to the serial path."""
        if self._stopping or self._failed or not self.active:
            return False
        if self.mode == "process" and self._started \
                and not any(w.alive for w in self._procs):
            return False
        return True

    def start(self) -> None:
        """Start the worker pool (idempotent). Fork-based process
        workers start here — as early in the node's life as possible,
        before the window machinery is hot."""
        with self._slock:
            if self._started or not self.active or self._stopping:
                return
            self._started = True
            if self.mode == "process":
                self._wake_r, self._wake_w = os.pipe()
                os.set_blocking(self._wake_r, False)
                os.set_blocking(self._wake_w, False)
                self._start_procs()
                self._committer = threading.Thread(
                    target=self._committer_loop, name="spec-committer",
                    daemon=True,
                )
                self._committer.start()
            elif self.mode == "thread":
                for i in range(self.workers):
                    t = threading.Thread(
                        target=self._thread_worker_loop, args=(i,),
                        name=f"spec-worker-{i}", daemon=True,
                    )
                    t.start()
                    self._threads.append(t)
            # manual: no workers — tests drive step()/pump()/drain()

    def _start_procs(self) -> None:
        import multiprocessing as mp

        ctx = mp.get_context("fork")
        for i in range(self.workers):
            if self.transport == "ring":
                from .specring import ring_pipe

                # built BEFORE fork; the child inherits the mapped
                # segments and its doorbell fds through Process args
                # (fork does not pickle them)
                cmd_r, cmd_w = ring_pipe()          # parent -> worker
                res_r, res_w = ring_pipe()          # worker -> parent
            else:
                cmd_r, cmd_w = ctx.Pipe(duplex=False)   # parent -> worker
                res_r, res_w = ctx.Pipe(duplex=False)   # worker -> parent
            proc = ctx.Process(
                target=_worker_main, args=(cmd_r, res_w),
                name=f"spec-worker-{i}", daemon=True,
            )
            proc.start()
            if self.transport == "ring":
                # keep cmd_w/res_r; settle drops the parent's copies of
                # the child-side doorbell fds so worker death surfaces
                # as EOF on res / EPIPE on cmd, like a broken pipe did
                cmd_w.settle()
                res_r.settle()
            else:
                cmd_r.close()
                res_w.close()
            self._procs.append(_Proc(proc, cmd_w, res_r))

    def stop(self) -> None:
        """Stop workers (Node.stop). Any open session is force-completed
        serially first so no records are abandoned mid-window."""
        with self._slock:
            self._stopping = True
            session = self.session
        if session is not None:
            self.end_window(session, timeout=0.0)
        for w in self._procs:
            w.send(("stop",))
        if self._wake_w is not None:
            self._wake()
        for w in self._procs:
            if w.proc.is_alive():
                w.proc.join(timeout=5)
                if w.proc.is_alive():
                    w.proc.terminate()
            w.alive = False
            for conn in (w.cmd, w.res):
                # ring ends: release + unlink the shared segments (the
                # creator owns teardown); pipe Connections just close.
                # getattr both ways: tests wrap conns in minimal fakes
                fin = getattr(conn, "destroy", None) \
                    or getattr(conn, "close", None)
                try:
                    if fin is not None:
                        fin()
                except OSError:
                    pass
        if self._committer is not None:
            self._committer.join(timeout=5)
            self._committer = None
        for fd in (self._wake_r, self._wake_w):
            if fd is not None:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self._wake_r = self._wake_w = None
        with self._slock:
            self._started = False

    def get_json(self) -> dict:
        out = self.counters.snapshot()
        out.update(workers=self.workers, mode=self.mode,
                   active=self.active, max_retries=self.max_retries,
                   transport=self.transport)
        if self.transport == "ring" and self._procs:
            ring = {"msgs_sent": 0, "bytes_sent": 0, "msgs_recv": 0,
                    "bytes_recv": 0, "full_waits": 0, "torn_slots": 0}
            for w in self._procs:
                cs = getattr(w.cmd, "counters", None)
                rs = getattr(w.res, "counters", None)
                if cs:
                    ring["msgs_sent"] += cs["msgs"]
                    ring["bytes_sent"] += cs["bytes"]
                    ring["full_waits"] += cs["full_waits"]
                if rs:
                    ring["msgs_recv"] += rs["msgs"]
                    ring["bytes_recv"] += rs["bytes"]
                    ring["torn_slots"] += rs["torn_slots"]
            out["ring"] = ring
        return out

    # -- window lifecycle (called under the chain lock) --------------------

    def begin_window(self, spec, parent_ledger, on_fold=None) -> SpecSession:
        self.start()
        with self._slock:
            self._window_seq += 1
            session = SpecSession(self, spec, parent_ledger,
                                  self._window_seq, on_fold=on_fold)
            self.session = session
        self.counters.add("windows")
        if self.mode == "process":
            scalars = spec.view.snapshot_scalars()
            for w in self._procs:
                w.delta_sent = 0
                w.send(("win", session.window_id, scalars))
        return session

    def dispatch(self, session: SpecSession, tx, origin: str) -> bool:
        """Enqueue one accepted tx for parallel speculation. Caller
        holds the chain lock (index allocation is the total order).
        Returns False when the executor cannot take it (stopped, window
        closed, committer crashed, or worker pool dead) — the caller
        falls back to the serial inline path after ending the window."""
        if self._stopping or self._failed or session.closed:
            return False
        if self.mode == "process" and not any(w.alive for w in self._procs):
            return False
        if tx.tx_type in HEADER_TYPES or session.spec.disabled:
            return True  # serial parity: these are never speculated
        txid = tx.txid()
        if txid in session.seen or txid in session.spec.records:
            return True  # dup submit: already scheduled this window
        index = session.spec.alloc_index()
        task = _Task(index, tx, origin)
        with session.lock:
            # indexes are allocated under the chain lock in dispatch
            # order, so the task list stays index-aligned
            assert index == len(session.tasks), "index/task misalignment"
            session.tasks.append(task)
            session.seen.add(txid)
            session.pending.append(index)
            session.cv.notify()
        self.counters.add("dispatched")
        tr = self.tracer
        if tr.enabled and tr.sampled(txid):
            tr.instant("spec.dispatch", "spec", txid=txid,
                       index=index, origin=origin)
        if self.mode == "process":
            self._wake()
        return True

    def _wake(self) -> None:
        """Poke the committer through the self-pipe (a single byte; no
        locks held — safe from the submit thread under the chain lock).
        EAGAIN means a wake is already pending: coalesced, done."""
        try:
            os.write(self._wake_w, b"x")
        except (BlockingIOError, OSError):
            pass

    def drain(self, session: SpecSession, timeout: float,
              force: bool = True) -> bool:
        """Wait for every dispatched task to commit. With ``force``
        (the close-side call), a timeout completes the window inline:
        the remaining tasks run serially in index order on THIS thread —
        the close never waits on a wedged pool. Advisory callers
        (pre-close drain outside the chain lock) pass force=False."""
        deadline = time.perf_counter() + max(0.0, timeout)
        self._pump(session)
        while True:
            with session.lock:
                if session.complete():
                    return True
                # waiting is pointless when nothing can make progress:
                # manual mode has no workers at all, a crashed committer
                # will never drive another commit, and a fully-dead
                # process pool will never deliver another result — go
                # straight to the serial completion instead of burning
                # the whole timeout window
                stalled = self.mode == "manual" or self._failed or (
                    self.mode == "process"
                    and not any(w.alive for w in self._procs)
                )
                if not stalled:
                    remaining = deadline - time.perf_counter()
                    if remaining > 0:
                        session.cv.wait(min(remaining, 0.05))
            if stalled or time.perf_counter() >= deadline:
                break
            self._pump(session)
        if not force:
            return False
        self.counters.add("drains_forced")
        self._force_serial(session)
        return True

    def end_window(self, session: SpecSession, timeout: float = None) -> None:
        """Drain + seal the window: after this returns no commit can
        mutate the SpecState, so the close may consume it."""
        self.drain(session,
                   self.drain_timeout_s if timeout is None else timeout)
        with session.commit_lock:   # waits out any in-flight commit
            session.closed = True
        with self._slock:
            if self.session is session:
                self.session = None
        if self.mode == "process":
            for w in self._procs:
                w.send(("end", session.window_id))

    # -- execution (workers) -----------------------------------------------

    def _thread_worker_loop(self, wid: int) -> None:
        while not self._stopping:
            with self._slock:
                session = self.session
            if session is None:
                time.sleep(0.005)
                continue
            with session.lock:
                if not session.pending:
                    session.cv.wait(0.05)
                    continue
                index = session.pending.popleft()
                task = session.tasks[index]
                task.state = RUNNING
            self._execute_inproc(session, task, wid)
            self._pump(session)

    def _execute_inproc(self, session: SpecSession, task: _Task,
                        wid) -> None:
        """Thread/manual-mode execution: an _ExecView over the SHARED
        committed view. Reads are optimistic — a commit mutating the
        overlay mid-read can tear, and validation (or the exception
        handler here) catches it."""
        t0 = time.perf_counter()
        try:
            view = _ExecView.over(session.view)
            view.begin_tx(task.txid)
            rec = execute_record(view, task.tx, task.origin)
            task.rec, task.error = rec, None
        except Exception as exc:  # noqa: BLE001 — torn optimistic read
            # or a genuine transactor bug; retry decides downstream
            task.rec, task.error = None, repr(exc)
        task.exec_span = (t0, time.perf_counter(), wid)
        self.counters.add("executed")
        with session.lock:
            task.state = READY
            session.cv.notify_all()

    # -- process transport (parent side) -----------------------------------

    def _assign_procs(self, session: SpecSession) -> None:
        """Hand pending tasks to workers by ACCOUNT AFFINITY, in index
        order, chunked up to exec_batch per message: one account's
        sequence chain always executes on one worker, where the
        journaled tentative chaining makes dependent neighbors see their
        predecessors — cross-worker aborts are left for genuine
        cross-account conflicts. Never assigns past the execution
        horizon (a replica only carries committed deltas, so execution
        far ahead of the commit frontier would re-run wholesale), and a
        saturated worker's tasks stay pending rather than spilling to a
        foreign worker. Serialized by _assign_lock: concurrent assigners
        (committer loop vs a drain caller's retry path) would interleave
        pending-pops and send one worker's chunks out of index order.
        _assign_lock is NOT reentrant, so a mid-assignment send failure
        is handled here, after the locked pass returns: requeue the
        casualty's tasks, recompute the live set, and assign again."""
        while True:
            live = [w for w in self._procs if w.alive]
            if not live:
                return
            with self._assign_lock:
                failed = self._assign_procs_locked(session, live)
            if not failed:
                return
            for w in failed:
                self.counters.add("worker_deaths")
                self._requeue_inflight(w, session)

    def _assign_procs_locked(self, session: SpecSession, live) -> list:
        failed: list = []
        budget = {
            id(w): 2 * self.exec_batch - w.outstanding for w in live
        }
        chunks: dict[int, list[_Task]] = {}
        leftover: list[int] = []
        with session.lock:
            while (session.pending
                   and (session.pending[0] - session.next_commit
                        < self.exec_horizon)):
                index = session.pending.popleft()
                task = session.tasks[index]
                w = live[task.owner % len(live)]
                chunk = chunks.setdefault(id(w), [])
                if budget[id(w)] <= 0 or len(chunk) >= self.exec_batch:
                    leftover.append(index)
                    continue
                budget[id(w)] -= 1
                task.state = RUNNING
                chunk.append(task)
            if leftover:
                session.pending.extendleft(reversed(leftover))
        for w in live:
            chunk = chunks.get(id(w))
            if not chunk:
                continue
            items = []
            retrying = False
            for task in chunk:
                if task.blob is None:
                    task.blob = task.tx.serialize()
                if task.attempts:
                    retrying = True
                items.append((task.index, task.blob, task.sig_good,
                              task.origin, task.attempts))
            w.outstanding += len(chunk)
            # committed-writer deltas ship ONLY with retry chunks: the
            # account-affinity schedule means a first execution reads
            # its own chain (tentatively present) and otherwise the
            # parent — if a cross-account conflict makes that stale,
            # commit validation catches it and the RETRY re-executes
            # against a replica brought fully current here. Shipping
            # (and worker-side applying) every commit to every worker
            # costs more than the rare retry it would prevent.
            ok = False
            if w.alive:
                try:
                    with w.send_lock:
                        deltas = ()
                        if retrying:
                            dlog = session.delta_log
                            deltas = dlog[w.delta_sent:]
                            w.delta_sent = len(dlog)
                        w.cmd.send(("exec", session.window_id, deltas,
                                    items))
                    if deltas:
                        self.counters.add("deltas_sent", len(deltas))
                    ok = True
                except (OSError, ValueError, BrokenPipeError):
                    w.alive = False
            if not ok:
                w.outstanding -= len(chunk)
                failed.append(w)
        return failed

    def _committer_loop(self) -> None:
        """THE parent-side pipeline thread (process mode): multiplexes
        every worker's result pipe plus the dispatch self-pipe, answers
        parent-state reads, records results, drives ordered commits and
        chunk assignment — all on one thread, so the steady state has no
        cross-thread handoffs to pay for."""
        from multiprocessing.connection import wait as conn_wait

        while not self._stopping:
            by_conn = {w.res: w for w in self._procs if w.alive}
            if not by_conn:
                break
            try:
                ready = conn_wait(list(by_conn) + [self._wake_r],
                                  timeout=0.1)
            except OSError:
                break
            with self._slock:
                session = self.session
            progressed = False
            try:
                for conn in ready:
                    if conn == self._wake_r:
                        try:
                            os.read(self._wake_r, 4096)
                        except (BlockingIOError, OSError):
                            pass
                        progressed = True
                        continue
                    w = by_conn[conn]
                    try:
                        msg = conn.recv()
                    except (EOFError, OSError) as exc:
                        # guard: the same worker may already have been
                        # discovered dead this iteration via a failed
                        # send
                        if w.alive:
                            w.alive = False
                            self.counters.add("worker_deaths")
                            # a torn ring slot (CRC/framing mismatch)
                            # retires the connection exactly like a
                            # death, but the distinction matters when
                            # debugging: spec.ring.torn means corrupt
                            # shared memory, spec.ring.dead a lost peer
                            if self.transport == "ring":
                                torn = (type(exc).__name__
                                        == "TornSlotError")
                                self.tracer.instant(
                                    "spec.ring.torn" if torn
                                    else "spec.ring.dead",
                                    "spec", error=str(exc)[:120],
                                )
                            if session is not None:
                                self._fail_worker(w, session)
                        continue
                    progressed = self._handle_worker_msg(session, w, msg) \
                        or progressed
                if progressed and session is not None:
                    self._pump(session)
                    self._assign_procs(session)
            except Exception:  # noqa: BLE001 — ANY commit-machinery
                # failure (the fold-ordering assertion, a bug in
                # message handling, a corrupt pipe unpickling in
                # recv — anything beyond the clean worker-EOF path)
                # must not silently kill this thread and leave every
                # later close burning its full drain timeout: log
                # LOUDLY, flag the executor failed (dispatch refuses,
                # drain goes straight to serial completion) and stop
                # driving — the node degrades to the serial path
                log.exception(
                    "spec committer crashed; degrading to serial "
                    "speculation"
                )
                self.counters.add("committer_errors")
                self._failed = True
                if session is not None:
                    with session.lock:
                        session.cv.notify_all()
                return

    def _handle_worker_msg(self, session, w: _Proc, msg) -> bool:
        """-> True when the message may have unblocked commits or
        assignment (a result batch or a dispatch wake)."""
        kind = msg[0]
        if kind in ("r", "s"):
            _k, wid, key = msg
            data = None
            if session is not None and wid == session.window_id:
                self.counters.add("reads_served")
                if kind == "r":
                    item = session.parent_ledger.state_map.get(key)
                    data = item.data if item is not None else None
                else:
                    item = session.parent_ledger.state_map.succ(key)
                    data = item.tag if item is not None else None
            was_alive = w.alive
            if not w.send(("rr" if kind == "r" else "sr", data)) \
                    and was_alive:
                # undeliverable reply: the worker is wedged waiting for
                # it, so its in-flight tasks will never produce results
                # — requeue them now instead of burning the close's
                # whole drain timeout
                self.counters.add("worker_deaths")
                if session is not None:
                    self._fail_worker(w, session)
            return False
        if kind == "resb":
            _k, wid, results = msg
            # under _assign_lock: the increment in _assign_procs_locked
            # and this decrement are read-modify-writes from different
            # threads — unsynchronized, a lost decrement would skew the
            # worker's budget upward until it starves
            with self._assign_lock:
                w.outstanding = max(0, w.outstanding - len(results))
            if session is None or wid != session.window_id:
                return False
            n = 0
            with session.lock:
                for index, t0, t1, err, payload, attempt in results:
                    task = session.tasks[index]
                    if task.state != RUNNING \
                            or attempt != task.attempts:
                        # superseded: drain/retry, or a stale execution
                        # instance (the task was requeued after a worker
                        # loss and re-issued under a NEWER attempt —
                        # accepting the old result here would let its
                        # epoch collide with another instance's
                        # tentative chain on a different worker)
                        continue
                    task.wire, task.error = payload, err
                    task.exec_span = (t0, t1, w.proc.name)
                    task.state = READY
                    n += 1
                session.cv.notify_all()
            if n:
                self.counters.add("executed", n)
            return True
        return False

    def _fail_worker(self, w: _Proc, session: SpecSession) -> None:
        """A worker died: its in-flight tasks go back to pending (their
        results will never arrive) and the survivors pick them up; the
        drain's serial completion covers a fully-dead pool. Must be
        called WITHOUT _assign_lock held (the reassignment takes it)."""
        w.alive = False
        self._requeue_inflight(w, session)
        self._assign_procs(session)

    def _requeue_inflight(self, w: _Proc, session: SpecSession) -> None:
        with session.lock:
            # reversed so the appendlefts leave pending index-sorted
            # (in-flight indexes are all below the pending head)
            for task in reversed(session.tasks):
                if task.state == RUNNING and task.wire is None \
                        and task.error is None:
                    task.state = PENDING
                    # a NEW execution instance: a still-in-flight result
                    # from the old assignment (this requeue is
                    # conservative — it also re-pends tasks running on
                    # survivors) is dropped by the resb attempt check,
                    # so two instances of one task can never both land
                    # and their epoch-tagged tentative chains can never
                    # cross-validate
                    task.attempts += 1
                    session.pending.appendleft(task.index)
            session.cv.notify_all()

    # -- manual mode (deterministic test schedules) ------------------------

    def step(self, session: SpecSession, index: int) -> None:
        """Execute task `index` synchronously on this thread against the
        CURRENT committed state (manual mode). Tests call this in seeded
        orders to replay conflict interleavings deterministically."""
        with session.lock:
            task = session.tasks[index]
            if task.state not in (PENDING, RUNNING):
                return
            if index in session.pending:
                session.pending.remove(index)
            task.state = RUNNING
        self._execute_inproc(session, task, "manual")

    def pump(self, session: SpecSession) -> None:
        """Drive ordered commits over whatever candidates are ready."""
        self._pump(session)

    # -- ordered commit ----------------------------------------------------

    def _pump(self, session: SpecSession) -> None:
        while True:
            if not session.commit_lock.acquire(blocking=False):
                return  # the holder re-checks the frontier on release
            task = None
            try:
                if session.closed:
                    return
                with session.lock:
                    if session.next_commit < len(session.tasks):
                        cand = session.tasks[session.next_commit]
                        if cand.state == READY:
                            task = cand
                if task is not None:
                    self._commit_one(session, task)
            finally:
                session.commit_lock.release()
            if task is not None:
                continue
            # the frontier was not READY while we held commit_lock — but
            # a concurrent setter may have made it READY after our check
            # and had ITS try-acquire fail against us. Re-check now that
            # we've released: if it is READY, loop and commit it; if the
            # window is quiet, whoever flips it next pumps successfully.
            with session.lock:
                if (session.closed
                        or session.next_commit >= len(session.tasks)
                        or session.tasks[session.next_commit].state
                        != READY):
                    return

    def _force_serial(self, session: SpecSession) -> None:
        """Complete the window inline: every uncommitted task executes
        serially, in index order, against the committed view (the
        drain's close-side guarantee)."""
        with session.commit_lock:
            if session.closed:
                return
            while True:
                with session.lock:
                    if session.complete():
                        return
                    task = session.tasks[session.next_commit]
                    if task.state in (PENDING, RUNNING):
                        task.state = READY
                        task.rec, task.wire = None, None
                        task.error = "drain_forced"
                        if task.index in session.pending:
                            session.pending.remove(task.index)
                self._commit_one(session, task)

    def _candidate(self, task: _Task) -> Optional[tuple]:
        """-> (rec, retained) from whichever transport produced it."""
        if task.rec is not None:
            rec = task.rec
            return rec, not (rec.did_apply and rec.meta is None)
        if task.wire is not None:
            return _unwire_record(task.wire)
        return None

    def _commit_one(self, session: SpecSession, task: _Task) -> None:
        """Validate-or-retry-or-serial-fallback, then commit, in index
        order. Caller holds session.commit_lock; NEVER the chain lock."""
        tr = self.tracer
        t0 = time.perf_counter()
        spec = session.spec
        rec = retained = None
        cand = None if task.error is not None else self._candidate(task)
        if cand is not None:
            rec, retained = cand
            if task.exec_span is not None and tr.enabled \
                    and tr.sampled(task.txid):
                e0, e1, wid = task.exec_span
                tr.complete("spec.exec", "spec", e0, e1, txid=task.txid,
                            index=task.index, worker=str(wid),
                            attempt=task.attempts)
            if not self._validate(session, rec,
                                  epochal=task.rec is None):
                self.counters.add("validation_aborts")
                cand = rec = None  # stale execution
        if cand is None:
            # no candidate (exec error / worker loss) or a stale one
            if task.error is None and task.attempts < self.max_retries:
                task.attempts += 1
                self.counters.add("retries")
                if tr.enabled and tr.sampled(task.txid):
                    tr.instant("spec.retry", "spec", txid=task.txid,
                               index=task.index, attempt=task.attempts)
                with session.lock:
                    task.state = PENDING
                    task.rec = task.wire = None
                    # retries go to the FRONT: the task is the commit
                    # frontier itself, and pending stays index-sorted
                    session.pending.appendleft(task.index)
                    session.cv.notify_all()
                if self.mode == "process":
                    self._assign_procs(session)
                return
            if task.error is not None and task.error != "drain_forced":
                self.counters.add("exec_errors")
            # serial fallback: execute against the committed view itself
            # — the serial path, valid by construction. speculate() bakes
            # the writes into the overlay and retains the record (or
            # poisons the overlay on an execution exception, exactly the
            # serial semantics).
            self.counters.add("serial_fallbacks")
            rec = spec.speculate(task.tx, origin=task.origin,
                                 index=task.index)
            retained = rec is not None and spec.records.get(task.txid) is rec
            if rec is not None:
                self._finish_commit(session, task, rec, retained,
                                    serial=True)
            else:
                with session.lock:
                    task.state = SKIPPED
                    session.next_commit += 1
                    session.cv.notify_all()
            if tr.enabled and tr.sampled(task.txid):
                tr.complete("spec.validate", "spec", t0,
                            time.perf_counter(), txid=task.txid,
                            index=task.index, outcome="serial_fallback")
            return
        # optimistic candidate validated: fold it into the committed view
        # (applied=False for the kept-no-record case — the serial path's
        # incomplete commit tail bakes the writes but never reaches
        # record_transaction, so the tx-map membership must not either)
        rec.index = task.index
        if task.rec is None:
            # process record: normalize the (txid, attempt) epochs back
            # to the bare txids the close's splice validation consumes
            rec.reads = {
                k: (w[0] if type(w) is tuple else w)
                for k, w in rec.reads.items()
            }
        session.view.apply_record(task.txid, rec.write_items,
                                  rec.did_apply and retained)
        if retained:
            spec.records[task.txid] = rec
        self._finish_commit(session, task, rec, retained, serial=False)
        if tr.enabled and tr.sampled(task.txid):
            tr.complete("spec.validate", "spec", t0, time.perf_counter(),
                        txid=task.txid, index=task.index,
                        outcome="commit", attempts=task.attempts)

    def _finish_commit(self, session: SpecSession, task: _Task, rec,
                       retained: bool, serial: bool) -> None:
        spec = session.spec
        if retained:
            self.counters.add("committed")
            if spec.building is not None:
                folded = spec.fold_building(rec)
                if folded and session.on_fold is not None:
                    session.on_fold(folded)
        else:
            # kept-no-record: the writes are already in the overlay
            # (apply_record on the worker path, speculate() on the
            # serial one) — only the record itself is withheld
            self.counters.add("no_records")
        if self.mode == "process" and rec.write_items:
            pairs = [(k, it.data if it is not None else None)
                     for k, it in rec.write_items]
            # the committed created-set delta is authoritative for the
            # worker replicas (they never probe the parent for existence)
            added, removed = self._created_delta(session, rec)
            # the committed epoch: the attempt whose execution produced
            # this record, or -1 for a serial (committed-view) execution
            # — tentative same-txid values from OTHER attempts can never
            # validate against it
            epoch = -1 if serial else task.attempts
            for k, _it in rec.write_items:
                session.writer_epoch[k] = (task.txid, epoch)
            session.delta_log.append(
                (task.index, task.txid, pairs, added, removed,
                 rec.did_apply and retained, epoch)
            )
        with session.lock:
            task.state = COMMITTED if retained else SKIPPED
            task.rec = rec if retained else None
            task.wire = None
            session.next_commit += 1
            session.cv.notify_all()
        # no per-commit assignment: the generous horizon means commits
        # rarely release gated work, and the committer loop assigns on
        # every dispatch wake and result batch anyway — an extra
        # session.lock acquisition per commit just contends with the
        # submit thread. The retry path assigns explicitly (latency).

    def _created_delta(self, session: SpecSession, rec) -> tuple:
        """(created_added, created_removed) for one committed record, as
        observed in the committed view AFTER application."""
        view = session.view
        added, removed = [], []
        for k, it in rec.write_items:
            if it is None:
                removed.append(k)
            elif k in view._created_set:
                added.append(k)
        return added, removed

    def _validate(self, session: SpecSession, rec,
                  epochal: bool) -> bool:
        """The commit-time read validation — the same provenance +
        succ-reproduction test the close's try_splice applies. Process
        records carry (txid, attempt) epochs and validate against the
        session's epoch map (a read of an aborted attempt's tentative
        value must never pass); thread/manual records read the live
        committed view and validate against its bare-txid writers."""
        writers = session.writer_epoch if epochal else session.view._writers
        for k, wid in rec.reads.items():
            if writers.get(k, PARENT) != wid:
                return False
        for cursor, tag in rec.succs:
            item = session.view.resolve_succ(cursor)
            if (item.tag if item is not None else None) != tag:
                return False
        return True
