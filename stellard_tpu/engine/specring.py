"""Shared-memory ring transport for the Block-STM worker pool.

The PR 6 pool moved records over ``multiprocessing.Pipe`` — every chunk
paid a pickle, a syscall per send, and a pickle again on the far side.
On the small boxes the pool targets, that submit+committer overhead
(~1.1 ms/tx) exceeded the whole serial speculation cost, so ``workers>1``
lost. This module replaces the wire with single-producer/single-consumer
byte rings over ``multiprocessing.shared_memory``:

- one ring per direction per worker, data moves by memcpy into the
  mapped segment — no per-message allocation on the wire, no pickle;
- messages are encoded with a small fixed-vocabulary tagged binary codec
  (``_encode_msg``/``_decode_msg``) covering exactly the types the spec
  protocol ships (ints, bytes, str, float, None, bool, tuples/lists/
  dicts/sets) — a pickle-free reply can never execute code on the
  parent, and a torn slot can never half-deserialize into a live object;
- every record carries a fixed-layout slot header
  ``[magic u32][len u32][crc32 u32][seq u32]`` so a torn or corrupted
  slot is DETECTED (``TornSlotError``) instead of misparsed — the
  committer treats it exactly like a worker death;
- readiness is an ``os.pipe`` doorbell with a strict one-byte-per-record
  protocol: the producer publishes the record (payload, then head
  pointer) BEFORE writing the doorbell byte, so a consumer that read a
  byte is guaranteed to pop a whole record; the doorbell fd is what
  ``fileno()`` exposes, so ``multiprocessing.connection.wait`` keeps
  multiplexing worker channels exactly as it did with pipes, and peer
  death surfaces as EOF on the doorbell just like a broken pipe did.

``RingConn`` mimics the ``Connection`` API (``send``/``recv``/``poll``/
``fileno``/``close``) so ``_worker_main``, ``_Proc`` and the committer
loop run unchanged over either transport ([spec] transport=ring|pipe).
"""

from __future__ import annotations

import os
import select
import struct
import threading
import time
import zlib
from multiprocessing import shared_memory

__all__ = [
    "RingConn",
    "TornSlotError",
    "ring_pipe",
    "encode_msg",
    "decode_msg",
]


class TornSlotError(OSError):
    """A ring slot failed validation (magic/len/crc/seq): the peer died
    mid-write or the segment was corrupted. Raised from ``recv`` so the
    committer's existing (EOFError, OSError) death handling absorbs it."""


# ---------------------------------------------------------------------------
# codec — the spec wire vocabulary, no pickle
# ---------------------------------------------------------------------------
#
# Tags (1 byte each):
#   N None   T True   F False
#   I int    (u8 length + signed big-endian bytes)
#   D float  (8-byte IEEE double)
#   B bytes  (u32 length + raw)
#   S str    (u32 length + utf-8)
#   U tuple  L list   M dict   Y set   Z frozenset  (u32 count + items)

_U32 = struct.Struct("<I")
_F64 = struct.Struct("<d")


def _encode(obj, out: bytearray) -> None:
    t = type(obj)
    if obj is None:
        out += b"N"
    elif t is bool:
        out += b"T" if obj else b"F"
    elif t is int:
        b = obj.to_bytes((obj.bit_length() + 8) // 8, "big", signed=True)
        if len(b) > 255:
            raise ValueError("int too large for spec wire")
        out += b"I"
        out.append(len(b))
        out += b
    elif t is float:
        out += b"D"
        out += _F64.pack(obj)
    elif t is bytes:
        out += b"B"
        out += _U32.pack(len(obj))
        out += obj
    elif t is str:
        e = obj.encode("utf-8")
        out += b"S"
        out += _U32.pack(len(e))
        out += e
    elif t is tuple:
        out += b"U"
        out += _U32.pack(len(obj))
        for item in obj:
            _encode(item, out)
    elif t is list:
        out += b"L"
        out += _U32.pack(len(obj))
        for item in obj:
            _encode(item, out)
    elif t is dict:
        out += b"M"
        out += _U32.pack(len(obj))
        for k, v in obj.items():
            _encode(k, out)
            _encode(v, out)
    elif t is set:
        out += b"Y"
        out += _U32.pack(len(obj))
        for item in obj:
            _encode(item, out)
    elif t is frozenset:
        out += b"Z"
        out += _U32.pack(len(obj))
        for item in obj:
            _encode(item, out)
    elif t is bytearray or t is memoryview:
        b = bytes(obj)
        out += b"B"
        out += _U32.pack(len(b))
        out += b
    else:
        raise TypeError(f"type {t.__name__} is not in the spec wire "
                        f"vocabulary")


def _decode(buf, pos: int):
    tag = buf[pos:pos + 1]
    pos += 1
    if tag == b"N":
        return None, pos
    if tag == b"T":
        return True, pos
    if tag == b"F":
        return False, pos
    if tag == b"I":
        n = buf[pos]
        pos += 1
        return int.from_bytes(buf[pos:pos + n], "big", signed=True), pos + n
    if tag == b"D":
        return _F64.unpack_from(buf, pos)[0], pos + 8
    if tag == b"B":
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        return bytes(buf[pos:pos + n]), pos + n
    if tag == b"S":
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        return bytes(buf[pos:pos + n]).decode("utf-8"), pos + n
    if tag in (b"U", b"L", b"Y", b"Z"):
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        items = []
        for _ in range(n):
            item, pos = _decode(buf, pos)
            items.append(item)
        if tag == b"U":
            return tuple(items), pos
        if tag == b"L":
            return items, pos
        if tag == b"Y":
            return set(items), pos
        return frozenset(items), pos
    if tag == b"M":
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        d = {}
        for _ in range(n):
            k, pos = _decode(buf, pos)
            v, pos = _decode(buf, pos)
            d[k] = v
        return d, pos
    raise TornSlotError(f"unknown wire tag {tag!r}")


def encode_msg(obj) -> bytes:
    out = bytearray()
    _encode(obj, out)
    return bytes(out)


def decode_msg(payload) -> object:
    try:
        obj, pos = _decode(payload, 0)
    except TornSlotError:
        raise
    except (struct.error, IndexError, ValueError, OverflowError) as exc:
        # truncated/garbled bytes must surface as a TORN slot (the
        # committer's worker-death path), never as a stray struct.error
        # that would crash the committer thread
        raise TornSlotError(f"undecodable ring record: {exc}") from None
    if pos != len(payload):
        raise TornSlotError(
            f"trailing garbage in ring record ({len(payload) - pos} bytes)"
        )
    return obj


# ---------------------------------------------------------------------------
# the SPSC byte ring
# ---------------------------------------------------------------------------

_MAGIC = 0x52494E47  # "RING"
_HDR = struct.Struct("<IIII")  # magic, len, crc32, seq
_HEAD_OFF = 0    # u64, monotonic, producer-written
_TAIL_OFF = 8    # u64, monotonic, consumer-written
_DATA_OFF = 64   # keep the pointers on their own cache line
_Q = struct.Struct("<Q")


class _Ring:
    """Single-producer/single-consumer byte ring in a shared segment.
    head/tail are monotonic u64 byte counters; records are a 16-byte slot
    header + payload, padded to 8 bytes, copied with a wrap split (no
    alignment constraint on the reader side beyond the header struct)."""

    def __init__(self, shm: shared_memory.SharedMemory, capacity: int):
        self.shm = shm
        self.cap = capacity
        self.buf = shm.buf
        self.seq_out = 0  # producer-side record sequence
        self.seq_in = 0   # consumer-side expected sequence

    # -- pointer access (8-byte pack/unpack; the doorbell read/write
    #    syscalls on either side of every access are full barriers, so
    #    the values a woken peer reads are published and stable) --------

    def _head(self) -> int:
        return _Q.unpack_from(self.buf, _HEAD_OFF)[0]

    def _tail(self) -> int:
        return _Q.unpack_from(self.buf, _TAIL_OFF)[0]

    def _copy_in(self, pos: int, data) -> None:
        off = pos % self.cap
        first = min(len(data), self.cap - off)
        self.buf[_DATA_OFF + off:_DATA_OFF + off + first] = data[:first]
        if first < len(data):
            self.buf[_DATA_OFF:_DATA_OFF + len(data) - first] = data[first:]

    def _copy_out(self, pos: int, n: int) -> bytes:
        off = pos % self.cap
        first = min(n, self.cap - off)
        out = bytes(self.buf[_DATA_OFF + off:_DATA_OFF + off + first])
        if first < n:
            out += bytes(self.buf[_DATA_OFF:_DATA_OFF + n - first])
        return out

    def push(self, payload: bytes, timeout: float = 5.0) -> int:
        """Append one record. Returns the number of bounded full-ring
        waits taken; raises OSError when the ring never drains (a wedged
        or dead consumer — the caller's worker-death path handles it)."""
        need = _HDR.size + ((len(payload) + 7) & ~7)
        if need > self.cap:
            raise OSError(
                f"ring record ({need}B) exceeds ring capacity ({self.cap}B)"
            )
        head = self._head()
        waits = 0
        deadline = None
        while self.cap - (head - self._tail()) < need:
            waits += 1
            if deadline is None:
                deadline = time.monotonic() + timeout
            elif time.monotonic() > deadline:
                raise OSError("ring full: consumer is not draining")
            time.sleep(0.0002)
        rec = _HDR.pack(_MAGIC, len(payload), zlib.crc32(payload),
                        self.seq_out & 0xFFFFFFFF)
        self._copy_in(head, rec)
        self._copy_in(head + _HDR.size, payload)
        self.seq_out += 1
        # publish LAST: a consumer woken by the doorbell (written by the
        # caller after this returns) always sees a whole record
        _Q.pack_into(self.buf, _HEAD_OFF, head + need)
        return waits

    def pop(self):
        """Remove and return the next record's payload, or None when the
        ring is empty. Validates the slot header; a failed check raises
        TornSlotError and leaves the ring poisoned (no further pops)."""
        tail = self._tail()
        head = self._head()
        if head == tail:
            return None
        if head - tail < _HDR.size:
            raise TornSlotError("ring header truncated")
        magic, length, crc, seq = _HDR.unpack(
            self._copy_out(tail, _HDR.size))
        need = _HDR.size + ((length + 7) & ~7)
        if magic != _MAGIC or head - tail < need or length > self.cap:
            raise TornSlotError(
                f"torn ring slot: magic={magic:#x} len={length} "
                f"avail={head - tail}"
            )
        if seq != self.seq_in & 0xFFFFFFFF:
            raise TornSlotError(
                f"ring slot out of sequence: got {seq}, "
                f"want {self.seq_in & 0xFFFFFFFF}"
            )
        payload = self._copy_out(tail + _HDR.size, length)
        if zlib.crc32(payload) != crc:
            raise TornSlotError("ring slot crc mismatch")
        self.seq_in += 1
        _Q.pack_into(self.buf, _TAIL_OFF, tail + need)
        return payload


# ---------------------------------------------------------------------------
# the Connection-shaped channel
# ---------------------------------------------------------------------------


class RingConn:
    """One end of a simplex shared-memory ring channel.

    ``role`` is "send" or "recv". Both ends share ONE SharedMemory
    mapping (created pre-fork; the child inherits it — on this Python,
    attaching by name would re-register the segment with the resource
    tracker and get it unlinked out from under the peer). Each end owns
    ONE doorbell fd (read for "recv", write for "send") and records the
    peer's fd number so post-fork ``settle`` can drop the inherited copy
    — that is what turns peer death into EOF/EPIPE, exactly like the
    pipe transport. ``destroy`` (creator process only) releases and
    unlinks the segment."""

    def __init__(self, ring: _Ring, own_fd: int, peer_fd: int, role: str,
                 owner_pid: int):
        self._ring = ring
        self._fd = own_fd
        self._peer_fd = peer_fd
        self.role = role
        self._owner_pid = owner_pid
        self._closed = False
        self.counters = {"msgs": 0, "bytes": 0, "full_waits": 0,
                         "torn_slots": 0}

    # -- Connection API ----------------------------------------------------

    def send(self, obj) -> None:
        if self._closed:
            raise OSError("ring channel closed")
        payload = encode_msg(obj)
        self.counters["full_waits"] += self._ring.push(payload)
        self.counters["msgs"] += 1
        self.counters["bytes"] += len(payload)
        os.write(self._fd, b"\x01")  # doorbell: strictly 1 byte/record

    def recv(self):
        if self._closed:
            raise EOFError("ring channel closed")
        b = os.read(self._fd, 1)
        if b == b"":
            raise EOFError("ring peer closed")
        try:
            payload = self._ring.pop()
        except TornSlotError:
            self.counters["torn_slots"] += 1
            raise
        if payload is None:
            # the doorbell byte promises a published record
            self.counters["torn_slots"] += 1
            raise TornSlotError("doorbell rang on an empty ring")
        try:
            msg = decode_msg(payload)
        except TornSlotError:
            self.counters["torn_slots"] += 1
            raise
        self.counters["msgs"] += 1
        self.counters["bytes"] += len(payload)
        return msg

    def poll(self, timeout: float = 0.0) -> bool:
        r, _w, _x = select.select([self._fd], [], [], timeout)
        return bool(r)

    def fileno(self) -> int:
        # the doorbell read fd: multiprocessing.connection.wait
        # readiness is exact (one byte pending <=> one record poppable)
        return self._fd

    # -- lifecycle ---------------------------------------------------------

    def settle(self) -> None:
        """Post-fork fd hygiene, called once per KEPT end in each
        process: drop this process's copy of the peer's doorbell fd so
        peer death surfaces as EOF (reader side) / EPIPE (writer side)
        exactly like a broken pipe did."""
        fd, self._peer_fd = self._peer_fd, -1
        if fd >= 0:
            try:
                os.close(fd)
            except OSError:
                pass

    def close(self) -> None:
        """Close this end's fds (idempotent). Never touches the shared
        segment — a forked child must not tear the mapping out from
        under the parent; ``destroy`` does that, in the creator only."""
        if self._closed:
            return
        self._closed = True
        for fd in (self._fd, self._peer_fd):
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self._fd = self._peer_fd = -1

    def destroy(self) -> None:
        """close() plus segment release+unlink — creator process only
        (the executor's stop path calls this on the ends it kept)."""
        self.close()
        if os.getpid() != self._owner_pid:
            return
        try:
            self._ring.buf = None
            self._ring.shm.close()
        except (OSError, BufferError):
            pass
        try:
            self._ring.shm.unlink()
        except OSError:
            pass


def ring_pipe(capacity: int = 1 << 22) -> tuple[RingConn, RingConn]:
    """-> (recv_end, send_end), mirroring ``ctx.Pipe(duplex=False)``.
    Build BEFORE fork; pass the child its end through Process args (the
    fork start method does not pickle them)."""
    shm = shared_memory.SharedMemory(create=True,
                                     size=_DATA_OFF + capacity)
    shm.buf[:_DATA_OFF] = b"\x00" * _DATA_OFF
    rfd, wfd = os.pipe()
    ring = _Ring(shm, capacity)
    pid = os.getpid()
    return (RingConn(ring, rfd, wfd, "recv", pid),
            RingConn(ring, wfd, rfd, "send", pid))
