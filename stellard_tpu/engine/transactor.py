"""Transactor base: the common apply pipeline and the type registry.

Reference: src/ripple_app/transactors/Transactor.cpp —
makeTransactor (:34-84, here a decorator registry instead of the switch),
apply() = preCheck (:256-287) → account load → checkSeq (:182-253) →
payFee (:112-149) → checkSig (:151-180) → precheckAgainstLedger →
doApply.

Open-ledger semantics follow the reference exactly: in open mode apply()
returns after the checks, BEFORE doApply — the open ledger only records
the transaction; state changes happen when the close re-applies it
(Transactor.cpp:345-347).
"""

from __future__ import annotations

from typing import Callable, Optional, Type

from ..protocol.formats import LedgerEntryType, TxType
from ..protocol.sfields import (
    sfAccountTxnID,
    sfBalance,
    sfLastLedgerSequence,
    sfRegularKey,
    sfSequence,
)
from ..protocol.stamount import STAmount
from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from ..state import indexes
from ..utils.hashes import hash160
from .flags import lsfDisableMaster

__all__ = ["Transactor", "register_transactor", "make_transactor"]

_REGISTRY: dict[TxType, Type["Transactor"]] = {}

# TxParams flag values as plain ints: `int & IntFlag` falls into
# IntFlag.__rand__ (enum-member construction), measurable at flood rates
from .engine import TxParams as _TP  # no cycle: engine imports this module lazily

_OPEN_LEDGER = int(_TP.OPEN_LEDGER)
_RETRY = int(_TP.RETRY)
_ADMIN = int(_TP.ADMIN)
_NO_CHECK_SIGN = int(_TP.NO_CHECK_SIGN)
del _TP


def register_transactor(tx_type: TxType) -> Callable:
    def deco(cls: Type["Transactor"]) -> Type["Transactor"]:
        _REGISTRY[tx_type] = cls
        return cls

    return deco


def make_transactor(tx: SerializedTransaction, params: int, engine) -> Optional["Transactor"]:
    """reference: Transactor::makeTransactor (Transactor.cpp:34-84)"""
    cls = _REGISTRY.get(tx.tx_type)
    if cls is None:
        return None
    return cls(tx, params, engine)


class Transactor:
    """One transaction application. Subclasses implement do_apply()
    and may override check hooks."""

    def __init__(self, tx: SerializedTransaction, params: int, engine):
        self.tx = tx
        self.params = int(params)  # keep flag tests on the int fast path
        self.engine = engine
        self.les = engine.les
        self.account_id: bytes = b""
        self.account = None  # source account SLE working copy
        self.prior_balance = STAmount.from_drops(0)
        self.source_balance = STAmount.from_drops(0)
        self.has_auth_key = False
        self.sig_master = False
        # ledger-header mutations requested by do_apply; the engine applies
        # them only after the invariant gate passes (keys: tot_coins_delta,
        # inflation_seq_delta, fee_pool, base_fee, reference_fee_units,
        # reserve_base, reserve_increment)
        self.header_changes: dict = {}

    # -- hooks ------------------------------------------------------------

    def calculate_base_fee(self) -> int:
        """reference: Transactor::calculateBaseFee"""
        return self.engine.ledger.base_fee

    def must_have_valid_account(self) -> bool:
        return True

    def precheck_against_ledger(self) -> TER:
        return TER.tesSUCCESS

    def do_apply(self) -> TER:
        raise NotImplementedError

    # -- pipeline ---------------------------------------------------------

    def pre_check(self) -> TER:
        """reference: Transactor::preCheck (:256-287)"""
        self.account_id = self.tx.account
        if self.account_id == b"\x00" * 20 or not self.account_id:
            return TER.temBAD_SRC_ACCOUNT
        if not (self.params & _NO_CHECK_SIGN):
            if not self.tx.check_sign():
                return TER.temINVALID
        return TER.tesSUCCESS

    def check_seq(self) -> TER:
        """reference: Transactor::checkSeq (:182-253) — in open-ledger mode
        the account seq is predicted by walking the open tx map."""
        t_seq = self.tx.sequence
        a_seq = self.account[sfSequence]

        if self.params & _OPEN_LEDGER:
            # predicted seq from the open ledger's per-account cache —
            # O(1), maintained by add_open_transaction (the reference
            # walks the open tx map per tx, which is quadratic)
            cached = self.engine.ledger.open_tx_seqs.get(self.account_id)
            if cached is not None and cached + 1 > a_seq:
                a_seq = cached + 1

        if t_seq != a_seq:
            if a_seq < t_seq:
                return TER.terPRE_SEQ
            if self.engine.ledger.tx_map.get(self.tx.txid()) is not None:
                return TER.tefALREADY
            return TER.tefPAST_SEQ

        if sfAccountTxnID in self.tx.obj and (
            self.account.get(sfAccountTxnID) != self.tx.obj[sfAccountTxnID]
        ):
            return TER.tefWRONG_PRIOR
        if sfLastLedgerSequence in self.tx.obj and (
            self.engine.ledger.seq > self.tx.obj[sfLastLedgerSequence]
        ):
            return TER.tefMAX_LEDGER

        self.account[sfSequence] = t_seq + 1
        if sfAccountTxnID in self.account:
            self.account[sfAccountTxnID] = self.tx.txid()
        return TER.tesSUCCESS

    def pay_fee(self) -> TER:
        """reference: Transactor::payFee (:112-149)"""
        paid = self.tx.fee
        fee_due = STAmount.from_drops(
            self.engine.ledger.scale_fee_load(
                self.calculate_base_fee(), bool(self.params & _ADMIN)
            )
        )
        if not paid.is_native or paid.negative:
            return TER.temBAD_FEE
        if (self.params & _OPEN_LEDGER) and paid < fee_due:
            return TER.telINSUF_FEE_P
        if paid.is_zero():
            return TER.tesSUCCESS
        if self.source_balance < paid:
            return TER.terINSUF_FEE_B
        self.source_balance = self.source_balance - paid
        self.account[sfBalance] = self.source_balance
        return TER.tesSUCCESS

    def check_sig(self) -> TER:
        """Signing-key authority: master key vs regular key
        (reference: Transactor::checkSig :151-180)."""
        from ..protocol.sfields import sfFlags

        signer_id = hash160(self.tx.signing_pub_key)
        if signer_id == self.account_id:
            self.sig_master = True
            if (self.account.get(sfFlags, 0) & lsfDisableMaster) != 0:
                return TER.tefMASTER_DISABLED
            return TER.tesSUCCESS
        if self.has_auth_key and signer_id == self.account.get(sfRegularKey):
            return TER.tesSUCCESS
        if self.has_auth_key:
            return TER.tefBAD_AUTH
        return TER.temBAD_AUTH_MASTER

    def apply(self) -> TER:
        """reference: Transactor::apply (:294-353)"""
        ter = self.pre_check()
        if ter != TER.tesSUCCESS:
            return ter

        idx = indexes.account_root_index(self.account_id)
        self.account = self.les.peek(idx)
        if self.account is None:
            if self.must_have_valid_account():
                return TER.terNO_ACCOUNT
        else:
            self.prior_balance = self.account[sfBalance]
            self.source_balance = self.prior_balance
            self.has_auth_key = sfRegularKey in self.account

        ter = self.check_seq()
        if ter != TER.tesSUCCESS:
            return ter
        ter = self.pay_fee()
        if ter != TER.tesSUCCESS:
            return ter
        ter = self.check_sig()
        if ter != TER.tesSUCCESS:
            return ter
        ter = self.precheck_against_ledger()
        if ter != TER.tesSUCCESS:
            return ter

        if self.params & _OPEN_LEDGER:
            # open ledger: checks only; the close re-applies for real
            # (reference: Transactor.cpp:345-347)
            return TER.tesSUCCESS

        if self.account is not None:
            self.les.modify(idx)
        return self.do_apply()
