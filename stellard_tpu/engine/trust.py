"""TrustSet transactor.

Reference: src/ripple_app/transactors/SetTrust.cpp (406 LoC) — the full
limit/quality/flags update with per-side reserve accounting, default-state
deletion, and line creation with reserve check.
"""

from __future__ import annotations

from ..protocol.formats import TxType
from ..protocol.sfields import (
    sfFlags,
    sfHighLimit,
    sfHighQualityIn,
    sfHighQualityOut,
    sfLimitAmount,
    sfLowLimit,
    sfLowQualityIn,
    sfLowQualityOut,
    sfOwnerCount,
    sfQualityIn,
    sfQualityOut,
)
from ..protocol.stamount import ACCOUNT_ZERO, STAmount
from ..protocol.ter import TER
from ..state import indexes
from .flags import (
    lsfHighAuth,
    lsfHighNoRipple,
    lsfHighReserve,
    lsfLowAuth,
    lsfLowNoRipple,
    lsfLowReserve,
    lsfRequireAuth,
    tfClearAuth,
    tfClearNoRipple,
    tfSetNoRipple,
    tfSetfAuth,
    tfTrustSetMask,
)
from .transactor import Transactor, register_transactor
from .views import ACCOUNT_ONE, QUALITY_ONE, trust_create, trust_delete



@register_transactor(TxType.ttTRUST_SET)
class TrustSetTransactor(Transactor):
    def do_apply(self) -> TER:
        tx = self.tx
        limit_amount: STAmount = tx.obj.get(sfLimitAmount)
        if limit_amount is None:
            limit_amount = STAmount.from_drops(0)
        has_qin = sfQualityIn in tx.obj
        has_qout = sfQualityOut in tx.obj
        quality_in = tx.obj.get(sfQualityIn, 0)
        quality_out = tx.obj.get(sfQualityOut, 0)
        if quality_in == QUALITY_ONE:
            quality_in = 0
        if quality_out == QUALITY_ONE:
            quality_out = 0

        currency = limit_amount.currency
        dst_id = limit_amount.issuer
        high = self.account_id > dst_id
        flags = tx.flags

        if flags & tfTrustSetMask:
            return TER.temINVALID_FLAG
        set_auth = bool(flags & tfSetfAuth)
        clear_auth = bool(flags & tfClearAuth)
        set_no_ripple = bool(flags & tfSetNoRipple)
        clear_no_ripple = bool(flags & tfClearNoRipple)

        if set_auth and not (self.account.get(sfFlags, 0) & lsfRequireAuth):
            return TER.tefNO_AUTH_REQUIRED
        if limit_amount.is_native:
            return TER.temBAD_LIMIT
        if limit_amount.negative:
            return TER.temBAD_LIMIT
        if not dst_id or dst_id == ACCOUNT_ZERO or dst_id == ACCOUNT_ONE:
            return TER.temDST_NEEDED

        line_idx = indexes.ripple_state_index(self.account_id, dst_id, currency)

        if self.account_id == dst_id:
            # clearing a redundant self-line (reference: SetTrust.cpp:104-123)
            line = self.les.peek(line_idx)
            if line is not None:
                return trust_delete(self.les, line_idx, self.account_id, dst_id)
            return TER.temDST_IS_SRC

        dst = self.les.account_root(dst_id)
        if dst is None:
            return TER.tecNO_DST

        owner_count = self.account.get(sfOwnerCount, 0)
        # reserve needed to add a line (reference: SetTrust.cpp:135-141)
        reserve_create = (
            0 if owner_count < 2
            else self.engine.ledger.reserve(owner_count + 1)
        )

        limit_allow = STAmount.from_iou(
            currency, self.account_id, limit_amount.mantissa,
            limit_amount.offset, limit_amount.negative,
        )

        line = self.les.peek(line_idx)
        if line is not None:
            return self._modify_line(
                line, line_idx, dst_id, high, limit_allow,
                has_qin, quality_in, has_qout, quality_out,
                set_auth, clear_auth, set_no_ripple, clear_no_ripple,
                reserve_create,
            )

        # line does not exist (reference: SetTrust.cpp:357-405)
        if (
            limit_allow.is_zero()
            and (not has_qin or not quality_in)
            and (not has_qout or not quality_out)
            and not set_auth
            and not clear_auth
        ):
            return TER.tecNO_LINE_REDUNDANT
        if self.prior_balance.mantissa < reserve_create:
            return TER.tecNO_LINE_INSUF_RESERVE

        balance = STAmount.zero_like(currency, ACCOUNT_ONE)
        return trust_create(
            self.les,
            high,
            self.account_id,
            dst_id,
            line_idx,
            auth=set_auth,
            no_ripple=set_no_ripple and not clear_no_ripple,
            balance=balance,
            limit=limit_allow,
            quality_in=quality_in,
            quality_out=quality_out,
        )

    def _modify_line(self, line, line_idx, dst_id, high, limit_allow,
                     has_qin, quality_in, has_qout, quality_out,
                     set_auth, clear_auth, set_no_ripple, clear_no_ripple,
                     reserve_create) -> TER:
        """reference: SetTrust.cpp:149-356"""
        from ..protocol.sfields import sfBalance
        low_balance = line[sfBalance]
        high_balance = -low_balance
        my_balance = high_balance if high else low_balance

        line[sfHighLimit if high else sfLowLimit] = limit_allow
        low_limit = line[sfLowLimit]
        high_limit = line[sfHighLimit]

        # qualities (set / clear / keep)
        if has_qin:
            f = sfHighQualityIn if high else sfLowQualityIn
            if quality_in:
                line[f] = quality_in
            else:
                line.pop(f)
        if has_qout:
            f = sfHighQualityOut if high else sfLowQualityOut
            if quality_out:
                line[f] = quality_out
            else:
                line.pop(f)

        low_qin = line.get(sfLowQualityIn, 0)
        low_qout = line.get(sfLowQualityOut, 0)
        high_qin = line.get(sfHighQualityIn, 0)
        high_qout = line.get(sfHighQualityOut, 0)
        if low_qin == QUALITY_ONE:
            low_qin = 0
        if low_qout == QUALITY_ONE:
            low_qout = 0
        if high_qin == QUALITY_ONE:
            high_qin = 0
        if high_qout == QUALITY_ONE:
            high_qout = 0

        flags_in = line.get(sfFlags, 0)
        flags_out = flags_in

        if set_no_ripple and not clear_no_ripple and my_balance.signum() >= 0:
            flags_out |= lsfHighNoRipple if high else lsfLowNoRipple
        elif clear_no_ripple and not set_no_ripple:
            flags_out &= ~(lsfHighNoRipple if high else lsfLowNoRipple)
        if set_auth:
            flags_out |= lsfHighAuth if high else lsfLowAuth
        if clear_auth:
            flags_out &= ~(lsfHighAuth if high else lsfLowAuth)

        low_reserve_set = bool(
            low_qin or low_qout or (flags_out & lsfLowNoRipple)
            or not low_limit.is_zero() or low_balance.signum() > 0
        )
        high_reserve_set = bool(
            high_qin or high_qout or (flags_out & lsfHighNoRipple)
            or not high_limit.is_zero() or high_balance.signum() > 0
        )
        default = not low_reserve_set and not high_reserve_set
        low_reserved = bool(flags_in & lsfLowReserve)
        high_reserved = bool(flags_in & lsfHighReserve)
        reserve_increase = False

        low_id = dst_id if high else self.account_id
        high_id = self.account_id if high else dst_id

        if low_reserve_set and not low_reserved:
            self.les.adjust_owner_count(low_id, 1)
            flags_out |= lsfLowReserve
            if not high:
                reserve_increase = True
        if not low_reserve_set and low_reserved:
            self.les.adjust_owner_count(low_id, -1)
            flags_out &= ~lsfLowReserve
        if high_reserve_set and not high_reserved:
            self.les.adjust_owner_count(high_id, 1)
            flags_out |= lsfHighReserve
            if high:
                reserve_increase = True
        if not high_reserve_set and high_reserved:
            self.les.adjust_owner_count(high_id, -1)
            flags_out &= ~lsfHighReserve

        if flags_in != flags_out:
            line[sfFlags] = flags_out

        if default:
            return trust_delete(self.les, line_idx, low_id, high_id)
        if reserve_increase and self.prior_balance.mantissa < reserve_create:
            return TER.tecINSUF_RESERVE_LINE
        self.les.modify(line_idx)
        return TER.tesSUCCESS
