"""Ledger view helpers: trust lines, rippling credit, balances, offers.

Reference: the transactional helpers on LedgerEntrySet
(src/ripple_app/ledger/LedgerEntrySet.cpp): trustCreate (:1239-1312),
trustDelete (:1314-1350), rippleCredit (:1570-1650), rippleSend
(:1652-1696), accountSend (:1698-1760), rippleTransferFee,
accountHolds/accountFunds, offerDelete. Implemented as free functions over
our LedgerEntrySet.

Conventions (identical to the reference):
- a trust line (ltRIPPLE_STATE) is keyed by {low account, high account,
  currency}; sfBalance is from the LOW account's perspective with neutral
  issuer ACCOUNT_ONE; sfLowLimit/sfHighLimit carry each side's limit with
  that side as issuer.
- transfer fees: sending third-party IOUs costs amount * TransferRate
  (rate stored in the issuer's account root, 1e9 = no fee).
"""

from __future__ import annotations

from typing import Optional

from ..protocol.formats import LedgerEntryType
from ..protocol.sfields import (
    sfBalance,
    sfFlags,
    sfHighLimit,
    sfHighNode,
    sfHighQualityIn,
    sfHighQualityOut,
    sfLowLimit,
    sfLowNode,
    sfLowQualityIn,
    sfLowQualityOut,
    sfOwnerCount,
    sfTransferRate,
)
from ..protocol.stamount import STAmount
from ..protocol.ter import TER
from ..state import indexes
from ..state.entryset import LedgerEntrySet
from .flags import (
    lsfHighAuth,
    lsfHighNoRipple,
    lsfHighReserve,
    lsfLowAuth,
    lsfLowNoRipple,
    lsfLowReserve,
    lsfRequireAuth,
)

__all__ = [
    "ACCOUNT_ONE",
    "QUALITY_ONE",
    "trust_create",
    "trust_delete",
    "ripple_balance",
    "ripple_credit",
    "ripple_send",
    "account_send",
    "ripple_transfer_rate",
    "ripple_transfer_fee",
    "account_holds",
    "account_funds",
    "offer_delete",
]

ACCOUNT_ONE = (0).to_bytes(19, "big") + b"\x01"  # neutral issuer marker
QUALITY_ONE = 1_000_000_000  # 1e9 == parity (reference QUALITY_ONE)


def owner_count_adjust(les: LedgerEntrySet, account_id: bytes, delta: int) -> None:
    les.adjust_owner_count(account_id, delta)


# --------------------------------------------------------------------------
# trust lines


def trust_create(
    les: LedgerEntrySet,
    src_high: bool,
    src_id: bytes,
    dst_id: bytes,
    index: bytes,
    auth: bool,
    no_ripple: bool,
    balance: STAmount,  # balance of the account being set, issuer ACCOUNT_ONE
    limit: STAmount,  # limit for the account being charged (its issuer = that account)
    quality_in: int = 0,
    quality_out: int = 0,
) -> TER:
    """reference: LedgerEntrySet::trustCreate (LedgerEntrySet.cpp:1239)"""
    low_id = dst_id if src_high else src_id
    high_id = src_id if src_high else dst_id

    line = les.create(LedgerEntryType.ltRIPPLE_STATE, index)

    ter, low_node = les.dir_add(indexes.owner_dir_index(low_id), index)
    if ter != TER.tesSUCCESS:
        return ter
    ter, high_node = les.dir_add(indexes.owner_dir_index(high_id), index)
    if ter != TER.tesSUCCESS:
        return ter

    set_dst = limit.issuer == dst_id
    set_high = src_high ^ set_dst  # which side the limit belongs to

    line[sfLowNode] = low_node
    line[sfHighNode] = high_node
    line[sfHighLimit if set_high else sfLowLimit] = limit
    other = src_id if set_dst else dst_id
    line[sfLowLimit if set_high else sfHighLimit] = STAmount.zero_like(
        balance.currency, other
    )
    if quality_in:
        line[sfHighQualityIn if set_high else sfLowQualityIn] = quality_in
    if quality_out:
        line[sfHighQualityOut if set_high else sfLowQualityOut] = quality_out

    flags = lsfHighReserve if set_high else lsfLowReserve
    if auth:
        flags |= lsfHighAuth if set_high else lsfLowAuth
    if no_ripple:
        flags |= lsfHighNoRipple if set_high else lsfLowNoRipple
    line[sfFlags] = flags

    owner_count_adjust(les, dst_id if set_dst else src_id, 1)

    # stored balance is low-perspective
    stored = -balance if set_high else balance
    line[sfBalance] = STAmount.from_iou(
        balance.currency, ACCOUNT_ONE, stored.mantissa, stored.offset, stored.negative
    )
    return TER.tesSUCCESS


def trust_delete(les: LedgerEntrySet, line_index: bytes,
                 low_id: bytes, high_id: bytes) -> TER:
    """reference: LedgerEntrySet::trustDelete (LedgerEntrySet.cpp:1314)"""
    line = les.peek(line_index)
    if line is None:
        return TER.tefBAD_LEDGER
    low_node = line.get(sfLowNode, 0)
    high_node = line.get(sfHighNode, 0)
    ter = les.dir_delete(indexes.owner_dir_index(low_id), low_node, line_index)
    if ter != TER.tesSUCCESS:
        return ter
    ter = les.dir_delete(indexes.owner_dir_index(high_id), high_node, line_index)
    if ter != TER.tesSUCCESS:
        return ter
    les.erase(line_index)
    return TER.tesSUCCESS


def ripple_balance(les: LedgerEntrySet, account_id: bytes, issuer_id: bytes,
                   currency: bytes) -> STAmount:
    """Balance of `account_id` on its line with `issuer_id`, from the
    account's perspective (reference: rippleHolds/rippleBalance)."""
    line = les.peek(indexes.ripple_state_index(account_id, issuer_id, currency))
    if line is None:
        return STAmount.zero_like(currency, issuer_id)
    bal = line[sfBalance]
    if account_id > issuer_id:
        bal = -bal
    return STAmount.from_iou(currency, issuer_id, bal.mantissa, bal.offset,
                             bal.negative)


def ripple_credit(les: LedgerEntrySet, sender_id: bytes, receiver_id: bytes,
                  amount: STAmount, check_issuer: bool = True) -> TER:
    """Move `amount` of IOU credit from sender to receiver on their mutual
    line, creating the line if absent and deleting it when it returns to
    default (reference: rippleCredit, LedgerEntrySet.cpp:1570-1650)."""
    assert sender_id != receiver_id
    currency = amount.currency
    sender_high = sender_id > receiver_id
    index = indexes.ripple_state_index(sender_id, receiver_id, currency)
    line = les.peek(index)

    if line is None:
        balance = STAmount.from_iou(
            currency, ACCOUNT_ONE, amount.mantissa, amount.offset, amount.negative
        )
        return trust_create(
            les,
            sender_high,
            sender_id,
            receiver_id,
            index,
            auth=False,
            no_ripple=False,
            balance=balance,
            limit=STAmount.zero_like(currency, receiver_id),
        )

    balance = line[sfBalance]
    if sender_high:
        balance = -balance  # sender terms
    before = balance
    balance = balance - amount

    # RequireAuth gate (reference: PathState::pushNode:309 — "can't
    # receive IOUs from issuer without auth", terNO_AUTH): ANY movement
    # whose SENDER set lsfRequireAuth across a line lacking the
    # sender-side auth flag is refused, unconditionally of balances
    # (the reference checks the edge at path-expansion time).
    if amount.signum() > 0:
        sender_root = les.peek(indexes.account_root_index(sender_id))
        if sender_root is not None and (
            sender_root.get(sfFlags, 0) & lsfRequireAuth
        ):
            sender_auth = lsfHighAuth if sender_high else lsfLowAuth
            if not (line.get(sfFlags, 0) & sender_auth):
                return TER.terNO_AUTH

    # line returned to default on the sender's side? clear reserve/delete
    # (reference: LedgerEntrySet.cpp:1620-1650)
    flags = line.get(sfFlags, 0)
    sender_reserve = lsfHighReserve if sender_high else lsfLowReserve
    sender_no_ripple = lsfHighNoRipple if sender_high else lsfLowNoRipple
    sender_limit = line.get(sfHighLimit if sender_high else sfLowLimit)
    sender_qin = line.get(sfHighQualityIn if sender_high else sfLowQualityIn, 0)
    sender_qout = line.get(sfHighQualityOut if sender_high else sfLowQualityOut, 0)

    delete_line = False
    if (
        before.signum() > 0
        and balance.signum() <= 0
        and (flags & sender_reserve)
        and not (flags & sender_no_ripple)
        and (sender_limit is None or sender_limit.is_zero())
        and not sender_qin
        and not sender_qout
    ):
        owner_count_adjust(les, sender_id, -1)
        line[sfFlags] = flags & ~sender_reserve
        receiver_reserve = lsfLowReserve if sender_high else lsfHighReserve
        if balance.is_zero() and not (line[sfFlags] & receiver_reserve):
            delete_line = True

    if sender_high:
        balance = -balance  # back to low terms
    line[sfBalance] = STAmount.from_iou(
        currency, ACCOUNT_ONE, balance.mantissa, balance.offset, balance.negative
    )
    les.modify(index)

    if delete_line:
        low_id = receiver_id if sender_high else sender_id
        high_id = sender_id if sender_high else receiver_id
        return trust_delete(les, index, low_id, high_id)
    return TER.tesSUCCESS


def ripple_transfer_rate(les: LedgerEntrySet, issuer_id: bytes) -> int:
    """Issuer's TransferRate, 1e9 = parity
    (reference: rippleTransferRate)."""
    acct = les.account_root(issuer_id)
    if acct is None:
        return QUALITY_ONE
    rate = acct.get(sfTransferRate, 0)
    return rate if rate else QUALITY_ONE


def ripple_quality(
    les: LedgerEntrySet,
    to_id: bytes,
    from_id: bytes,
    currency: bytes,
    inbound: bool,
) -> int:
    """`to_id`'s QualityIn (inbound=True) or QualityOut on its line with
    `from_id`, 1e9 = parity; parity when absent / no line / self
    (reference: LedgerEntrySet::rippleQualityIn/Out,
    LedgerEntrySet.cpp:1225 — field picked from to_id's side of the
    line, zero clamped to 1 against divide-by-zero)."""
    from ..protocol.sfields import (
        sfHighQualityIn,
        sfHighQualityOut,
        sfLowQualityIn,
        sfLowQualityOut,
    )
    from ..state import indexes as _ix

    if to_id == from_id:
        return QUALITY_ONE
    line = les.peek(_ix.ripple_state_index(to_id, from_id, currency))
    if line is None:
        return QUALITY_ONE
    is_low = to_id < from_id
    if inbound:
        field = sfLowQualityIn if is_low else sfHighQualityIn
    else:
        field = sfLowQualityOut if is_low else sfHighQualityOut
    q = line.get(field, 0)
    if not q:
        q = QUALITY_ONE if field not in line else 1
    return q


def ripple_transfer_fee(les: LedgerEntrySet, sender_id: bytes,
                        receiver_id: bytes, issuer_id: bytes,
                        amount: STAmount) -> STAmount:
    """Fee charged by the issuer for third-party transfer
    (reference: rippleTransferFee)."""
    if sender_id != issuer_id and receiver_id != issuer_id:
        rate = ripple_transfer_rate(les, issuer_id)
        if rate != QUALITY_ONE:
            total = STAmount.multiply(
                amount,
                STAmount.from_iou(amount.currency, ACCOUNT_ONE, rate, -9),
                amount.currency,
                issuer_id,
            )
            return total - amount
    return STAmount.zero_like(amount.currency, issuer_id)


def ripple_send(les: LedgerEntrySet, sender_id: bytes, receiver_id: bytes,
                amount: STAmount) -> tuple[TER, STAmount]:
    """-> (TER, actual cost to sender). reference: rippleSend
    (LedgerEntrySet.cpp:1652-1696)."""
    issuer_id = amount.issuer
    if sender_id == issuer_id or receiver_id == issuer_id or issuer_id == ACCOUNT_ONE:
        ter = ripple_credit(les, sender_id, receiver_id, amount, check_issuer=False)
        return ter, amount
    fee = ripple_transfer_fee(les, sender_id, receiver_id, issuer_id, amount)
    actual = amount + fee if not fee.is_zero() else amount
    actual = STAmount.from_iou(actual.currency, issuer_id, actual.mantissa,
                               actual.offset, actual.negative)
    ter = ripple_credit(les, issuer_id, receiver_id, amount)
    if ter == TER.tesSUCCESS:
        ter = ripple_credit(les, sender_id, issuer_id, actual)
    return ter, actual


def account_send(les: LedgerEntrySet, sender_id: bytes, receiver_id: bytes,
                 amount: STAmount) -> TER:
    """Native or IOU transfer between accounts
    (reference: accountSend, LedgerEntrySet.cpp:1698-1760)."""
    if not amount.is_native:
        ter, _ = ripple_send(les, sender_id, receiver_id, amount)
        return ter
    sender_idx = indexes.account_root_index(sender_id)
    receiver_idx = indexes.account_root_index(receiver_id)
    sender = les.peek(sender_idx)
    receiver = les.peek(receiver_idx)
    if sender is not None:
        if sender[sfBalance] < amount:
            return TER.tecFAILED_PROCESSING
        sender[sfBalance] = sender[sfBalance] - amount
        les.modify(sender_idx)
    if receiver is not None:
        receiver[sfBalance] = receiver[sfBalance] + amount
        les.modify(receiver_idx)
    return TER.tesSUCCESS


# --------------------------------------------------------------------------
# balances / funds


def account_holds(les: LedgerEntrySet, account_id: bytes, currency: bytes,
                  issuer_id: bytes) -> STAmount:
    """Spendable balance of one asset (reference: accountHolds — native:
    balance minus reserve; IOU: line balance)."""
    if currency == b"\x00" * 20:  # native
        acct = les.account_root(account_id)
        if acct is None:
            return STAmount.from_drops(0)
        reserve = les.ledger.reserve(acct.get(sfOwnerCount, 0))
        bal = acct[sfBalance]
        avail = bal.mantissa - reserve
        return STAmount.from_drops(max(0, avail))
    bal = ripple_balance(les, account_id, issuer_id, currency)
    if bal.negative:
        return STAmount.zero_like(currency, issuer_id)
    return bal


def account_funds(les: LedgerEntrySet, account_id: bytes,
                  amount: STAmount) -> STAmount:
    """Funds available to deliver `amount` (reference: accountFunds —
    issuers of their own IOU are unlimited)."""
    if not amount.is_native and account_id == amount.issuer:
        return amount
    return account_holds(les, account_id, amount.currency, amount.issuer)


# --------------------------------------------------------------------------
# offers


def offer_delete(les: LedgerEntrySet, offer_index: bytes) -> TER:
    """Remove an offer and its directory entries
    (reference: offerDelete, LedgerEntrySet.cpp)."""
    from ..protocol.sfields import sfAccount, sfBookDirectory, sfBookNode, sfOwnerNode

    offer = les.peek(offer_index)
    if offer is None:
        return TER.tesSUCCESS
    owner = offer[sfAccount]
    ter = les.dir_delete(
        indexes.owner_dir_index(owner), offer.get(sfOwnerNode, 0), offer_index
    )
    if ter != TER.tesSUCCESS:
        return ter
    ter = les.dir_delete(
        offer[sfBookDirectory], offer.get(sfBookNode, 0), offer_index
    )
    if ter != TER.tesSUCCESS:
        return ter
    owner_count_adjust(les, owner, -1)
    les.erase(offer_index)
    return TER.tesSUCCESS
