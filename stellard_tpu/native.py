"""Loader for the native C++ components (native/libstellard_native.so).

The reference's performance-critical host components are C++ (NodeStore
backends, OpenSSL hashing — SURVEY §2 [native-perf]); this module builds
and binds their equivalents. The library is compiled on first use with
`make` (toolchain is in the image) and cached; every consumer degrades
gracefully to the pure-Python path when the toolchain or build is
unavailable, mirroring the pluggable-backend seam.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

__all__ = [
    "load_native",
    "load_stser",
    "native_available",
    "Sha512Native",
    "Ed25519HostPrep",
    "Ed25519NativeVerify",
    "CppLogLib",
    "SegIdxNative",
    "scan_segment_records",
]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libstellard_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def load_native() -> Optional[ctypes.CDLL]:
    """Build (once) and dlopen the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.isdir(_NATIVE_DIR) and not os.path.exists(_LIB_PATH):
            return None
        # always let make run its (cheap) up-to-date check: a prebuilt .so
        # from an older source tree must be refreshed, or newly added
        # symbols would be missing from the dlopened library
        if os.path.isdir(_NATIVE_DIR):
            try:
                subprocess.run(
                    ["make", "-s"],
                    cwd=_NATIVE_DIR,
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except (OSError, subprocess.SubprocessError):
                if not os.path.exists(_LIB_PATH):
                    return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        try:
            _bind(lib)
        except AttributeError:
            # stale library missing newer symbols and unrebuildable:
            # degrade to the pure-Python paths rather than crash consumers
            return None
        _lib = lib
        return _lib


def native_available() -> bool:
    return load_native() is not None


_stser_mod = None
_stser_tried = False


def load_stser():
    """Build (once) and import the _stser CPython extension (the
    STObject serializer fast path); None when the toolchain or build is
    unavailable — callers keep the pure-Python encode loop."""
    global _stser_mod, _stser_tried
    with _lock:
        if _stser_mod is not None or _stser_tried:
            return _stser_mod
        _stser_tried = True
        path = os.path.join(_NATIVE_DIR, "_stser.so")
        if os.path.isdir(_NATIVE_DIR):
            try:
                # build against the RUNNING interpreter's headers — the
                # Makefile's `python3` may be a different installation,
                # and a version-mismatched extension dlopens anyway
                # (inline object-layout macros would then misread)
                import sysconfig

                subprocess.run(
                    ["make", "-s", "_stser.so",
                     f"PY_INC={sysconfig.get_paths()['include']}"],
                    cwd=_NATIVE_DIR,
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except (OSError, subprocess.SubprocessError):
                if not os.path.exists(path):
                    return None
        if not os.path.exists(path):
            return None
        try:
            import importlib.machinery
            import importlib.util

            loader = importlib.machinery.ExtensionFileLoader("_stser", path)
            spec = importlib.util.spec_from_loader("_stser", loader)
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
        except (ImportError, OSError):
            return None
        _stser_mod = mod
        return _stser_mod


def _bind(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.sha512h_batch.argtypes = [
        ctypes.c_char_p,  # packed data
        ctypes.POINTER(ctypes.c_uint64),  # offsets[n+1]
        ctypes.POINTER(ctypes.c_uint32),  # prefixes[n]
        u8p,  # out
        ctypes.c_uint64,  # n
        ctypes.c_uint64,  # out_len
    ]
    lib.sha512h_batch.restype = None

    # newer symbols bind leniently: a stale prebuilt .so on a box where
    # `make` can't run keeps its older components (sha512/cpplog) usable
    try:
        lib.ed25519_h_batch.argtypes = [
            ctypes.c_char_p,  # packed 32B R values
            ctypes.c_char_p,  # packed 32B A (public key) values
            ctypes.c_char_p,  # packed messages
            ctypes.POINTER(ctypes.c_uint64),  # offsets[n+1]
            u8p,  # out: packed 32B h-scalars (LE, already mod l)
            ctypes.c_uint64,  # n
        ]
        lib.ed25519_h_batch.restype = None
        lib.sc_reduce_batch.argtypes = [ctypes.c_char_p, u8p, ctypes.c_uint64]
        lib.sc_reduce_batch.restype = None
        lib.has_ed25519_prep = True
    except AttributeError:
        lib.has_ed25519_prep = False

    try:
        lib.ed25519_verify_batch.argtypes = [
            ctypes.c_char_p,  # packed 32B public keys
            ctypes.c_char_p,  # packed messages
            ctypes.POINTER(ctypes.c_uint64),  # offsets[n+1]
            ctypes.c_char_p,  # packed 64B signatures
            u8p,  # out: n bytes, 1 = valid
            ctypes.c_uint64,  # n
        ]
        lib.ed25519_verify_batch.restype = None
        lib.has_ed25519_verify = True
    except AttributeError:
        lib.has_ed25519_verify = False

    # segstore primitives (segmented log-structured NodeStore) — newer
    # symbols, bound leniently like the ed25519 batch kernels
    try:
        lib.segidx_new.argtypes = [ctypes.c_uint64]
        lib.segidx_new.restype = ctypes.c_void_p
        lib.segidx_free.argtypes = [ctypes.c_void_p]
        lib.segidx_free.restype = None
        lib.segidx_count.argtypes = [ctypes.c_void_p]
        lib.segidx_count.restype = ctypes.c_uint64
        lib.segidx_put_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.segidx_put_batch.restype = ctypes.c_int
        lib.segidx_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.segidx_get.restype = ctypes.c_int64
        lib.segidx_remove.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.segidx_remove.restype = ctypes.c_int
        lib.segidx_filter_new.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, u8p,
        ]
        lib.segidx_filter_new.restype = None
        lib.segidx_dump.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64]
        lib.segidx_dump.restype = ctypes.c_uint64
        lib.segidx_load.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.segidx_load.restype = ctypes.c_int
        lib.segstore_pack.argtypes = [
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
            u8p, ctypes.c_uint64,
        ]
        lib.segstore_pack.restype = ctypes.c_int64
        lib.segstore_replay.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.segstore_replay.restype = ctypes.c_int64
        lib.has_segstore = True
    except AttributeError:
        lib.has_segstore = False

    # record-range scanner (out-of-core history shards): one C pass
    # indexes a whole file of segment-format records by key/type/offset
    try:
        lib.segrecs_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            u8p, u8p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.segrecs_scan.restype = ctypes.c_int64
        lib.has_segrecs_scan = True
    except AttributeError:
        lib.has_segrecs_scan = False

    try:
        lib.CPPLOG_ITER_CB = ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_void_p, u8p, ctypes.c_uint8, u8p,
            ctypes.c_uint32,
        )
        lib.cpplog_iterate.argtypes = [
            ctypes.c_void_p, lib.CPPLOG_ITER_CB, ctypes.c_void_p,
        ]
        lib.cpplog_iterate.restype = ctypes.c_int64
        lib.has_cpplog_iterate = True
    except AttributeError:
        lib.has_cpplog_iterate = False

    lib.cpplog_open.argtypes = [ctypes.c_char_p]
    lib.cpplog_open.restype = ctypes.c_void_p
    lib.cpplog_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint8,
        ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.cpplog_put.restype = ctypes.c_int
    lib.cpplog_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, u8p, ctypes.c_uint64,
    ]
    lib.cpplog_get.restype = ctypes.c_int64
    lib.cpplog_count.argtypes = [ctypes.c_void_p]
    lib.cpplog_count.restype = ctypes.c_uint64
    lib.cpplog_sync.argtypes = [ctypes.c_void_p]
    lib.cpplog_sync.restype = ctypes.c_int
    lib.cpplog_close.argtypes = [ctypes.c_void_p]
    lib.cpplog_close.restype = None


class Sha512Native:
    """Batched prefixed SHA-512-half over the C kernel."""

    def __init__(self):
        self.lib = load_native()
        if self.lib is None:
            raise RuntimeError("native library unavailable")

    def prefix_hash_batch(self, prefixes, payloads, out_len: int = 32) -> list[bytes]:
        n = len(payloads)
        if n == 0:
            return []
        data = b"".join(payloads)
        offsets = (ctypes.c_uint64 * (n + 1))()
        pos = 0
        for i, p in enumerate(payloads):
            offsets[i] = pos
            pos += len(p)
        offsets[n] = pos
        pfx = (ctypes.c_uint32 * n)(*[int(p) & 0xFFFFFFFF for p in prefixes])
        out = (ctypes.c_uint8 * (n * out_len))()
        self.lib.sha512h_batch(
            data, offsets, pfx, out, n, out_len
        )
        raw = bytes(out)
        return [raw[i * out_len : (i + 1) * out_len] for i in range(n)]

    def hash_packed(self, buf: bytes, offsets, out_len: int = 32) -> list[bytes]:
        """Batched SHA-512-half over PACKED messages: `buf` holds every
        message back to back (domain prefixes already embedded — the
        SHAMap flat-buffer node encoding), `offsets` is the n+1 boundary
        list. Zero per-message Python objects cross into C: one buffer,
        one offsets array, one call (sha512h_batch with NULL prefixes)."""
        n = len(offsets) - 1
        if n <= 0:
            return []
        arr = (ctypes.c_uint64 * (n + 1))(*offsets)
        out = (ctypes.c_uint8 * (n * out_len))()
        self.lib.sha512h_batch(bytes(buf), arr, None, out, n, out_len)
        raw = bytes(out)
        return [raw[i * out_len : (i + 1) * out_len] for i in range(n)]


class Ed25519HostPrep:
    """Batched h = SHA512(R||A||M) mod l over the C kernel (threaded).

    The per-signature host work feeding ops.ed25519_jax.verify_kernel,
    done in one ctypes call instead of a Python loop."""

    def __init__(self):
        self.lib = load_native()
        if self.lib is None:
            raise RuntimeError("native library unavailable")
        if not getattr(self.lib, "has_ed25519_prep", False):
            raise RuntimeError("native library predates ed25519_h_batch")

    def h_batch(self, rs: bytes, pubs: bytes, messages, n: int) -> "np.ndarray":
        """rs/pubs: packed 32-byte-per-element buffers; messages: sequence
        of bytes. Returns [n, 32] uint8 h-scalars (LE, reduced mod l)."""
        import numpy as np

        messages = list(messages)  # may be a generator; we iterate twice
        if len(messages) != n or len(rs) != 32 * n or len(pubs) != 32 * n:
            raise ValueError(
                f"h_batch: inconsistent batch (n={n}, msgs={len(messages)}, "
                f"rs={len(rs)}, pubs={len(pubs)})"
            )
        offsets = (ctypes.c_uint64 * (n + 1))()
        pos = 0
        for i, m in enumerate(messages):
            offsets[i] = pos
            pos += len(m)
        offsets[n] = pos
        packed = b"".join(messages)
        out = np.empty((n, 32), np.uint8)
        self.lib.ed25519_h_batch(
            rs, pubs, packed, offsets,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n,
        )
        return out


class Ed25519NativeVerify:
    """Batched full Ed25519 verification over the C++ kernel
    (native/src/ed25519_verify.cc) — the libsodium role of the reference
    (StellarPublicKey::verifySignature) without the per-call interpreter
    and GIL costs of the one-at-a-time host library path."""

    def __init__(self):
        self.lib = load_native()
        if self.lib is None:
            raise RuntimeError("native library unavailable")
        if not getattr(self.lib, "has_ed25519_verify", False):
            raise RuntimeError("native library predates ed25519_verify_batch")

    def verify_batch(self, publics, messages, signatures) -> "np.ndarray":
        """publics/signatures: sequences of 32/64-byte strings; messages:
        sequence of bytes. Returns a bool ndarray of per-item validity.
        Malformed-length items are rejected (False) without touching the
        C layer, mirroring keys.verify_signature's length gates."""
        import numpy as np

        n = len(publics)
        if not (len(messages) == len(signatures) == n):
            raise ValueError("verify_batch: ragged batch")
        ok_shape = [
            len(publics[i]) == 32 and len(signatures[i]) == 64
            for i in range(n)
        ]
        idx = [i for i in range(n) if ok_shape[i]]
        out = np.zeros(n, bool)
        if not idx:
            return out
        offsets = (ctypes.c_uint64 * (len(idx) + 1))()
        pos = 0
        for j, i in enumerate(idx):
            offsets[j] = pos
            pos += len(messages[i])
        offsets[len(idx)] = pos
        raw = (ctypes.c_uint8 * len(idx))()
        self.lib.ed25519_verify_batch(
            b"".join(publics[i] for i in idx),
            b"".join(messages[i] for i in idx),
            offsets,
            b"".join(signatures[i] for i in idx),
            raw,
            len(idx),
        )
        out[idx] = np.frombuffer(bytes(raw), np.uint8).astype(bool)
        return out


class SegIdxNative:
    """Native open-addressed key→loc index for the segstore backend
    (key = 32-byte content hash, loc = (seg_id << 44) | record_offset).
    NOT thread-safe — the owning backend serializes access under its own
    lock. The pure-Python mirror lives in nodestore/segstore.py and is
    differential-tested against this."""

    def __init__(self, cap_hint: int = 0):
        self.lib = load_native()
        if self.lib is None or not getattr(self.lib, "has_segstore", False):
            raise RuntimeError("native segstore primitives unavailable")
        self._h = self.lib.segidx_new(cap_hint)
        if not self._h:
            raise MemoryError("segidx_new failed")

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self.lib.segidx_free(h)

    def __len__(self) -> int:
        return int(self.lib.segidx_count(self._h))

    def get(self, key: bytes):
        loc = self.lib.segidx_get(self._h, key)
        return None if loc < 0 else int(loc)

    def put_batch(self, packed_keys: bytes, locs: list[int]) -> None:
        n = len(locs)
        arr = (ctypes.c_uint64 * n)(*locs)
        if self.lib.segidx_put_batch(self._h, n, packed_keys, arr) != 0:
            raise ValueError("segidx_put_batch: loc out of range")

    def remove(self, key: bytes, expect_loc=None) -> bool:
        exp = (2**64 - 1) if expect_loc is None else int(expect_loc)
        return bool(self.lib.segidx_remove(self._h, key, exp))

    def filter_new(self, packed_keys: bytes, n: int) -> bytes:
        """Byte mask: 1 where keys[i] is absent from the index (in-batch
        duplicates also masked off after their first occurrence)."""
        out = (ctypes.c_uint8 * n)()
        self.lib.segidx_filter_new(self._h, n, packed_keys, out)
        return bytes(out)

    def dump(self) -> bytes:
        """Checkpoint image: live entries as [32B key | u64 loc LE]."""
        n = len(self)
        out = (ctypes.c_uint8 * (n * 40))()
        got = self.lib.segidx_dump(self._h, out, n)
        return bytes(out[: int(got) * 40])

    def load(self, blob: bytes) -> None:
        n = len(blob) // 40
        if self.lib.segidx_load(self._h, blob, n) != 0:
            raise ValueError("segidx_load: corrupt checkpoint entry")

    def pack_records(self, packed_keys: bytes, types: bytes, buf,
                     offsets) -> bytes:
        """One-call append image from the flat-buffer node encoding."""
        n = len(types)
        arr = (ctypes.c_uint64 * (n + 1))(*offsets)
        cap = (len(buf) if not isinstance(buf, memoryview) else buf.nbytes) \
            + n * 38
        out = (ctypes.c_uint8 * cap)()
        got = self.lib.segstore_pack(
            n, packed_keys, types, bytes(buf), arr, out, cap
        )
        if got < 0:
            raise ValueError("segstore_pack failed")
        return bytes(out[: int(got)])

    def replay(self, path: str, seg_id: int, start: int) -> tuple:
        """Scan one segment file into the index; returns
        (clean_end_offset, records, bytes)."""
        recs = ctypes.c_uint64(0)
        byts = ctypes.c_uint64(0)
        end = self.lib.segstore_replay(
            self._h, path.encode(), seg_id, start,
            ctypes.byref(recs), ctypes.byref(byts),
        )
        if end < 0:
            raise OSError(f"segstore_replay failed: {path}")
        return int(end), int(recs.value), int(byts.value)


def scan_segment_records(path: str, start: int = 0):
    """Index a file of segment-format records in one native pass:
    [(key, type_byte, blob_offset, blob_len)] for every clean record —
    key/type/offset only, blobs stay on disk for decode-on-demand
    (the history-shard open path). Returns None when the native seam is
    unavailable (callers fall back to the Python struct loop)."""
    lib = load_native()
    if lib is None or not getattr(lib, "has_segrecs_scan", False):
        return None
    p = path.encode()
    n = lib.segrecs_scan(p, start, 0, None, None, None, None)
    if n < 0:
        raise OSError(f"segrecs_scan failed: {path}")
    n = int(n)
    if n == 0:
        return []
    keys = (ctypes.c_uint8 * (32 * n))()
    types = (ctypes.c_uint8 * n)()
    offs = (ctypes.c_uint64 * n)()
    lens = (ctypes.c_uint64 * n)()
    got = lib.segrecs_scan(p, start, n, keys, types, offs, lens)
    if got < 0:
        raise OSError(f"segrecs_scan failed: {path}")
    got = min(int(got), n)  # a concurrently-truncated tail fills fewer
    kb = bytes(keys)
    return [
        (kb[32 * i: 32 * i + 32], int(types[i]), int(offs[i]),
         int(lens[i]))
        for i in range(got)
    ]


class CppLogLib:
    """ctypes handle for one cpplog store. Thread-safe via a Python lock
    (the C side shares one FILE* between reads and appends)."""

    def __init__(self, path: str):
        self.lib = load_native()
        if self.lib is None:
            raise RuntimeError("native library unavailable")
        self._handle = self.lib.cpplog_open(path.encode())
        if not self._handle:
            raise OSError(f"cpplog_open failed: {path}")
        self._lock = threading.Lock()
        self._buf = (ctypes.c_uint8 * 65536)()

    def put(self, key: bytes, type_byte: int, blob: bytes) -> None:
        assert len(key) == 32
        with self._lock:
            rc = self.lib.cpplog_put(
                self._handle, key, type_byte, blob, len(blob)
            )
        if rc != 0:
            raise OSError("cpplog_put failed")

    def get(self, key: bytes) -> Optional[tuple[int, bytes]]:
        assert len(key) == 32
        with self._lock:
            n = self.lib.cpplog_get(
                self._handle, key, self._buf, len(self._buf)
            )
            if n <= -2:
                # -2 - needed_length: retry with an exact-size buffer
                # (one-off; the shared buffer keeps its normal size)
                need = int(-2 - n)
                big = (ctypes.c_uint8 * need)()
                n = self.lib.cpplog_get(self._handle, key, big, need)
                if n < 0:
                    raise OSError("cpplog_get failed after resize")
                raw = bytes(big[: int(n)])
                return raw[0], raw[1:]
            if n < 0:
                return None
            raw = bytes(self._buf[: int(n)])
        return raw[0], raw[1:]

    def count(self) -> int:
        with self._lock:
            return int(self.lib.cpplog_count(self._handle))

    def iterate(self):
        """Yield every live (key, type_byte, blob) record. The native
        callback scan snapshots into a Python list under the store lock
        (the C side shares one FILE* with appends), then yields outside
        it so consumers can interleave fetches/puts."""
        if not getattr(self.lib, "has_cpplog_iterate", False):
            raise OSError("native library predates cpplog_iterate")
        out: list[tuple[bytes, int, bytes]] = []

        def cb(_ctx, key, type_byte, blob, length):
            out.append((
                bytes(key[:32]), int(type_byte),
                bytes(blob[:length]) if length else b"",
            ))
            return 0

        cfun = self.lib.CPPLOG_ITER_CB(cb)
        with self._lock:
            n = self.lib.cpplog_iterate(self._handle, cfun, None)
        if n < 0:
            raise OSError("cpplog_iterate failed")
        return iter(out)

    def sync(self) -> None:
        with self._lock:
            rc = self.lib.cpplog_sync(self._handle)
        if rc != 0:
            # the store is failed (earlier torn write) or fsync failed:
            # callers must NOT believe the batch is durable
            raise OSError("cpplog_sync failed")

    def close(self) -> None:
        with self._lock:
            if self._handle:
                self.lib.cpplog_close(self._handle)
                self._handle = None
