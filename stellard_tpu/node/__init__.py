"""Node runtime: config, typed async executor, ledger chain state
machine, network operations brain, and the application container.

Reference layers L5/L8/L10 (SURVEY §1): src/ripple_core/functional,
src/ripple_app/misc/NetworkOPs.cpp, src/ripple_app/main/Application.cpp.
"""

from .config import Config
from .jobqueue import Job, JobQueue, JobType
from .hashrouter import HashRouter, SF_BAD, SF_RELAYED, SF_SAVED, SF_SIGGOOD, SF_TRUSTED
from .verifyplane import VerifyPlane
from .ledgermaster import LedgerMaster
from .networkops import NetworkOPs, OperatingMode
from .node import Node

__all__ = [
    "Config",
    "Job",
    "JobQueue",
    "JobType",
    "HashRouter",
    "SF_BAD",
    "SF_RELAYED",
    "SF_SAVED",
    "SF_SIGGOOD",
    "SF_TRUSTED",
    "VerifyPlane",
    "LedgerMaster",
    "NetworkOPs",
    "OperatingMode",
    "Node",
]
