"""Archive tier: full-history reporting nodes (doc/archive.md).

Production XRPL moved heavy history/API traffic off validators entirely
(reporting mode / Clio), while this repo's validators deliberately SHED
history: online deletion seals retiring ledger runs into
offline-verifiable shards (nodestore/shards.py) and trims. The archive
role re-assembles those pieces into "years of history, queryable at
scale":

- **tail ingest**: an archive runs the follower ingest plane unchanged
  (validation tailing + GetSegments catch-up, doc/follower.md);
- **deep-history backfill**: :class:`ShardBackfill` — the shard
  distribution network's fetch side. Peers advertise held shard seq
  ranges in their segment manifests (``lo``/``hi``/``file_bytes`` row
  fields, nonzero-only on the wire); the backfill selects uncovered
  ranges and fetches COMPLETE shard files over the existing
  GetSegments door (ids offset by ``SHARD_FILE_BASE``), so the
  transferred image is exactly what the offline verification contract
  covers. Every import is gated by ``verify_shard_blob`` — a peer whose
  shard fails verification is condemned (resource-charged via the
  overlay's ``charge_peer``, excluded for the session) and ZERO hostile
  bytes are retained;
- **full-history indexes**: :func:`feed_shard` fans a verified import
  out to the archive's nodestore (deep ``ledger``/state queries resolve
  through the ordinary lazy ``Ledger.load`` path) and its
  :class:`ArchiveTxDatabase` — a txdb with NO retain floor, fed in
  ``(ledger_seq, txn_seq)`` order, that refuses to trim;
- **forever cache**: the archive's verified floor (the contiguous
  sealed-shard coverage, ``HistoryShardStore.contiguous_floor``) feeds
  the read plane's immutable-seq result tier (rpc/readplane.py): any
  result whose window closes at or below the floor is cached forever,
  not swapped per epoch.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..nodestore.shards import SHARD_FILE_BASE, SHARD_SEG_BASE
from ..overlay.wire import GetSegments
from .txdb import TxDatabase

__all__ = ["ArchiveTxDatabase", "ShardBackfill", "feed_shard"]

# NodeObjectType values (nodestore.core) mirrored from shards.py so the
# feed walk stays self-contained
_T_LEDGER = 1


class ArchiveTxDatabase(TxDatabase):
    """Full-history txdb: the retain floor NEVER rises. The archive
    tier's contract is that every historical row stays queryable, so
    `trim_below` — the SQL half of online deletion — is a loud
    RuntimeError here, not a silent no-op: wiring [node_db] sql_trim or
    online deletion into an archive is an operator error, and an error
    that parses clean and drops rows would be the exact dead-config
    class the config plane rejects everywhere else."""

    def trim_below(self, ledger_seq: int) -> dict:
        raise RuntimeError(
            "archive txdb never trims: mode=archive keeps full history "
            "(doc/archive.md); disable [node_db] online_delete/sql_trim"
        )


def feed_shard(shardstore, sid: int, store: Optional[Callable] = None,
               txdb: Optional[TxDatabase] = None) -> dict:
    """Fan ONE verified, just-imported shard out to the archive's other
    stores: every record into the nodestore sink (``store(type_byte,
    key, blob)`` — deep-history ``ledger`` and state queries then
    resolve through the ordinary lazy ``Ledger.load`` path) and the
    never-trimming txdb — ledger headers first, then tx rows in
    ``(ledger_seq, txn_seq)`` order, statuses recovered from each tx's
    metadata result byte exactly like catch-up-adopted closes. The
    affected-accounts set comes from the shard's OWN account index rows
    (the set recorded at seal time), so the rebuilt SQL index
    byte-matches the sealed one instead of re-deriving from metadata."""
    from ..state.ledger import parse_header
    from ..utils.hashes import HP_LEDGER_MASTER

    ledger_prefix = HP_LEDGER_MASTER.to_bytes(4, "big")
    headers: list[dict] = []
    n_records = 0
    for key, type_byte, blob in shardstore.iter_records(sid):
        n_records += 1
        if store is not None:
            try:
                store(type_byte, key, blob)
            except Exception:  # noqa: BLE001 — one failed local write
                pass           # must not abort the whole import feed
        if type_byte == _T_LEDGER and blob[:4] == ledger_prefix:
            h = parse_header(blob[4:])
            h["hash"] = key
            headers.append(h)
    out = {"records": n_records, "headers": len(headers), "txs": 0}
    if txdb is None:
        return out
    if headers:
        txdb.save_header_dicts(sorted(headers, key=lambda h: h["seq"]))
    # group the account-index rows by txid: one Transactions row per tx,
    # every account sharing the txid becomes its affected set
    by_txid: dict[bytes, dict] = {}
    for acct, lseq, tseq, txid in shardstore.acct_rows(sid):
        ent = by_txid.setdefault(
            txid, {"accounts": [], "ledger_seq": lseq, "txn_seq": tseq}
        )
        ent["accounts"].append(acct)
    rows = []
    for txid, ent in sorted(
        by_txid.items(),
        key=lambda kv: (kv[1]["ledger_seq"], kv[1]["txn_seq"]),
    ):
        got = shardstore.tx_blob(sid, txid)
        if got is None:
            continue  # index row without a record: skip, not crash
        raw, meta = got
        tx_type, account, seq = "", b"", 0
        try:
            from ..protocol.sttx import SerializedTransaction

            tx = SerializedTransaction.from_bytes(raw)
            tx_type = tx.tx_type.name
            account = tx.account
            seq = tx.sequence
        except Exception:  # noqa: BLE001 — an unparseable tx still gets
            pass           # its raw/meta row (binary-mode serving works)
        rows.append((
            txid, tx_type, account, seq, ent["ledger_seq"],
            _meta_status(meta), raw, meta,
            ent["accounts"] or [account],
            ent["txn_seq"],
        ))
    if rows:
        txdb.save_transactions(rows)
    out["txs"] = len(rows)
    return out


def _meta_status(meta: Optional[bytes]) -> str:
    """TER token from the tx metadata's result byte (the import feed
    never applied these txs locally — same stance as adopted closes)."""
    from ..protocol.ter import TER

    if meta:
        try:
            from ..protocol.sfields import sfTransactionResult
            from ..protocol.stobject import STObject

            code = STObject.from_bytes(meta).get(sfTransactionResult)
            if code is not None:
                return TER(code).token
        except Exception:  # noqa: BLE001 — unparseable meta: default
            pass
    return TER.tesSUCCESS.token


class ShardBackfill:
    """Deep-history shard fetcher: the archive side of the shard
    distribution network (see module doc).

    Transport-agnostic and clock-driven like SegmentCatchup — the owner
    supplies ``send(peer, msg)``, ``peers()``, a monotonic ``clock()``
    and the target :class:`~..nodestore.shards.HistoryShardStore`;
    ``tick(now)`` drives timeouts/retries AND the session lifecycle
    (self-arming: an idle backfill rescans peers' manifests every
    ``rescan_s`` for newly sealed shards, so the archive keeps tracking
    the validators' rotation without an external trigger).

    Correctness stance: the ONLY install door is
    ``HistoryShardStore.import_shard``, which runs the full offline
    verification contract against the transferred image in memory
    first. A failing image condemns the serving peer — resource charge
    via ``on_condemn`` (the owner wires TcpOverlay.charge_peer with
    FEE_GARBAGE_SEGMENT), byzantine note, session exclusion — and the
    same shard is refetched from the next-best peer; zero hostile bytes
    are ever retained."""

    # a finished session re-arms after this long (fresh-manifest rescan
    # cadence); transfer failure re-arms on the same clock
    GROWTH_SLACK = 8 << 20
    # absolute per-shard-file ceiling, manifest or not
    MAX_SHARD_TRANSFER = 512 << 20

    def __init__(
        self,
        send: Callable[[object, object], None],
        peers: Callable[[], list],
        shardstore,
        clock: Callable[[], float],
        request_timeout: float = 4.0,
        max_retries: int = 8,
        backoff_base: float = 1.0,
        backoff_max: float = 30.0,
        rescan_s: float = 30.0,
        grace_s: float = 2.0,
        seed: int = 0,
        note_byzantine: Optional[Callable] = None,
        on_imported: Optional[Callable[[dict], None]] = None,
        on_condemn: Optional[Callable] = None,
    ):
        import random

        from .metrics import AtomicCounters

        # one lock for every public entry point: TCP replies land on
        # per-peer reader threads while tick() runs on the timer thread
        self._lock = threading.RLock()
        self.send = send
        self.peers = peers
        self.shardstore = shardstore
        self.clock = clock
        self.request_timeout = request_timeout
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.rescan_s = rescan_s
        self.rng = random.Random(0xA2C1 ^ seed)
        self.note_byzantine = note_byzantine
        self.on_imported = on_imported
        self.on_condemn = on_condemn
        self.active = False
        self.state = "idle"  # idle | manifest | fetch | done | fallback
        self._next_scan = grace_s  # vs a monotonic clock starting ~0
        self._started_once = False
        self.counters = AtomicCounters(
            "started", "completed", "requests", "replies", "timeouts",
            "retries", "backoffs", "peer_switches", "garbage_peers",
            "fallbacks", "imported", "duplicates", "import_rejects",
            "bytes", "late_replies", "epoch_restarts", "rescans",
        )
        self._reset_session()

    def _reset_session(self) -> None:
        # queue rows: (file_seg_id, advertised_file_bytes, lo, hi)
        self._queue: list[tuple[int, int, int, int]] = []
        self._cur: Optional[tuple[int, int, int, int]] = None
        self._buf = bytearray()
        self._want: Optional[tuple] = None  # ("manifest",) | ("file", id)
        self._deadline: Optional[float] = None
        self._backoff_until = 0.0
        self._attempts = 0
        self._peer = None
        self._peer_failures: dict = {}
        self._bad_peers: set = set()
        self._snap_epoch = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> bool:
        """Begin (or ignore if already running) a backfill session."""
        with self._lock:
            if self.active:
                return False
            self._reset_session()
            self.active = True
            self._started_once = True
            self.state = "manifest"
            self._want = ("manifest",)
            self.counters.add("started")
            self._send_current(self.clock())
            return True

    def stop(self) -> None:
        with self._lock:
            self.active = False
            self.state = "idle"
            self._want = None

    # -- peer selection (SegmentCatchup's fewest-failures discipline) ------

    def _eligible_peers(self) -> list:
        return [p for p in self.peers() if p not in self._bad_peers]

    def _pick_peer(self):
        cands = self._eligible_peers()
        if not cands:
            return None
        return min(
            cands, key=lambda p: (self._peer_failures.get(p, 0),
                                  cands.index(p))
        )

    def _maybe_switch_peer(self) -> None:
        best = self._pick_peer()
        if best is not None and best != self._peer:
            self._peer = best
            self.counters.add("peer_switches")

    # -- request machinery -------------------------------------------------

    def _send_current(self, now: float) -> None:
        if self._want is None:
            return
        if self._peer is None:
            self._peer = self._pick_peer()
        if self._peer is None:
            self._fallback("no_peers")
            return
        if self._want[0] == "manifest":
            msg = GetSegments(-1, 0)
        else:
            msg = GetSegments(self._want[1], len(self._buf),
                              snap_epoch=self._snap_epoch)
        self.counters.add("requests")
        self._deadline = now + self.request_timeout
        try:
            self.send(self._peer, msg)
        except Exception:  # noqa: BLE001 — a dead transport is a timeout
            pass

    def tick(self, now: float) -> None:
        """Timeout/backoff clock + the self-arming session lifecycle."""
        with self._lock:
            if not self.active:
                if now >= self._next_scan:
                    if self._started_once:
                        self.counters.add("rescans")
                    self._next_scan = now + self.rescan_s
                    self.start()
                return
            self._tick_locked(now)

    def _tick_locked(self, now: float) -> None:
        if self._want is None:
            return
        if self._deadline is not None and now >= self._deadline:
            self._deadline = None
            self.counters.add("timeouts")
            if self._peer is not None:
                self._peer_failures[self._peer] = (
                    self._peer_failures.get(self._peer, 0) + 1
                )
            self._attempts += 1
            if self._attempts > self.max_retries:
                self._fallback("retries_exhausted")
                return
            delay = min(
                self.backoff_max,
                self.backoff_base * (2 ** (self._attempts - 1)),
            )
            delay *= 1.0 + 0.25 * self.rng.random()  # jitter
            self._backoff_until = now + delay
            self.counters.add("backoffs")
            self._maybe_switch_peer()
            return
        if self._deadline is None and now >= self._backoff_until:
            self.counters.add("retries")
            self._send_current(now)

    # -- replies -----------------------------------------------------------

    def on_manifest(self, peer, segments: list, epoch: int = 0,
                    snap_seq: int = 0) -> None:
        """Select the peer's advertised shard rows this archive does not
        cover yet (range selection — never probe), translating each
        manifest id into its whole-file door id."""
        with self._lock:
            if not self.active or self._want != ("manifest",):
                self.counters.add("late_replies")
                return
            if peer != self._peer:
                self.counters.add("late_replies")
                return
            self.counters.add("replies")
            self._attempts = 0
            self._deadline = None
            self._snap_epoch = int(epoch)
            queue = []
            for row in segments:
                rid = int(row[0])
                if not (SHARD_SEG_BASE <= rid < SHARD_FILE_BASE):
                    continue  # live segstore rows: the tail ingest's job
                lo = int(row[4]) if len(row) > 4 else 0
                hi = int(row[5]) if len(row) > 5 else 0
                fbytes = int(row[6]) if len(row) > 6 else 0
                if lo <= 0 or hi < lo:
                    continue  # pre-range peer: cannot select, skip
                if (self.shardstore.covers(lo) is not None
                        and self.shardstore.covers(hi) is not None):
                    continue  # already held
                fid = SHARD_FILE_BASE + (rid - SHARD_SEG_BASE)
                queue.append((fid, fbytes, lo, hi))
            queue.sort(key=lambda r: r[2])  # oldest history first
            self._queue = queue
            if not self._queue:
                self._complete()
                return
            self.state = "fetch"
            self._next_shard()

    def _next_shard(self) -> None:
        if not self._queue:
            self._complete()
            return
        self._cur = self._queue.pop(0)
        self._buf = bytearray()
        self._want = ("file", self._cur[0])
        self._send_current(self.clock())

    def on_data(self, peer, msg) -> None:
        with self._lock:
            if (
                not self.active
                or self._want is None
                or self._want[0] != "file"
                or msg.seg_id != self._want[1]
                or peer != self._peer
                or msg.offset != len(self._buf)
            ):
                self.counters.add("late_replies")
                return
            self.counters.add("replies")
            self._attempts = 0
            self._deadline = None
            if (
                msg.snap_epoch
                and self._snap_epoch
                and msg.snap_epoch != self._snap_epoch
            ):
                # the source's sealed set moved under us: restart from a
                # fresh manifest instead of splicing two snapshots
                self.counters.add("epoch_restarts")
                self.state = "manifest"
                self._want = ("manifest",)
                self._queue = []
                self._buf = bytearray()
                self._cur = None
                self._snap_epoch = 0
                self._send_current(self.clock())
                return
            # transfer-size defense: advertised file size + slack, and a
            # hard ceiling — a hostile total never buys unbounded RAM
            advertised = self._cur[1] if self._cur else 0
            limit = min(
                self.MAX_SHARD_TRANSFER,
                (advertised + self.GROWTH_SLACK) if advertised
                else self.MAX_SHARD_TRANSFER,
            )
            if msg.total > limit or len(self._buf) + len(msg.data) > limit:
                self._condemn_peer(peer, "oversized_transfer")
                return
            if len(self._buf) < msg.total and not msg.data:
                self._condemn_peer(peer, "short_transfer")
                return
            self._buf.extend(msg.data)
            if len(self._buf) < msg.total:
                self._send_current(self.clock())  # next chunk
                return
            self._import_current(peer)

    def _condemn_peer(self, peer, why: str) -> None:
        """This peer served a shard that failed verification (or a
        hostile transfer shape): charge + exclude it, refetch the SAME
        shard from the next-best peer. Only an out-of-peers session
        falls back (the tail ingest keeps the archive live)."""
        self.counters.add("garbage_peers")
        if self.note_byzantine is not None:
            self.note_byzantine(
                "garbage_segment", peer=None,
                seg=self._cur[0] if self._cur else None, why=why,
            )
        if self.on_condemn is not None:
            try:
                self.on_condemn(peer)
            except Exception:  # noqa: BLE001 — the charge is bookkeeping
                pass
        self._bad_peers.add(peer)
        self._peer = None
        if not self._eligible_peers():
            self._fallback("all_peers_garbage")
            return
        self._buf = bytearray()
        self._maybe_switch_peer()
        self._send_current(self.clock())

    def _import_current(self, peer) -> None:
        """Verify-then-install the completed transfer. import_shard runs
        the full offline contract in memory BEFORE the store directory
        is touched; a rejected image retains zero bytes and condemns
        the serving peer."""
        data = bytes(self._buf)
        self._buf = bytearray()
        res = self.shardstore.import_shard(data)
        if not res.get("ok"):
            self.counters.add("import_rejects")
            self._condemn_peer(peer, "shard_verify_failed")
            return
        if res.get("duplicate"):
            self.counters.add("duplicates")
        else:
            self.counters.add("imported")
            self.counters.add("bytes", len(data))
            if self.on_imported is not None:
                try:
                    self.on_imported(res)
                except Exception:  # noqa: BLE001 — a failed index feed
                    pass           # must not kill the session
        self._next_shard()

    # -- terminal states ---------------------------------------------------

    def _complete(self) -> None:
        self.active = False
        self.state = "done"
        self._want = None
        self._next_scan = self.clock() + self.rescan_s
        self.counters.add("completed")

    def _fallback(self, reason: str) -> None:
        """Give up on THIS session (no peers / retries exhausted / every
        peer served garbage); the rescan clock re-arms a fresh one, so a
        bad episode never disables backfill forever."""
        self.active = False
        self.state = "fallback"
        self._want = None
        self._next_scan = self.clock() + self.rescan_s
        self.counters.add("fallbacks")

    def get_json(self) -> dict:
        out = self.counters.snapshot()
        with self._lock:
            out["state"] = self.state
            out["active"] = self.active
            out["queue"] = len(self._queue)
            out["snap_epoch"] = self._snap_epoch
            out["verified_floor"] = self.shardstore.contiguous_floor()
        return out
