"""ClosePipeline: ordered async persistence for closed ledgers.

Reference shape: Ledger::pendSaveValidated hands the just-accepted
ledger to a JobQueue worker so the close path never waits on the disk
(Ledger.cpp pendSaveValidated → savePostponedLedger). The TPU build
makes that stage explicit and strictly ordered:

- a bounded FIFO of sealed ledgers drained by ONE dedicated worker, so
  ledger N's NodeStore flush / tx-row insert / CLF commit run while
  ledger N+1 is already applying on the close path;
- ordered CLF commits: the single drain order guarantees the resume
  pointer never observes N+1 before N (concurrent workers could not);
- backpressure: when the queue is `depth` deep, the next close BLOCKS in
  submit() instead of pinning an unbounded backlog of whole Ledgers in
  memory — a disk that cannot keep up slows closes, never the process;
- read-your-writes: a queued-but-unpersisted ledger resolves from its
  in-flight entry (by hash, seq, or contained txid), so RPC/history
  lookups between close and persist never miss;
- drain-on-stop: stop() persists everything already queued before the
  worker exits, so the CLF pointer lands on the last closed ledger.

The pipeline is storage-agnostic: the node passes the three stage
callables (NodeStore save, txdb header+rows, CLF commit) and gets
per-stage latency histograms + queue-depth gauges back via get_json()
(surfaced in `server_state` / `get_counts`).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .metrics import LatencyHist

log = logging.getLogger("stellard.closepipeline")

# LatencyHist moved to node.metrics (one percentile implementation for
# the whole node); re-exported here for existing importers
__all__ = ["ClosePipeline", "LatencyHist"]


@dataclass
class _Entry:
    kind: str  # "close" (all stages) | "repair" (no CLF) | "task" (fn)
    ledger: object  # None for "task" entries
    results: dict
    done: Optional[Callable] = None  # done(results) after persist, in order
    on_failed: Optional[Callable] = None
    fn: Optional[Callable] = None  # "task" body, runs on the drain worker
    enqueued_at: float = field(default_factory=time.perf_counter)


class ClosePipeline:
    """Bounded, strictly-ordered persistence stage for closed ledgers."""

    def __init__(
        self,
        save_stage: Callable,          # save_stage(ledger) -> NodeStore flush
        txdb_stage: Callable,          # txdb_stage(ledger, results) -> rows
        clf_stage: Callable,           # clf_stage(ledger) -> CLF commit
        recover_results: Optional[Callable] = None,  # ledger -> {txid: TER}
        depth: int = 8,
        name: str = "ledger-persist",
        tracer=None,
    ):
        from .tracer import get_tracer

        self.save_stage = save_stage
        self.txdb_stage = txdb_stage
        self.clf_stage = clf_stage
        self.recover_results = recover_results
        self.tracer = tracer if tracer is not None else get_tracer()
        self.depth = max(1, int(depth))
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        self._queue: list[_Entry] = []
        self._active: Optional[_Entry] = None  # entry being persisted now
        self._by_hash: dict[bytes, _Entry] = {}
        self._by_seq: dict[int, _Entry] = {}
        self._stopping = False
        # metrics
        self.persisted = 0
        self.failed = 0
        self.depth_hwm = 0
        self.backpressure_waits = 0
        self.backpressure_ms = 0.0
        self.stage_hist = {
            "queue_wait": LatencyHist(),  # enqueue -> drain start
            "nodestore": LatencyHist(),
            "txdb": LatencyHist(),
            "clf": LatencyHist(),
            "total": LatencyHist(),
        }
        self._name = name
        # worker starts lazily on first submit: a Node constructed and
        # discarded without stop() must not leak a polling daemon thread
        self._thread: Optional[threading.Thread] = None

    def _ensure_worker(self) -> None:
        """Start the drain worker on first use; caller holds self._lock."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._drain, name=self._name, daemon=True
            )
            self._thread.start()

    # -- submission --------------------------------------------------------

    def submit_close(self, ledger, results: dict,
                     done: Optional[Callable] = None,
                     on_failed: Optional[Callable] = None) -> None:
        """Queue a freshly-closed ledger for full persistence (NodeStore +
        tx rows + ordered CLF commit). Blocks when the queue is full."""
        self._submit(_Entry("close", ledger, results, done, on_failed),
                     self.depth)

    def submit_repair(self, ledger, results: Optional[dict] = None,
                      done: Optional[Callable] = None,
                      on_failed: Optional[Callable] = None) -> None:
        """Queue a HISTORICAL ledger (cleaner repair / catch-up): data only,
        never the CLF resume pointer (it must not move backwards). Bounded
        more generously than closes — the cleaner's own in-flight cap is
        the real limiter — and each kind counts only against its OWN
        limit, so a repair burst can never back-pressure the consensus
        tick through the shared queue."""
        self._submit(_Entry("repair", ledger, results or {}, done, on_failed),
                     max(self.depth, 256))

    def submit_task(self, fn: Callable, done: Optional[Callable] = None,
                    on_failed: Optional[Callable] = None) -> None:
        """Queue a storage-maintenance task to run ON the drain worker,
        in order with the persists around it. The online-deletion sweep
        applies through here: while the task runs, no save_stage can be
        mid-flight, so a flush that already passed its known-set check
        can never land after the sweep deleted the nodes it skipped."""
        self._submit(
            _Entry("task", None, {}, done, on_failed, fn=fn),
            max(self.depth, 256),
        )

    @staticmethod
    def _fail(entry: _Entry) -> None:
        """Fire the submitter's failure accounting; its exceptions must
        never propagate into the pipeline."""
        if entry.on_failed is not None:
            try:
                entry.on_failed()
            except Exception:  # noqa: BLE001
                pass

    def _kind_depth(self, kind: str) -> int:
        return sum(1 for e in self._queue if e.kind == kind)

    def _submit(self, entry: _Entry, limit: int) -> None:
        with self._not_full:
            if self._stopping:
                # never strand the submitter's accounting on shutdown
                self._fail(entry)
                return
            if self._kind_depth(entry.kind) >= limit:
                self.backpressure_waits += 1
                t0 = time.perf_counter()
                while (self._kind_depth(entry.kind) >= limit
                       and not self._stopping):
                    self._not_full.wait(timeout=1.0)
                self.backpressure_ms += (time.perf_counter() - t0) * 1000.0
                if self._stopping:
                    # stop() fired while we were blocked: the drain worker
                    # may already have exited — appending now would strand
                    # the entry forever with neither callback fired
                    self._fail(entry)
                    return
            # stamped at APPEND, after any backpressure wait: queue_wait
            # must measure drain latency, not re-count backpressure_ms
            entry.enqueued_at = time.perf_counter()
            self._queue.append(entry)
            self._ensure_worker()
            self.depth_hwm = max(self.depth_hwm, len(self._queue))
            if entry.ledger is not None:
                h = entry.ledger.hash()
                self._by_hash[h] = entry
                self._by_seq[entry.ledger.seq] = entry
            self._not_empty.notify()

    # -- read-your-writes lookups -----------------------------------------

    def get(self, ledger_hash: bytes):
        """Queued-or-persisting ledger by hash, else None."""
        with self._lock:
            e = self._by_hash.get(ledger_hash)
            return e.ledger if e is not None else None

    def get_by_seq(self, seq: int):
        """Queued-or-persisting ledger by sequence, else None."""
        with self._lock:
            e = self._by_seq.get(seq)
            return e.ledger if e is not None else None

    def lookup_tx(self, txid: bytes) -> Optional[tuple]:
        """(ledger, tx_blob, meta_blob, results) for a tx inside any
        in-flight ledger — the txdb-miss resolver for the `tx` RPC."""
        with self._lock:
            entries = list(self._by_seq.values())
        for e in entries:
            found = e.ledger.get_transaction(txid)
            if found is not None:
                return e.ledger, found[0], found[1], e.results
        return None

    def pending(self) -> int:
        with self._lock:
            return len(self._queue) + (1 if self._active is not None else 0)

    # -- drain worker ------------------------------------------------------

    def _drain(self) -> None:
        while True:
            with self._not_empty:
                while not self._queue and not self._stopping:
                    self._not_empty.wait(timeout=1.0)
                if not self._queue:
                    # stopping and drained
                    self._idle.notify_all()
                    return
                entry = self._queue.pop(0)
                self._active = entry
                # all waiters: limits are per-kind, and a single notify
                # could wake a waiter whose own kind is still at limit
                self._not_full.notify_all()
            ok = False
            try:
                self._persist(entry)
                self.persisted += 1
                ok = True
            except Exception:  # noqa: BLE001 — keep persisting later ledgers
                self.failed += 1
                if entry.ledger is not None:
                    log.exception(
                        "persist failed for ledger seq %d", entry.ledger.seq
                    )
                else:
                    log.exception("pipeline task failed")
                self._fail(entry)
            finally:
                with self._lock:
                    self._active = None
                    if entry.ledger is not None:
                        h = entry.ledger.hash()
                        if self._by_hash.get(h) is entry:
                            del self._by_hash[h]
                        if self._by_seq.get(entry.ledger.seq) is entry:
                            del self._by_seq[entry.ledger.seq]
                    # every completion notifies: wait_for_closes watches
                    # individual entries, not just the queue-empty edge
                    self._idle.notify_all()
            if ok and entry.done is not None:
                # OUTSIDE the persist accounting: all storage stages
                # committed — a publish/WS-sink error must not read as a
                # phantom persistence failure (nor double-release the
                # cleaner's in-flight slot via on_failed)
                try:
                    entry.done(entry.results)
                except Exception:  # noqa: BLE001
                    log.exception(
                        "post-persist callback failed for ledger seq %d",
                        entry.ledger.seq,
                    )

    def _persist(self, entry: _Entry) -> None:
        if entry.kind == "task":
            entry.fn()
            return
        t_start = time.perf_counter()
        seq = entry.ledger.seq
        tr = self.tracer
        self.stage_hist["queue_wait"].record(
            (t_start - entry.enqueued_at) * 1000.0
        )
        tr.complete("persist.queue_wait", "persist", entry.enqueued_at,
                    t_start, seq=seq)
        results = entry.results
        if not results and self.recover_results is not None:
            # ledger we never applied locally (catch-up adoption / history
            # repair): recover per-tx results from the sfTransactionResult
            # metadata byte so stored history and streams report real codes
            results = self.recover_results(entry.ledger)
            entry.results = results

        t0 = time.perf_counter()
        self.save_stage(entry.ledger)
        t1 = time.perf_counter()
        self.stage_hist["nodestore"].record((t1 - t0) * 1000.0)
        tr.complete("persist.nodestore", "persist", t0, t1, seq=seq)
        self.txdb_stage(entry.ledger, results)
        t2 = time.perf_counter()
        self.stage_hist["txdb"].record((t2 - t1) * 1000.0)
        tr.complete("persist.txdb", "persist", t1, t2, seq=seq)
        if entry.kind == "close":
            self.clf_stage(entry.ledger)
            t3 = time.perf_counter()
            self.stage_hist["clf"].record((t3 - t2) * 1000.0)
            tr.complete("persist.clf", "persist", t2, t3, seq=seq)
        t_end = time.perf_counter()
        self.stage_hist["total"].record((t_end - t_start) * 1000.0)
        tr.complete("persist.total", "persist", t_start, t_end, seq=seq,
                    kind=entry.kind, txs=len(results or ()))
        # per-tx persist marks close out each SAMPLED transaction's
        # causal tree (submit → verify → apply → close → persist); runs
        # on the drain worker, off the close path, and the sampling gate
        # bounds it
        if results and tr.enabled:
            for txid in results:
                if tr.sampled(txid):
                    tr.instant("persist.tx", "persist", txid=txid,
                               ledger_seq=seq)

    # -- lifecycle ---------------------------------------------------------

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until everything queued so far is persisted. True when
        drained, False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._idle:
            while self._queue or self._active is not None:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._idle.wait(timeout=remaining if remaining else 1.0)
        return True

    def wait_for_closes(self, timeout: float = 10.0) -> bool:
        """Block until every CLOSE entry pending AT CALL TIME is
        persisted (repairs and later arrivals excluded — this is the
        bounded read-your-writes barrier for the SQL-index RPCs). True
        when they all landed, False on timeout."""
        with self._lock:
            targets = [
                (e.ledger.hash(), e)
                for e in self._queue if e.kind == "close"
            ]
            if self._active is not None and self._active.kind == "close":
                targets.append((self._active.ledger.hash(), self._active))
        if not targets:
            return True
        deadline = time.monotonic() + timeout
        with self._idle:
            while any(
                self._by_hash.get(h) is e or self._active is e
                for h, e in targets
            ):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(timeout=min(remaining, 1.0))
        return True

    def stop(self, timeout: float = 60.0) -> bool:
        """Drain the queue, then stop the worker. True when fully drained
        (nothing persisted is lost; the CLF pointer lands on the last
        closed ledger), False when the timeout expired first."""
        with self._lock:
            self._stopping = True
            self._not_empty.notify_all()
            self._not_full.notify_all()
            t = self._thread
        if t is None:
            return True  # worker never started: nothing ever queued
        t.join(timeout=timeout)
        if t.is_alive():
            log.error(
                "shutdown with ~%d ledgers still unpersisted", self.pending()
            )
            return False
        return True

    # -- metrics -----------------------------------------------------------

    def get_json(self) -> dict:
        with self._lock:
            depth = len(self._queue) + (1 if self._active is not None else 0)
        return {
            "depth": depth,
            "depth_limit": self.depth,
            "depth_hwm": self.depth_hwm,
            "persisted": self.persisted,
            "failed": self.failed,
            "backpressure_waits": self.backpressure_waits,
            "backpressure_ms": round(self.backpressure_ms, 3),
            "stages": {
                name: h.get_json() for name, h in self.stage_hist.items()
            },
        }
