"""Node configuration: INI-style sections, CLI-friendly overrides.

Reference: src/ripple_core/functional/Config.cpp (816 LoC) parses
``stellard.cfg`` sections listed in ConfigSections.h:39-98. This config
keeps the same section names where they exist and adds the TPU-native
knobs the north star requires (``[signature_backend]``, ``[hash_backend]``,
batch-window tuning) following the same pattern as the reference's
``[node_db] type=...`` pluggable-factory selection
(doc/stellard-example.cfg:795-802).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

__all__ = ["Config", "parse_ini_sections"]


def parse_ini_sections(text: str) -> dict[str, list[str]]:
    """Parse the reference's cfg format: ``[section]`` headers followed by
    value lines; ``#``/``;`` comments; later duplicate sections extend
    earlier ones (reference: Config::load / ParseSection)."""
    sections: dict[str, list[str]] = {}
    current: Optional[str] = None
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#") or line.startswith(";"):
            continue
        if line.startswith("[") and line.endswith("]"):
            current = line[1:-1].strip().lower()
            sections.setdefault(current, [])
            continue
        if current is not None:
            sections[current].append(line)
    return sections


def _kv(lines: list[str]) -> dict[str, str]:
    out = {}
    for line in lines:
        if "=" in line:
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def _reject_unknown(section: str, kv: dict, known: tuple) -> None:
    """The crypto-plane sections fail LOUDLY on unknown keys: before
    this, a typo'd (or never-plumbed) option like use_mesh= parsed
    clean and silently did nothing — dead config an operator believes
    is applied (ISSUE 15)."""
    unknown = sorted(set(kv) - set(known))
    if unknown:
        raise ValueError(
            f"[{section}] unknown key(s) {unknown}; known: {sorted(known)}"
        )


def _crypto_mesh(section: str, backend: str, kv: dict, default: str) -> str:
    """Validated `mesh=` for a crypto section: parse_mesh canonicalizes
    (0/N/auto; garbage raises), and a mesh request on a HOST backend is
    a loud config error — the operator believes chips are in play."""
    from ..crypto.backend import parse_mesh

    if "mesh" not in kv:
        return default
    mesh = parse_mesh(kv["mesh"])
    if mesh != "0" and backend not in ("tpu",):
        raise ValueError(
            f"[{section}] mesh={kv['mesh']} is meaningless with "
            f"type={backend} (host backends have no mesh); use type=tpu "
            "or mesh=0"
        )
    return mesh


def _crypto_routing(section: str, kv: dict) -> str:
    if "routing" not in kv:
        return ""
    routing = kv["routing"].strip().lower()
    if routing not in ("cost", "device"):
        # a routing toggle must not fail open into an unintended mode
        raise ValueError(
            f"[{section}] routing must be cost/device, got {routing!r}"
        )
    return routing


def _crypto_backend_gate(section: str, backend: str, kv: dict,
                         device_only: tuple, host_only: tuple = ()) -> None:
    """Keys that only a device (tpu) backend honors are a loud error
    with a host type, and vice versa — otherwise they would parse clean
    and be silently dropped downstream, recreating the exact dead-config
    class _reject_unknown exists to eliminate."""
    if backend != "tpu":
        bad = sorted(k for k in device_only if k in kv)
        if bad:
            raise ValueError(
                f"[{section}] {bad} only apply to type=tpu "
                f"(type={backend} would silently drop them)"
            )
    else:
        bad = sorted(k for k in host_only if k in kv)
        if bad:
            raise ValueError(
                f"[{section}] {bad} only apply to host backends "
                f"(type=tpu would silently drop them)"
            )


def resolve_spec_workers(workers, cpu_count=None, log=None) -> int:
    """Resolve ``[spec] workers`` to a concrete pool size at node setup.

    Integers pass through. ``"auto"`` resolves from ``os.cpu_count()``
    capped at 8 — and below 4 physical cores it LOUDLY disables the
    pool (returns 1, the inline serial path) instead of silently losing
    throughput: on a small box the pool's submit+committer overhead
    exceeds the serial speculation cost it replaces."""
    if workers != "auto":
        return int(workers)
    ncpu = cpu_count if cpu_count is not None else (os.cpu_count() or 1)
    if ncpu < 4:
        if log is not None:
            log.warning(
                "[spec] workers=auto: %d core(s) < 4 — parallel "
                "speculation pool DISABLED (inline serial path); the "
                "pool's IPC overhead would exceed the serial cost on "
                "this box", ncpu,
            )
        return 1
    return min(8, ncpu)


# default [kernel_tuning] path, shared with Node's outcome logging
DEFAULT_KERNEL_TUNING = "KERNEL_TUNING.json"


@dataclass
class Config:
    # -- run modes (reference Config.h RUN_STANDALONE / START_UP) ---------
    standalone: bool = True
    start_up: str = "fresh"  # fresh | load
    ledger_history: int = 256  # reference [ledger_history]
    # [node] mode=validator|follower|archive — follower is the read-only
    # tier (doc/follower.md): no consensus rounds, validated ledgers
    # ingested from the net (bulk GetSegments catch-up + validation
    # tailing), reads served from the last validated snapshot with the
    # result cache on by default. "archive" is the full-history
    # reporting tier (doc/archive.md): follower ingest of the validated
    # tail PLUS deep-history backfill of sealed shards from peers, a
    # txdb that never trims, and forever-cached immutable-seq results.
    # "validator" is the classic networked node.
    node_mode: str = "validator"
    # [node] upstream= "host port" lines (follower trees, doc/follower.md):
    # a follower dials THESE instead of [ips] as its serving tier —
    # naming a peer FOLLOWER here cascades the validated-ledger tail and
    # the GetSegments catch-up door one tier down, so the leader's
    # egress is bounded by its direct children, not the fleet. Empty =
    # dial [ips] (the flat PR 9 topology). Ignored on validators.
    node_upstream: list[str] = field(default_factory=list)

    # -- archive tier ([archive], doc/archive.md) --------------------------
    # shard-import directory for mode=archive (the archive's OWN sealed
    # set, distinct from [node_db] shards=). "" derives
    # <node_db path or database_path>.archive-shards.
    archive_path: str = ""
    # backfill=0 disables the deep-history fetcher (tail-only archive);
    # on by default — an archive that never backfills is a follower
    archive_backfill: int = 1
    # re-poll peers' manifests for newly sealed shards every N seconds
    archive_rescan_s: float = 30.0

    # -- storage ([node_db], [database_path]) ------------------------------
    node_db_type: str = "memory"
    node_db_path: str = ""
    node_db_compression: str = ""  # "" | zlib (cpplog snappy-role knob)
    # segstore durability: fsync (one fsync per flush batch — the
    # default), batch (group commit: one fsync per group_commit_ms
    # window), async (page cache only outside rolls/checkpoints/close)
    node_db_durability: str = "fsync"
    node_db_group_commit_ms: float = 5.0
    node_db_segment_mb: int = 64       # segment roll size
    node_db_checkpoint_mb: int = 32    # index snapshot every N MB appended
    node_db_compact_ratio: float = 0.5  # rewrite segments below this live%
    # online deletion (rippled SHAMapStore online_delete role): retain N
    # validated ledgers; unreachable nodes are mark-and-swept and their
    # segments compacted so disk stays bounded near the live set. 0=off.
    node_db_online_delete: int = 0
    # sweep every K validated ledgers (0 = retain/2)
    node_db_online_delete_interval: int = 0
    # trim txdb SQL history rows (tx/account-tx/ledger headers) below
    # the same retention horizon on the same drain worker (the
    # NodeStore sweep alone leaves the SQL mirror growing forever)
    node_db_sql_trim: int = 1
    # history shards ([node_db] shards=): directory where online-
    # deletion rotation SEALS the retired range as offline-verifiable
    # shard files before deleting it — below-floor account_tx and
    # cold-node catch-up serve from these instead of lgrIdxInvalid
    # (doc/storage.md "History shards"). "1" derives <path>.shards from
    # the node_db path; empty = off (trimmed history is discarded).
    node_db_shards: str = ""
    node_db_synchronous: str = ""      # sqlite PRAGMA synchronous= pass
    database_path: str = ""

    # -- crypto plane (TPU-native knobs; pattern of [node_db] type=) -------
    signature_backend: str = "cpu"  # cpu | tpu
    hash_backend: str = "cpu"  # cpu | tpu
    verify_batch_window_ms: float = 2.0  # coalescing window
    verify_max_batch: int = 16384
    verify_min_device_batch: int = 64  # below this, CPU path is used
    # mesh= is the multi-chip width axis (GSPMD stance): 0 = no mesh
    # (which still runs the SAME sharded program at width 1 — width is
    # config, not a code path), N = shard the batch dimension over N
    # chips, auto = every visible device. Widths beyond the visible
    # device count clamp with a warning. Only meaningful on device
    # backends — mesh= with a host type is a loud config error.
    verify_mesh: str = "auto"
    hash_mesh: str = "auto"
    # routing= cost (default: measured-latency host/1-chip/N-chip
    # routing) | device (force every eligible batch onto the widest
    # arm — the anti-vacuity mode smokes/benches use)
    verify_routing: str = ""  # "" = env default (STELLARD_VERIFY_ROUTING)
    hash_routing: str = ""    # "" = env default (STELLARD_HASH_ROUTING)
    # host-side thread pool for the cpu signature backend
    verify_threads: int = 4
    # device-wedge watchdog deadlines (utils.devicewatch defaults when
    # None) — previously constructor-only, unreachable from any cfg
    verify_device_first_timeout_s: Optional[float] = None
    verify_device_warm_timeout_s: Optional[float] = None
    hash_device_first_timeout_s: Optional[float] = None
    # flat-batch device floor for the hash plane (None = the
    # make_watched_hasher default / STELLARD_HASH_MIN_DEVICE_NODES)
    hash_min_device_nodes: Optional[int] = None
    # [kernel_tuning]: path to an on-chip sweep's KERNEL_TUNING.json —
    # applied as env defaults at node setup so a daemon honors the
    # measured kernel winner (default: the file name in the CWD, if
    # any; "none"/"off" disables)
    kernel_tuning: str = DEFAULT_KERNEL_TUNING

    # -- ledger-close pipeline ([close_pipeline]) --------------------------
    # enabled=1: standalone closes hand persistence (NodeStore flush,
    # tx rows, ordered CLF commit) to the bounded pipeline worker so
    # ledger N persists while N+1 applies; enabled=0 is the serial
    # fallback (persist in-line on the close path). depth bounds the
    # queue — a full queue back-pressures the next close.
    close_pipeline_enabled: bool = True
    close_pipeline_depth: int = 8

    # -- state-tree commit plane ([tree]) ----------------------------------
    # incremental=1: speculated writes fold into a pre-seal building
    # tree that a background drainer hashes through the routed hash
    # plane between closes, so the in-close seal adopts the pre-hashed
    # root and hashes only the residual (state/shamap.py bulk_update +
    # engine/deltareplay.py). incremental=0 is the kill-switch: the
    # full serial seal, which also remains the automatic per-close
    # fallback whenever adoption cannot apply. drain_batch is how many
    # folded writes accumulate before a background drain fires — bigger
    # batches suit the device kernel, smaller ones keep less residual.
    tree_incremental_seal: bool = True
    tree_drain_batch: int = 256
    # cache_mb bounds the process-wide hot-node cache — the resident
    # set of the out-of-core state plane (state/hotcache.py): lazy
    # trees fault nodes from the NodeStore through this cache and RSS
    # stays near the budget regardless of ledger size
    tree_cache_mb: int = 256
    # fused=1 (default): whole dirty trees hash through the device
    # hasher's fused level-chained pipeline (hash_tree) — digests stay
    # device-resident across levels, ONE readback per tree. fused=0 is
    # the kill-switch: the staged per-level hash_packed path, one
    # round-trip per level — kept as the fused-vs-staged identity leg.
    tree_fused: bool = True

    # -- admission control ([txq]) -----------------------------------------
    # enabled=1: post-verify intake routes through the TxQ (node/txq.py)
    # — a soft per-ledger cap adapted to measured close capacity, an
    # escalating open-ledger fee above it, and a bounded fee-priority
    # queue with per-account sequence chains, replace-by-fee, cheapest-
    # first eviction and close-time promotion. enabled=0 is the
    # kill-switch: the direct-apply path, byte-for-byte.
    txq_enabled: bool = True
    txq_ledgers_in_queue: int = 20    # queue bound = soft cap x this
    txq_account_cap: int = 10         # max queued txs per account
    txq_retry_fee_pct: int = 25       # replace-by-fee bump requirement
    txq_retention_ledgers: int = 20   # queued-entry expiry horizon
    txq_min_cap: int = 256            # soft-cap floor (txs per ledger)
    txq_max_cap: int = 100_000        # soft-cap ceiling
    txq_target_close_ms: float = 2000.0  # close budget the cap targets

    # -- parallel speculation ([spec]) -------------------------------------
    # workers=N (N>1): submitted and TxQ-promoted transactions execute
    # speculatively across an N-worker Block-STM pool with optimistic
    # read validation and ordered commit at the chain's speculation
    # index (engine/specexec.py); the close drains the window before
    # splicing. workers=1 (default) is the kill-switch: the serial
    # inline speculation path, byte-for-byte. mode selects the worker
    # transport: "process" (fork workers around the GIL — the scaling
    # path), "thread" (in-process, GIL-bound — the concurrency-hammer
    # configuration), "manual" (no workers; tests drive seeded
    # schedules). max_retries bounds optimistic re-execution before the
    # committing thread falls back to a serial in-order apply;
    # drain_timeout_s bounds how long a close waits on the pool before
    # completing the window serially itself. workers=auto resolves from
    # os.cpu_count() at node setup (resolve_spec_workers): capped at 8,
    # and below 4 cores the pool is LOUDLY disabled (workers=1, inline
    # serial) instead of silently losing throughput to IPC overhead.
    # transport selects the process-worker wire: "ring" (shared-memory
    # SPSC rings + pickle-free codec, engine/specring.py — the default)
    # or "pipe" (the PR 6 pickled multiprocessing.Pipe wire).
    spec_workers: int | str = 1
    spec_mode: str = "process"
    spec_max_retries: int = 3
    spec_drain_timeout_s: float = 10.0
    spec_transport: str = "ring"

    # -- ledger close ([close]) --------------------------------------------
    # delta_replay=1: the open-ledger accept also executes the tx once in
    # close mode against a speculative overlay, recording its read/write
    # sets; the close then splices recorded deltas whose reads still
    # validate instead of re-running the transactor, falling back to the
    # full serial apply per tx on any conflict (engine/deltareplay.py).
    # delta_replay=0 is the always-available serial path.
    close_delta_replay: bool = True

    # -- network identity / trust ([validation_seed], [validators]) --------
    validation_seed: str = ""  # base58 seed; empty = not a validator
    validators: list[str] = field(default_factory=list)  # node public keys
    # same-operator cluster members ([cluster_nodes], ConfigSections.h:40):
    # members relay each other's load-fee reports (mtCLUSTER) so the
    # whole cluster escalates fees together. List the key each member
    # proves in its peer hello — its VALIDATION public when it
    # validates, its node identity public otherwise
    cluster_nodes: list[str] = field(default_factory=list)
    validators_file: str = ""  # local validators.txt ([validators_file])
    validators_site: str = ""  # hosted stellar.txt URL ([validators_site])
    validation_quorum: int = 1  # reference Config.h:406 default sizing
    consensus_threshold: int = 0  # Stellar addition (Config.h:407)

    # -- ops ([sntp_servers], [insight]) -----------------------------------
    sntp_servers: list[str] = field(default_factory=list)  # host[:port]
    insight: str = ""  # '' | 'statsd:host:port[:prefix]'
    # embedded metrics history (node/metrics.py MetricsHistory): bounded
    # ring of instrument snapshots every history_interval seconds kept
    # for history_window seconds, served by the `metrics_history` admin
    # RPC and scraped by the `GET /metrics` Prometheus door. history=0
    # disables sampling (and with it the health watchdog's metric rules).
    insight_history: bool = True
    insight_history_interval: float = 5.0
    insight_history_window: float = 300.0

    # -- tracing plane ([trace]) -------------------------------------------
    # enabled=1 (default): transaction-lifecycle spans recorded into a
    # bounded ring buffer (node/tracer.py), exported via the
    # trace_status/trace_dump admin RPCs (Chrome trace-event JSON) and
    # span-derived stage percentiles through [insight]. sample is the
    # deterministic per-transaction sampling rate (ledger-scoped spans
    # are always recorded); capacity bounds the ring.
    trace_enabled: bool = True
    trace_capacity: int = 16384
    trace_sample: float = 0.125
    # propagate=1 (default): outbound tx/proposal/validation/segment
    # frames carry a TraceContext extension (wire field 60) so spans on
    # different nodes join one causal tree; deterministic per-txid
    # sampling means every node samples the same transactions.
    # propagate=0 is the kill switch: frames are byte-identical to the
    # pre-extension wire, and inbound contexts are stripped on decode.
    trace_propagate: bool = True

    # -- SLO health watchdog + flight recorder ([health]) ------------------
    # node/health.py: EWMA/threshold rules over the metrics history —
    # close cadence stalls/drift, validation lag, fanout delivery p99,
    # verify/hash routing flips, cache hit collapse, persist backlog —
    # surfacing ok/warn/critical (with reasons) in server_state and
    # get_counts, plus an always-on bounded flight recorder dumped to
    # disk on crash, degradation to TRACKING, or health transitions.
    health_enabled: bool = True
    health_stall_warn_s: float = 12.0
    health_stall_crit_s: float = 45.0
    health_drift_factor: float = 2.5
    health_lag_warn: int = 4
    health_lag_crit: int = 16
    health_fanout_p99_warn_ms: float = 250.0
    health_flips_warn: int = 8
    health_cache_hit_warn: float = 0.10
    health_persist_depth_warn: float = 512.0
    health_flight_dir: str = ""  # '' = <database_path>/flight
    health_flight_spans: int = 2048

    # -- subscription fanout ([subs]) --------------------------------------
    # shards=N partitions InfoSub/RPCSub event delivery across N worker
    # threads (subscribers pinned to one shard so per-client order
    # holds); 0 delivers inline on the publishing thread (the legacy
    # path — one slow consumer then stalls publish for everyone).
    # sendq_cap bounds each client's pending-event queue (drop-OLDEST
    # on overflow: a slow reader sees a gap, never a stale stream);
    # evict_drops is the consecutive-drop threshold after which a slow
    # consumer is evicted outright. Counters ride get_counts `subs`.
    subs_shards: int = 4
    subs_sendq_cap: int = 512
    subs_evict_drops: int = 64
    # RPCSub HTTP-push retry (reference RPCSub keeps a retry deque):
    # bounded attempts with exponential backoff + jitter per event
    subs_push_retries: int = 5
    # resume_horizon=N keeps the last N published ledgerClosed events in
    # a bounded replay ring: a reconnecting client presents its
    # last-delivered seq and replays the gap instead of re-subscribing
    # cold; a cursor past the horizon gets an explicit cold-resubscribe
    # answer, never a silent gap (doc/follower.md). 0 disables resume.
    subs_resume_horizon: int = 1024

    # -- liquidity plane ([paths]) -----------------------------------------
    # The production path_find read plane (paths/plane.py, ISSUE 17):
    # enabled=0 removes the plane entirely (path RPCs fall back to the
    # on-demand per-request library). incremental=0 is the kill-switch
    # that forces a full OrderBookDB rebuild per close, pinned
    # result-identical to the incremental write-set advance.
    # device_prune=0 disables the device-batched candidate pre-ranking;
    # prune_floor/prune_keep bound when/how it prunes (sets at or below
    # the floor are never touched). max_updates_per_close caps how many
    # path subscriptions re-rank per validated close (the rest shed,
    # stalest-first next close). mesh/min_device_batch/routing shape the
    # evaluator's host/1-chip/N-chip routing exactly like
    # [hash_backend]'s (parse_mesh values; routing cost|device|host).
    paths_enabled: bool = True
    paths_incremental: bool = True
    paths_device_prune: bool = True
    paths_prune_floor: int = 64
    paths_prune_keep: int = 32
    paths_max_updates_per_close: int = 256
    paths_mesh: str = "0"
    paths_min_device_batch: int = 256
    paths_routing: str = "cost"

    # -- validated-seq result cache ([rpc_cache]) --------------------------
    # whole-result memo for the hot read RPCs (account_info,
    # book_offers, ledger, account_tx), keyed by validated ledger seq —
    # entries are immutable by construction and a new validated seq
    # invalidates the whole generation (rpc/readplane.py). size=0 off.
    rpc_cache_size: int = 8192

    # -- API doors ([rpc_*], [websocket_*]) --------------------------------
    rpc_ip: str = "127.0.0.1"
    rpc_port: Optional[int] = None  # None = disabled, 0 = ephemeral
    # connections from these source IPs get ADMIN role (reference:
    # [rpc_admin_allow]); everything else is GUEST
    admin_ips: list[str] = field(default_factory=lambda: ["127.0.0.1", "::1"])
    websocket_ip: str = "127.0.0.1"
    websocket_port: Optional[int] = None  # None = disabled, 0 = ephemeral
    # TLS on the API doors (reference [rpc_secure]/[websocket_secure],
    # ConfigSections.h:85-86 + Config.cpp:475-492). Cert/key paths are
    # optional: empty means auto-generate a self-signed transport cert in
    # the state dir (same machinery as the peer links, overlay/peertls.py)
    rpc_secure: int = 0
    rpc_ssl_cert: str = ""  # [rpc_ssl_cert]
    rpc_ssl_key: str = ""  # [rpc_ssl_key]
    websocket_secure: int = 0
    websocket_ssl_cert: str = ""  # [websocket_ssl_cert]
    websocket_ssl_key: str = ""  # [websocket_ssl_key]

    # -- overlay ([peer_ip]/[peer_port]/[ips]/[overlay]) -------------------
    peer_ip: str = "127.0.0.1"
    peer_port: int = 0  # 0 = disabled
    ips: list[str] = field(default_factory=list)  # bootstrap peers host:port
    # [overlay] defense plane (doc/overlay.md): squelch= is the relay
    # subset size per validator (0 = full flood, the kill-switch that
    # reproduces pre-squelch behavior byte-for-byte);
    # squelch_rotate= ledgers per subset rotation epoch; sendq_cap=
    # bounds each peer's outbound queue (drop-oldest on overflow, 0 =
    # built-in default) and sendq_evict_drops= is the consecutive-drop
    # threshold that evicts a wedged peer; rpc_resource= prices RPC
    # clients with the peer charge schedule (admin IPs exempt)
    overlay_squelch: int = 8
    overlay_squelch_rotate: int = 16
    overlay_sendq_cap: int = 0
    overlay_sendq_evict_drops: int = 0
    overlay_rpc_resource: bool = True
    # [peer_ssl]: "" = plaintext, "allow" = TLS out + autodetect in,
    # "require" = TLS only (plaintext peers refused). Reference peers are
    # always SSL (PeerImp.h:88-90); "allow" exists for mixed-net upgrades.
    peer_ssl: str = ""
    # test-net accelerator: virtual seconds per real second for the
    # overlay clock (consensus windows shrink accordingly; 1.0 = live)
    clock_speed: float = 1.0

    # -- ops ([node_size], fees, [debug_logfile]) --------------------------
    node_size: str = "tiny"  # tiny|small|medium|large|huge (thread sizing)
    fee_default: int = 10
    debug_logfile: str = ""  # full-severity log mirror on disk
    network_time_offset: int = 0

    @classmethod
    def from_ini(cls, text: str) -> "Config":
        s = parse_ini_sections(text)
        cfg = cls()

        def one(name: str, default: str = "") -> str:
            vals = s.get(name, [])
            return vals[0] if vals else default

        if "standalone" in s:
            cfg.standalone = one("standalone", "1") not in ("0", "false", "no")
        cfg.start_up = one("start_up", cfg.start_up).lower()
        node_sec = _kv(s.get("node", []))
        if "mode" in node_sec:
            cfg.node_mode = node_sec["mode"].lower()
            if cfg.node_mode not in ("validator", "follower", "archive"):
                # a mode toggle must not fail open into a validator that
                # proposes when the operator believes it is read-only
                raise ValueError(
                    f"[node] mode must be validator/follower/archive, "
                    f"got {cfg.node_mode!r}"
                )
        # upstream= repeats (one "host port" line per upstream, like
        # [ips]); _kv would collapse duplicates so collect them raw
        upstreams = [
            line.split("=", 1)[1].strip()
            for line in s.get("node", [])
            if "=" in line and line.split("=", 1)[0].strip() == "upstream"
        ]
        if upstreams:
            if cfg.node_mode not in ("follower", "archive"):
                # an upstream on a validator would parse clean and be
                # silently dropped — the dead-config class again
                raise ValueError(
                    "[node] upstream= only applies to mode=follower/archive"
                )
            cfg.node_upstream = upstreams
        archive_sec = _kv(s.get("archive", []))
        if archive_sec:
            if cfg.node_mode != "archive":
                # [archive] on a validator/follower would parse clean
                # and be silently dropped — the dead-config class again
                raise ValueError(
                    "[archive] only applies to [node] mode=archive"
                )
            _reject_unknown("archive", archive_sec,
                            ("path", "backfill", "rescan_s"))
            cfg.archive_path = archive_sec.get("path", cfg.archive_path)
            if "backfill" in archive_sec:
                cfg.archive_backfill = int(archive_sec["backfill"])
            if "rescan_s" in archive_sec:
                cfg.archive_rescan_s = float(archive_sec["rescan_s"])
                if cfg.archive_rescan_s <= 0:
                    raise ValueError(
                        "[archive] rescan_s must be positive"
                    )
        if one("ledger_history"):
            cfg.ledger_history = int(one("ledger_history"))

        node_db = _kv(s.get("node_db", []))
        cfg.node_db_type = node_db.get("type", cfg.node_db_type).lower()
        cfg.node_db_path = node_db.get("path", cfg.node_db_path)
        cfg.node_db_compression = node_db.get(
            "compression", cfg.node_db_compression).lower()
        if "durability" in node_db:
            cfg.node_db_durability = node_db["durability"].lower()
            if cfg.node_db_durability not in ("fsync", "batch", "async"):
                # a durability toggle must not fail open into a default
                raise ValueError(
                    f"[node_db] durability must be fsync/batch/async, "
                    f"got {cfg.node_db_durability!r}"
                )
        for key, attr, conv in (
            ("group_commit_ms", "node_db_group_commit_ms", float),
            ("segment_mb", "node_db_segment_mb", int),
            ("checkpoint_mb", "node_db_checkpoint_mb", int),
            ("compact_ratio", "node_db_compact_ratio", float),
            ("online_delete", "node_db_online_delete", int),
            ("sql_trim", "node_db_sql_trim", int),
            ("online_delete_interval", "node_db_online_delete_interval",
             int),
        ):
            if key in node_db:
                setattr(cfg, attr, conv(node_db[key]))
        cfg.node_db_shards = node_db.get("shards", cfg.node_db_shards)
        cfg.node_db_synchronous = node_db.get(
            "synchronous", cfg.node_db_synchronous).lower()
        cfg.database_path = one("database_path", cfg.database_path)

        sig = _kv(s.get("signature_backend", []))
        _reject_unknown("signature_backend", sig, (
            "type", "window_ms", "max_batch", "min_device_batch", "mesh",
            "routing", "threads", "device_first_timeout_s",
            "device_warm_timeout_s",
        ))
        cfg.signature_backend = sig.get("type", one("signature_backend",
                                                    cfg.signature_backend)).lower()
        if "window_ms" in sig:
            cfg.verify_batch_window_ms = float(sig["window_ms"])
        if "max_batch" in sig:
            cfg.verify_max_batch = int(sig["max_batch"])
        if "min_device_batch" in sig:
            cfg.verify_min_device_batch = int(sig["min_device_batch"])
        if "threads" in sig:
            cfg.verify_threads = int(sig["threads"])
        if "device_first_timeout_s" in sig:
            cfg.verify_device_first_timeout_s = float(
                sig["device_first_timeout_s"]
            )
        if "device_warm_timeout_s" in sig:
            cfg.verify_device_warm_timeout_s = float(
                sig["device_warm_timeout_s"]
            )
        cfg.verify_mesh = _crypto_mesh(
            "signature_backend", cfg.signature_backend, sig, cfg.verify_mesh
        )
        cfg.verify_routing = _crypto_routing("signature_backend", sig)
        _crypto_backend_gate(
            "signature_backend", cfg.signature_backend, sig,
            device_only=("routing", "device_first_timeout_s",
                         "device_warm_timeout_s"),
            host_only=("threads",),
        )
        hsh = _kv(s.get("hash_backend", []))
        _reject_unknown("hash_backend", hsh, (
            "type", "mesh", "routing", "min_device_nodes",
            "device_first_timeout_s",
        ))
        cfg.hash_backend = hsh.get(
            "type", one("hash_backend", cfg.hash_backend)
        ).lower()
        if "min_device_nodes" in hsh:
            cfg.hash_min_device_nodes = int(hsh["min_device_nodes"])
        if "device_first_timeout_s" in hsh:
            cfg.hash_device_first_timeout_s = float(
                hsh["device_first_timeout_s"]
            )
        cfg.hash_mesh = _crypto_mesh(
            "hash_backend", cfg.hash_backend, hsh, cfg.hash_mesh
        )
        cfg.hash_routing = _crypto_routing("hash_backend", hsh)
        _crypto_backend_gate(
            "hash_backend", cfg.hash_backend, hsh,
            device_only=("routing", "min_device_nodes",
                         "device_first_timeout_s"),
        )
        cfg.kernel_tuning = one("kernel_tuning", cfg.kernel_tuning)
        cp = _kv(s.get("close_pipeline", []))
        if "enabled" in cp:
            cfg.close_pipeline_enabled = cp["enabled"].lower() not in (
                "0", "false", "no", "off"
            )
        if "depth" in cp:
            cfg.close_pipeline_depth = int(cp["depth"])
        txq = _kv(s.get("txq", []))
        if "enabled" in txq:
            cfg.txq_enabled = txq["enabled"].lower() not in (
                "0", "false", "no", "off"
            )
        for key, attr, conv in (
            ("ledgers_in_queue", "txq_ledgers_in_queue", int),
            ("account_cap", "txq_account_cap", int),
            ("retry_fee_pct", "txq_retry_fee_pct", int),
            ("retention_ledgers", "txq_retention_ledgers", int),
            ("min_cap", "txq_min_cap", int),
            ("max_cap", "txq_max_cap", int),
            ("target_close_ms", "txq_target_close_ms", float),
        ):
            if key in txq:
                setattr(cfg, attr, conv(txq[key]))
        spec = _kv(s.get("spec", []))
        if "workers" in spec:
            v = spec["workers"].strip().lower()
            if v == "auto":
                cfg.spec_workers = "auto"
            else:
                try:
                    cfg.spec_workers = int(v)
                except ValueError:
                    # dead-config-seam convention: a typo'd knob raises
                    # at build ("atuo" must not silently mean serial)
                    raise ValueError(
                        f"[spec] workers must be an integer or 'auto', "
                        f"got {spec['workers']!r}"
                    ) from None
        if "transport" in spec:
            cfg.spec_transport = spec["transport"].lower()
            if cfg.spec_transport not in ("ring", "pipe"):
                raise ValueError(
                    f"[spec] transport must be ring/pipe, "
                    f"got {cfg.spec_transport!r}"
                )
        if "mode" in spec:
            cfg.spec_mode = spec["mode"].lower()
            if cfg.spec_mode not in ("process", "thread", "manual"):
                # a parallelism toggle must not fail open into an
                # unintended transport
                raise ValueError(
                    f"[spec] mode must be process/thread/manual, "
                    f"got {cfg.spec_mode!r}"
                )
        if "max_retries" in spec:
            cfg.spec_max_retries = int(spec["max_retries"])
        if "drain_timeout_s" in spec:
            cfg.spec_drain_timeout_s = float(spec["drain_timeout_s"])
        close = _kv(s.get("close", []))
        if "delta_replay" in close:
            cfg.close_delta_replay = close["delta_replay"].lower() not in (
                "0", "false", "no", "off"
            )
        tree = _kv(s.get("tree", []))
        if "incremental" in tree:
            cfg.tree_incremental_seal = tree["incremental"].lower() not in (
                "0", "false", "no", "off"
            )
        if "drain_batch" in tree:
            cfg.tree_drain_batch = int(tree["drain_batch"])
        if "cache_mb" in tree:
            cfg.tree_cache_mb = int(tree["cache_mb"])
        if "fused" in tree:
            cfg.tree_fused = tree["fused"].lower() not in (
                "0", "false", "no", "off"
            )

        subs = _kv(s.get("subs", []))
        for key, attr in (
            ("shards", "subs_shards"),
            ("sendq_cap", "subs_sendq_cap"),
            ("evict_drops", "subs_evict_drops"),
            ("push_retries", "subs_push_retries"),
            ("resume_horizon", "subs_resume_horizon"),
        ):
            if key in subs:
                setattr(cfg, attr, int(subs[key]))
        rpc_cache = _kv(s.get("rpc_cache", []))
        if "size" in rpc_cache:
            cfg.rpc_cache_size = int(rpc_cache["size"])

        paths = _kv(s.get("paths", []))
        _reject_unknown("paths", paths, (
            "enabled", "incremental", "device_prune", "prune_floor",
            "prune_keep", "max_updates_per_close", "mesh",
            "min_device_batch", "routing",
        ))
        for key, attr in (
            ("enabled", "paths_enabled"),
            ("incremental", "paths_incremental"),
            ("device_prune", "paths_device_prune"),
        ):
            if key in paths:
                setattr(cfg, attr, paths[key].lower() not in (
                    "0", "false", "no", "off"
                ))
        for key, attr in (
            ("prune_floor", "paths_prune_floor"),
            ("prune_keep", "paths_prune_keep"),
            ("max_updates_per_close", "paths_max_updates_per_close"),
            ("min_device_batch", "paths_min_device_batch"),
        ):
            if key in paths:
                setattr(cfg, attr, int(paths[key]))
        if "mesh" in paths:
            from ..crypto.backend import parse_mesh

            cfg.paths_mesh = parse_mesh(paths["mesh"])
        if "routing" in paths:
            routing = paths["routing"].strip().lower()
            if routing not in ("cost", "device", "host"):
                # a routing toggle must not silently fail open
                raise ValueError(
                    f"[paths] routing must be cost|device|host, "
                    f"got {paths['routing']!r}"
                )
            cfg.paths_routing = routing

        cfg.validation_seed = one("validation_seed", cfg.validation_seed)
        cfg.sntp_servers = [line.split()[0] for line in s.get("sntp_servers", [])]
        cfg.validators_file = one("validators_file", cfg.validators_file)
        cfg.validators_site = one("validators_site", cfg.validators_site)
        # [insight] is a hybrid section: the legacy bare collector line
        # ('statsd:host:port[:prefix]') plus key=value history knobs
        insight_lines = s.get("insight", [])
        bare = [ln for ln in insight_lines if "=" not in ln]
        if bare:
            cfg.insight = bare[0]
        ikv = _kv(insight_lines)
        _reject_unknown("insight", ikv, (
            "history", "history_interval", "history_window",
        ))
        if "history" in ikv:
            cfg.insight_history = ikv["history"].lower() not in (
                "0", "false", "no", "off"
            )
        if "history_interval" in ikv:
            cfg.insight_history_interval = float(ikv["history_interval"])
        if "history_window" in ikv:
            cfg.insight_history_window = float(ikv["history_window"])
        trace = _kv(s.get("trace", []))
        _reject_unknown("trace", trace, (
            "enabled", "capacity", "sample", "propagate",
        ))
        if "enabled" in trace:
            cfg.trace_enabled = trace["enabled"].lower() not in (
                "0", "false", "no", "off"
            )
        if "capacity" in trace:
            cfg.trace_capacity = int(trace["capacity"])
        if "sample" in trace:
            cfg.trace_sample = float(trace["sample"])
        if "propagate" in trace:
            cfg.trace_propagate = trace["propagate"].lower() not in (
                "0", "false", "no", "off"
            )
        health = _kv(s.get("health", []))
        _reject_unknown("health", health, (
            "enabled", "stall_warn_s", "stall_crit_s", "drift_factor",
            "lag_warn", "lag_crit", "fanout_p99_warn_ms", "flips_warn",
            "cache_hit_warn", "persist_depth_warn", "flight_dir",
            "flight_spans",
        ))
        if "enabled" in health:
            cfg.health_enabled = health["enabled"].lower() not in (
                "0", "false", "no", "off"
            )
        for key, attr, conv in (
            ("stall_warn_s", "health_stall_warn_s", float),
            ("stall_crit_s", "health_stall_crit_s", float),
            ("drift_factor", "health_drift_factor", float),
            ("lag_warn", "health_lag_warn", int),
            ("lag_crit", "health_lag_crit", int),
            ("fanout_p99_warn_ms", "health_fanout_p99_warn_ms", float),
            ("flips_warn", "health_flips_warn", int),
            ("cache_hit_warn", "health_cache_hit_warn", float),
            ("persist_depth_warn", "health_persist_depth_warn", float),
            ("flight_spans", "health_flight_spans", int),
        ):
            if key in health:
                setattr(cfg, attr, conv(health[key]))
        if "flight_dir" in health:
            cfg.health_flight_dir = health["flight_dir"]
        cfg.validators = [
            line.split()[0] for line in s.get("validators", [])
        ]  # reference allows trailing comments per line
        cfg.cluster_nodes = [
            line.split()[0] for line in s.get("cluster_nodes", [])
        ]
        if one("validation_quorum"):
            cfg.validation_quorum = int(one("validation_quorum"))
        if one("consensus_threshold"):
            cfg.consensus_threshold = int(one("consensus_threshold"))

        if one("rpc_ip"):
            cfg.rpc_ip = one("rpc_ip")
        if s.get("rpc_admin_allow"):
            cfg.admin_ips = list(s["rpc_admin_allow"])
        if one("rpc_port"):
            cfg.rpc_port = int(one("rpc_port"))
        if one("websocket_ip"):
            cfg.websocket_ip = one("websocket_ip")
        if one("websocket_port"):
            cfg.websocket_port = int(one("websocket_port"))
        if one("rpc_secure"):
            cfg.rpc_secure = int(one("rpc_secure"))
        cfg.rpc_ssl_cert = one("rpc_ssl_cert", cfg.rpc_ssl_cert)
        cfg.rpc_ssl_key = one("rpc_ssl_key", cfg.rpc_ssl_key)
        if one("websocket_secure"):
            cfg.websocket_secure = int(one("websocket_secure"))
        cfg.websocket_ssl_cert = one(
            "websocket_ssl_cert", cfg.websocket_ssl_cert
        )
        cfg.websocket_ssl_key = one("websocket_ssl_key", cfg.websocket_ssl_key)
        if one("peer_ip"):
            cfg.peer_ip = one("peer_ip")
        if one("peer_port"):
            cfg.peer_port = int(one("peer_port"))
        cfg.ips = list(s.get("ips", []))
        ov = _kv(s.get("overlay", []))
        for key, attr in (
            ("squelch", "overlay_squelch"),
            ("squelch_rotate", "overlay_squelch_rotate"),
            ("sendq_cap", "overlay_sendq_cap"),
            ("sendq_evict_drops", "overlay_sendq_evict_drops"),
        ):
            if key in ov:
                setattr(cfg, attr, int(ov[key]))
        if "rpc_resource" in ov:
            cfg.overlay_rpc_resource = ov["rpc_resource"].lower() not in (
                "0", "false", "no", "off"
            )
        if one("peer_ssl"):
            cfg.peer_ssl = one("peer_ssl").lower()
            if cfg.peer_ssl not in ("", "allow", "require"):
                # a security toggle must not fail open: an unrecognized
                # value running plaintext while the operator believes TLS
                # is on would be silent downgrade
                raise ValueError(
                    f"[peer_ssl] must be 'allow' or 'require', "
                    f"got {cfg.peer_ssl!r}"
                )
        if one("clock_speed"):
            cfg.clock_speed = float(one("clock_speed"))
        if one("network_time_offset"):
            cfg.network_time_offset = int(one("network_time_offset"))

        cfg.node_size = one("node_size", cfg.node_size).lower()
        if one("fee_default"):
            cfg.fee_default = int(one("fee_default"))
        cfg.debug_logfile = one("debug_logfile", cfg.debug_logfile)
        return cfg

    def verify_backend_opts(self) -> dict:
        """Factory kwargs for make_verifier, built from the
        [signature_backend] section — the plumbing that makes backend
        options (mesh width, batch bounds, host threads) reachable from
        a cfg file. Unknown keys fail loudly inside make_verifier."""
        if self.signature_backend == "tpu":
            return {
                "mesh": self.verify_mesh,
                "max_batch": self.verify_max_batch,
            }
        if self.signature_backend in ("cpu", "openssl"):
            return {"threads": self.verify_threads}
        return {}

    def thread_count(self) -> int:
        """reference: JobQueue thread heuristic from [node_size]
        (Config::getSize / Application.cpp). Standalone uses a small pool
        (the reference uses 0=caller-runs; we keep one worker so async
        submission still works)."""
        if self.standalone:
            return 1
        return {"tiny": 2, "small": 4, "medium": 6, "large": 8, "huge": 12}.get(
            self.node_size, 4
        )
