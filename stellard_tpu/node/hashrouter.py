"""HashRouter: suppression table + per-hash flags.

Reference: src/ripple_app/misc/{IHashRouter.h,HashRouter.cpp} — dedups
relays (by 256-bit hash + set of peers that already sent it) and memoizes
signature verdicts process-wide (SF_SIGGOOD/SF_BAD), which is what lets
the consensus close path skip re-verification
(LedgerConsensus.cpp:2101-2106).
"""

from __future__ import annotations

import threading
import time

__all__ = [
    "HashRouter",
    "SF_RELAYED",
    "SF_BAD",
    "SF_SIGGOOD",
    "SF_SAVED",
    "SF_RETRY",
    "SF_TRUSTED",
]

# reference: IHashRouter.h:27-33
SF_RELAYED = 0x01  # has already been relayed to peers
SF_BAD = 0x02  # signature/format known bad
SF_SIGGOOD = 0x04  # signature known good
SF_SAVED = 0x08
SF_RETRY = 0x10
SF_TRUSTED = 0x20

_HOLD_SECONDS = 300  # reference: HashRouter holdTime


class _Entry:
    __slots__ = ("flags", "peers", "touched")

    def __init__(self):
        self.flags = 0
        self.peers: set[int] = set()
        self.touched = time.monotonic()


class HashRouter:
    def __init__(self, hold_seconds: float = _HOLD_SECONDS):
        self._lock = threading.Lock()
        self._map: dict[bytes, _Entry] = {}
        self._hold = hold_seconds
        self._last_sweep = time.monotonic()

    def _get(self, h: bytes) -> _Entry:
        e = self._map.get(h)
        if e is None:
            e = self._map[h] = _Entry()
        e.touched = time.monotonic()
        if e.touched - self._last_sweep > self._hold:
            self._sweep(e.touched)
        return e

    def _sweep(self, now: float) -> None:
        self._last_sweep = now
        dead = [h for h, e in self._map.items() if now - e.touched > self._hold]
        for h in dead:
            del self._map[h]

    # -- suppression (reference: addSuppressionPeer) ----------------------

    def add_suppression_peer(self, h: bytes, peer: int) -> bool:
        """Record that `peer` sent `h`; True if this hash is NEW
        (i.e. should be processed, not a duplicate)."""
        return self.note_peer(h, peer)[0]

    def note_peer(self, h: bytes, peer: int) -> tuple[bool, bool]:
        """Suppression with re-send attribution: (is_new, same_peer_dup).
        ``same_peer_dup`` is True when THIS peer already sent this hash —
        an honest relay mesh delivers each hash once per neighbor, so a
        same-peer re-send is the flooder signature the resource plane
        charges (cross-peer duplicates stay free)."""
        with self._lock:
            known = h in self._map
            e = self._get(h)
            resend = peer in e.peers
            e.peers.add(peer)
            return not known, known and resend

    def get_flags(self, h: bytes) -> int:
        with self._lock:
            e = self._map.get(h)
            return e.flags if e else 0

    def set_flag(self, h: bytes, flag: int) -> bool:
        """OR a flag in; True if the flag was newly set."""
        with self._lock:
            e = self._get(h)
            was = e.flags & flag
            e.flags |= flag
            return not was

    def swap_set(self, h: bytes, peers: set[int], flag: int) -> tuple[set[int], bool]:
        """Atomically take the peer set (for relay fan-out exclusion) and
        set a flag (reference: swapSet used on SF_RELAYED before
        broadcast). Returns (previous peers, flag newly set)."""
        with self._lock:
            e = self._get(h)
            prev = e.peers
            e.peers = set()
            was = e.flags & flag
            e.flags |= flag
            return prev, not was

    def size(self) -> int:
        with self._lock:
            return len(self._map)
