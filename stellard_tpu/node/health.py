"""SLO health watchdog + flight recorder (the judgment layer of the
observability plane).

The tracer answers "what happened", the metrics history answers "what
were the numbers" — this module answers "is the node healthy RIGHT NOW,
and if not, why", continuously, in-process, with the evidence preserved:

- HealthWatchdog: EWMA/threshold rules over the metrics history ring
  (node/metrics.py MetricsHistory) plus two direct feeds (close events,
  closed/validated seqs). Emits ok/warn/critical with machine-readable
  reasons, `health.*` tracer instants on every status transition, and a
  `health` block for server_state/get_counts. Deterministic: status is a
  pure function of the fed observations and the clock values handed in,
  so the scenario runner can drive it with virtual time and get
  bit-identical scorecards.

- FlightRecorder: an always-on bounded black box — recent spans (fed by
  the tracer), health transitions, counter-snapshot deltas — dumped
  ATOMICALLY to disk (tmp + rename) on crash, degradation to TRACKING,
  or a fuzzer invariant violation, so the moments before a failure
  survive the failure.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

__all__ = ["HealthWatchdog", "FlightRecorder", "HEALTH_OK", "HEALTH_WARN",
           "HEALTH_CRITICAL"]

HEALTH_OK = "ok"
HEALTH_WARN = "warn"
HEALTH_CRITICAL = "critical"

_RANK = {HEALTH_OK: 0, HEALTH_WARN: 1, HEALTH_CRITICAL: 2}


class FlightRecorder:
    """Bounded black box: the newest N spans / health transitions /
    counter snapshots, whatever the sampling rate. deque(maxlen=) keeps
    every append O(1) and the memory ceiling fixed; appends are GIL-
    atomic so the tracer's record path takes no extra lock."""

    def __init__(self, directory: str = "", spans_cap: int = 2048,
                 events_cap: int = 256):
        self.directory = directory or "."
        self._spans: deque = deque(maxlen=max(16, int(spans_cap)))
        self._transitions: deque = deque(maxlen=max(4, int(events_cap)))
        self._counters: deque = deque(maxlen=max(4, int(events_cap)))
        self._dump_lock = threading.Lock()
        self._dump_n = 0
        self.dumps: list[str] = []  # paths written this process

    # -- feeds (hot-ish paths: deque.append only) --------------------------

    def note_span(self, ph: str, name: str, cat: str, trace, ms: float) -> None:
        self._spans.append((round(time.time(), 3), ph, name, cat, trace, ms))

    def note_transition(self, status: str, reasons: list, ts: float) -> None:
        self._transitions.append((round(ts, 3), status, list(reasons)))

    def note_counters(self, snap: dict) -> None:
        """One history snapshot's counters (the watchdog feeds these so
        the dump shows the numeric trajectory into the failure)."""
        self._counters.append(
            {"ts": snap.get("ts"), "counters": dict(snap.get("counters", {}))}
        )

    # -- dump --------------------------------------------------------------

    def payload(self, reason: str) -> dict:
        return {
            "reason": reason,
            "wall_time": time.time(),
            "pid": os.getpid(),
            "spans": list(self._spans),
            "health_transitions": list(self._transitions),
            "counter_snapshots": list(self._counters),
        }

    def dump(self, reason: str, directory: Optional[str] = None) -> Optional[str]:
        """Write the black box atomically (tmp + os.replace): a crash
        mid-dump leaves either the previous dump or a complete new one,
        never a torn file. Returns the path, or None on I/O failure —
        the recorder must never turn a failure into a worse failure."""
        with self._dump_lock:
            self._dump_n += 1
            n = self._dump_n
        d = directory or self.directory
        safe = "".join(c if (c.isalnum() or c in "-_.") else "_" for c in reason)
        path = os.path.join(d, f"flight-{safe[:64]}-{os.getpid()}-{n}.json")
        tmp = path + ".tmp"
        try:
            os.makedirs(d, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.payload(reason), f, default=repr)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        self.dumps.append(path)
        return path

    def get_json(self) -> dict:
        return {
            "spans": len(self._spans),
            "transitions": len(self._transitions),
            "dumps": list(self.dumps),
        }


class HealthWatchdog:
    """Six SLO rules, each with a warn and (where meaningful) a critical
    line; overall status is the worst tripped rule:

    1. close cadence: no close for > stall_warn_s (stall_crit_s) OR the
       EWMA of close gaps drifted past drift_factor x the target cadence
    2. validation lag: closed_seq - validated_seq beyond lag_warn
       (lag_crit) ledgers — quorum is slipping
    3. fanout delivery: the subscription fanout lag p99 (registered
       LatencyHist) above fanout_p99_warn_ms
    4. routing flips: measured-cost verify/hash arm routing flipped more
       than flips_warn times within one history window — thrashing
    5. cache collapse: any `*.hit_rate` gauge/hook under cache_hit_warn
    6. persist backlog: any `*queue_depth`/`*persist_depth` gauge/hook
       above persist_depth_warn

    Rules with no data report nothing (a node without subscribers is not
    "unhealthy", it is silent) — the anti-vacuity gate lives in the
    scenario fuzzer, which INJECTS a cadence stall and requires a trip.
    """

    def __init__(
        self,
        target_close_s: float = 3.0,
        stall_warn_s: float = 12.0,
        stall_crit_s: float = 45.0,
        drift_factor: float = 2.5,
        lag_warn: int = 4,
        lag_crit: int = 16,
        fanout_p99_warn_ms: float = 250.0,
        flips_warn: int = 8,
        cache_hit_warn: float = 0.10,
        persist_depth_warn: float = 512.0,
        ewma_alpha: float = 0.25,
        tracer=None,
        flight: Optional[FlightRecorder] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.target_close_s = float(target_close_s)
        self.stall_warn_s = float(stall_warn_s)
        self.stall_crit_s = float(stall_crit_s)
        self.drift_factor = float(drift_factor)
        self.lag_warn = int(lag_warn)
        self.lag_crit = int(lag_crit)
        self.fanout_p99_warn_ms = float(fanout_p99_warn_ms)
        self.flips_warn = int(flips_warn)
        self.cache_hit_warn = float(cache_hit_warn)
        self.persist_depth_warn = float(persist_depth_warn)
        self.ewma_alpha = float(ewma_alpha)
        self.tracer = tracer
        self.flight = flight
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        # feeds
        self._last_close_ts: Optional[float] = None
        self._ewma_gap: Optional[float] = None
        self._closed_seq = 0
        self._validated_seq = 0
        self._flip_counts: dict[str, int] = {}  # counter name -> last seen
        self._flips_window: deque = deque(maxlen=64)  # (ts, delta)
        # state
        self.status = HEALTH_OK
        self.reasons: list[str] = []
        self.transitions = 0
        self.evaluations = 0
        # observers of (old_status, new_status, reasons) — node.py wires
        # the flight-recorder dump here
        self.on_transition: list[Callable[[str, str, list], None]] = []

    # -- feeds -------------------------------------------------------------

    def note_close(self, seq: int, ts: Optional[float] = None) -> None:
        """One ledger close (consensus OR follower adoption)."""
        now = self.clock() if ts is None else float(ts)
        with self._lock:
            if self._last_close_ts is not None:
                gap = max(0.0, now - self._last_close_ts)
                a = self.ewma_alpha
                self._ewma_gap = (
                    gap if self._ewma_gap is None
                    else a * gap + (1.0 - a) * self._ewma_gap
                )
            self._last_close_ts = now
            if seq > self._closed_seq:
                self._closed_seq = seq

    def note_seqs(self, closed: int, validated: int) -> None:
        with self._lock:
            self._closed_seq = int(closed)
            self._validated_seq = int(validated)

    def note_validated(self, seq: int) -> None:
        """Quorum-validated tip advanced (LedgerMaster.on_validated)."""
        with self._lock:
            self._validated_seq = max(self._validated_seq, int(seq))
            # a validated ledger was necessarily closed — keep the pair
            # ordered so the lag rule never reads a negative lag
            if self._closed_seq < self._validated_seq:
                self._closed_seq = self._validated_seq

    def on_snapshot(self, snap: dict) -> None:
        """MetricsHistory on_sample observer: ingest counter deltas for
        the flip rule, forward the snapshot to the flight recorder, then
        re-evaluate at the snapshot's timestamp."""
        counters = dict(snap.get("counters", {}))
        # flip telemetry may ride a pull-hook (node.serve's
        # verify_routing.flips) rather than a pushed counter
        for name, val in snap.get("hooks", {}).items():
            if "routing_flip" in name or name.endswith(".flips"):
                counters[name] = val
        ts = snap.get("ts")
        with self._lock:
            for name, val in counters.items():
                if "routing_flip" in name or name.endswith(".flips"):
                    prev = self._flip_counts.get(name)
                    if prev is not None and val > prev:
                        self._flips_window.append((ts, val - prev))
                    self._flip_counts[name] = val
        if self.flight is not None:
            self.flight.note_counters(snap)
        self.evaluate(snap=snap, now=self.clock())

    # -- evaluation --------------------------------------------------------

    def _rules(self, snap: Optional[dict], now: float) -> list[tuple[str, str]]:
        """(severity, reason) for every tripped rule."""
        out: list[tuple[str, str]] = []
        with self._lock:
            last_close = self._last_close_ts
            ewma = self._ewma_gap
            closed, validated = self._closed_seq, self._validated_seq
            flips = sum(d for _t, d in self._flips_window)
        # 1. close cadence
        if last_close is not None:
            idle = now - last_close
            if idle > self.stall_crit_s:
                out.append((HEALTH_CRITICAL,
                            f"close_stall:{idle:.1f}s>{self.stall_crit_s:g}s"))
            elif idle > self.stall_warn_s:
                out.append((HEALTH_WARN,
                            f"close_stall:{idle:.1f}s>{self.stall_warn_s:g}s"))
            if (
                ewma is not None
                and ewma > self.drift_factor * self.target_close_s
            ):
                out.append((HEALTH_WARN,
                            f"close_drift:ewma={ewma:.1f}s"
                            f">{self.drift_factor:g}x{self.target_close_s:g}s"))
        # 2. validation lag
        lag = closed - validated
        if validated and lag >= self.lag_crit:
            out.append((HEALTH_CRITICAL, f"validation_lag:{lag}"))
        elif validated and lag >= self.lag_warn:
            out.append((HEALTH_WARN, f"validation_lag:{lag}"))
        if snap:
            hists = snap.get("hists", {})
            # 3. fanout delivery p99
            for name, h in hists.items():
                if "fanout" in name or "subs" in name:
                    p99 = h.get("p99_ms", 0.0)
                    if h.get("count") and p99 > self.fanout_p99_warn_ms:
                        out.append((HEALTH_WARN,
                                    f"fanout_p99:{name}={p99:g}ms"))
            vals = dict(snap.get("gauges", {}))
            vals.update(snap.get("hooks", {}))
            for name, v in vals.items():
                # 5. cache hit collapse — only with real traffic: a
                # fresh/idle cache reports hit_rate=0 and is not sick
                if name.endswith("hit_rate") and v < self.cache_hit_warn:
                    stem = name[: -len("hit_rate")]
                    volume = sum(
                        vals.get(stem + s, 0) or 0
                        for s in ("hits", "misses", "lookups")
                    )
                    if volume >= 100:
                        out.append((HEALTH_WARN,
                                    f"cache_collapse:{name}={v:g}"))
                # 6. persist backlog
                if (
                    name.endswith(("queue_depth", "persist_depth"))
                    and v > self.persist_depth_warn
                ):
                    out.append((HEALTH_WARN, f"persist_backlog:{name}={v:g}"))
        # 4. routing flips
        if flips > self.flips_warn:
            out.append((HEALTH_WARN, f"routing_flips:{flips}"))
        return out

    def evaluate(self, snap: Optional[dict] = None,
                 now: Optional[float] = None) -> str:
        now = self.clock() if now is None else float(now)
        tripped = self._rules(snap, now)
        status = HEALTH_OK
        reasons: list[str] = []
        for sev, reason in tripped:
            reasons.append(reason)
            if _RANK[sev] > _RANK[status]:
                status = sev
        with self._lock:
            self.evaluations += 1
            old = self.status
            self.status = status
            self.reasons = reasons
        if status != old:
            self.transitions += 1
            if self.tracer is not None:
                self.tracer.instant(
                    f"health.{status}", "health",
                    prev=old, reasons=";".join(reasons) or None,
                )
            if self.flight is not None:
                self.flight.note_transition(status, reasons, now)
            for fn in list(self.on_transition):
                try:
                    fn(old, status, reasons)
                except Exception:  # noqa: BLE001 — observers never break
                    pass           # the watchdog
        return status

    # -- export ------------------------------------------------------------

    def get_json(self) -> dict:
        with self._lock:
            return {
                "status": self.status,
                "reasons": list(self.reasons),
                "transitions": self.transitions,
                "evaluations": self.evaluations,
                "ewma_close_gap_s": (
                    round(self._ewma_gap, 3)
                    if self._ewma_gap is not None else None
                ),
                "closed_seq": self._closed_seq,
                "validated_seq": self._validated_seq,
            }
