"""InboundLedger: network acquisition of a ledger by hash, and the
serving side that answers peers' requests.

Reference: src/ripple_app/ledger/InboundLedger.cpp (state machine: base
header → tx tree → state tree; trigger/takeNodes) and InboundLedgers.cpp
(container with dedup). Used for catch-up: when validations show the
network is on a ledger we don't have, we acquire it and switch
(reference: NetworkOPs::checkLastClosedLedger → switchLastClosedLedger).

``SegmentCatchup`` is the segment-granular bulk path layered under the
tree walk: instead of pulling a cold node's whole state one
GetLedger/LedgerData node wave at a time, it transfers entire store
segments (nodestore/segstore ``fetch_segment`` — contiguous byte ranges
whose every record is self-verifying: key == SHA-512-half of the blob)
into the local NodeStore, after which the tree acquisition resolves
almost everything via ``local_fetch`` and only the tip delta crosses the
wire node-by-node. Faults are first-class: per-request timeout on the
node's own clock, bounded retries with exponential backoff + seeded
jitter, peer scoring on failure, and per-peer fallback when a peer
serves garbage (a record whose bytes do not hash to its key).
"""

from __future__ import annotations

import struct
from typing import Callable, Optional

from ..overlay.wire import GetLedger, GetSegments, LedgerData, SegmentData
from ..state.ledger import Ledger, parse_header, strip_ledger_prefix
from ..state.shamap import SHAMap, TNType, resolve_node
from ..state.shamapsync import IncompleteMap, SHAMapNodeID
from ..utils.hashes import HP_LEDGER_MASTER, prefix_hash

__all__ = [
    "InboundLedger",
    "InboundLedgers",
    "SegmentCatchup",
    "iter_segment_records",
    "serve_get_ledger",
]

# GetLedger.what codes
W_HEADER = 0
# reply-size budget for fat GetLedger answers (nodes per LedgerData)
MAX_REPLY_NODES = 512
W_TX_TREE = 1
W_STATE_TREE = 2


class InboundLedger:
    """One acquisition session (reference: InboundLedger.cpp:93-265)."""

    def __init__(self, ledger_hash: bytes, hash_batch: Optional[Callable] = None,
                 now: Optional[float] = None):
        import time as _time

        self.hash = ledger_hash
        self.hash_batch = hash_batch
        self.header: Optional[bytes] = None
        self.fields: Optional[dict] = None
        self.tx_map: Optional[IncompleteMap] = None
        self.state_map: Optional[IncompleteMap] = None
        self.failed = False
        self.created_at = _time.monotonic() if now is None else now
        self.last_progress = self.created_at
        # True when the LCL catch-up path requested this ledger; repair
        # acquisitions (LedgerCleaner) must NEVER route through LCL
        # adoption (on_complete), only through their own callbacks
        self.for_lcl = False

    # -- progress ---------------------------------------------------------

    def is_complete(self) -> bool:
        return (
            self.header is not None
            and self.tx_map is not None
            and self.state_map is not None
            and self.tx_map.is_complete()
            and self.state_map.is_complete()
        )

    def next_requests(self, per_tree: int = 256) -> list[GetLedger]:
        """What to ask peers for next (reference: trigger)."""
        if self.header is None:
            return [GetLedger(self.hash, 0, W_HEADER, [])]
        out = []
        for what, imap in (
            (W_TX_TREE, self.tx_map),
            (W_STATE_TREE, self.state_map),
        ):
            if imap is not None and not imap.is_complete():
                missing = imap.missing_nodes(per_tree)
                out.append(
                    GetLedger(
                        self.hash, 0, what, [nid.encode() for nid, _h in missing]
                    )
                )
        return out

    def resolve_local(self, fetch: Callable[[bytes], Optional[bytes]]) -> int:
        """Fill missing nodes from a LOCAL (hash -> prefix-blob) source
        before asking the network: near-tip ledgers share almost their
        whole trees with ledgers we already hold, so catch-up only
        fetches the delta over the wire (the reference gets this from
        SHAMap's node cache + fetch packs). Returns nodes resolved."""
        total = 0
        for imap in (self.tx_map, self.state_map):
            if imap is None:
                continue
            while not imap.is_complete():
                found = []
                for _nid, h in imap.missing_nodes(4096):
                    blob = fetch(h)
                    if blob is not None:
                        found.append((h, blob))
                if not found or imap.add_nodes(found) == 0:
                    break
                total += len(found)
        return total

    # -- data intake ------------------------------------------------------

    def take_header(self, blob: bytes) -> bool:
        """Verify and accept the ledger header (the 'base' in the
        reference). The header IS the hashed content: LWR-prefixed
        SHA-512-half must equal the ledger hash we're acquiring."""
        if self.header is not None:
            return False  # duplicate — no progress
        if prefix_hash(HP_LEDGER_MASTER, blob) != self.hash:
            return False
        self.header = blob
        f = parse_header(blob)
        self.fields = f
        self.tx_map = IncompleteMap(f["tx_hash"], TNType.TX_MD)
        self.state_map = IncompleteMap(f["account_hash"], TNType.ACCOUNT_STATE)
        return True

    def take_nodes(self, what: int, pairs: list[tuple[bytes, bytes]]) -> int:
        """Accept LedgerData nodes: (node_id_wire, blob) pairs. Node
        position ids route the request; integrity comes from the
        hash-verified attach inside IncompleteMap (reference: takeNodes →
        SHAMapSync::addKnownNode)."""
        imap = self.tx_map if what == W_TX_TREE else self.state_map
        if imap is None:
            return 0
        by_id: dict[SHAMapNodeID, bytes] = {}
        for nid_wire, blob in pairs:
            try:
                by_id[SHAMapNodeID.decode(nid_wire)] = blob
            except ValueError:
                continue
        # a reply can contain several tree levels; every accepted level
        # exposes new positions, so keep matching until nothing new lands
        n = 0
        progressed = True
        while progressed and by_id:
            progressed = False
            want = {
                nid: h
                for nid, h in imap.missing_nodes(limit=4 * len(by_id) + 16)
            }
            batch = [
                (h, by_id[nid])
                for nid, h in want.items()
                if nid in by_id and not imap.have_node(h)
            ]
            if batch:
                got = imap.add_nodes(batch)
                n += got
                progressed = got > 0
        return n

    # -- completion -------------------------------------------------------

    def build_ledger(self) -> Ledger:
        assert self.is_complete()
        f = self.fields
        led = Ledger(
            seq=f["seq"],
            parent_hash=f["parent_hash"],
            tot_coins=f["tot_coins"],
            fee_pool=f["fee_pool"],
            inflation_seq=f["inflation_seq"],
            close_time=f["close_time"],
            parent_close_time=f["parent_close_time"],
            close_resolution=f["close_resolution"],
            close_flags=f["close_flags"],
            tx_map=self.tx_map.to_shamap(self.hash_batch),
            state_map=self.state_map.to_shamap(self.hash_batch),
        )
        led.closed = True
        led.accepted = True
        if led.hash() != self.hash:
            raise ValueError("acquired ledger does not hash to target")
        return led


class InboundLedgers:
    """Dedup container of running acquisitions
    (reference: InboundLedgers.cpp)."""

    def __init__(self, send: Callable[[GetLedger], None],
                 hash_batch: Optional[Callable] = None,
                 local_fetch: Optional[Callable[[bytes], Optional[bytes]]] = None,
                 clock: Optional[Callable[[], float]] = None):
        import time as _time

        self.send = send  # broadcast/anycast a GetLedger to peers
        # progress/expiry clock: the NODE's clock (virtual on the
        # deterministic simnet — wall-clock deadlines never fire there,
        # which once let a dead acquisition pin LCL catch-up forever)
        self.clock = clock if clock is not None else _time.monotonic
        self.hash_batch = hash_batch
        # optional hash -> prefix-blob lookup into local storage so
        # acquisitions only fetch the DELTA over the wire
        self.local_fetch = local_fetch
        self.live: dict[bytes, InboundLedger] = {}
        self.on_complete: Optional[Callable[[Ledger], None]] = None
        # per-acquisition completion callbacks (repair path)
        self._callbacks: dict[bytes, list[Callable]] = {}
        # hashes of acquisitions that recently left `live` (completed,
        # failed, or expired) -> monotonic time of departure. Late
        # replies from peers we legitimately asked (timer re-anycasts
        # rotate targets) must be neither charged nor scored.
        self._recent: dict[bytes, float] = {}

    RECENT_TTL = 60.0

    RECENT_CAP = 256

    def _mark_recent(self, ledger_hash: bytes) -> None:
        now = self.clock()
        self._recent.pop(ledger_hash, None)  # re-insert at newest position
        self._recent[ledger_hash] = now
        if len(self._recent) > self.RECENT_CAP:
            # TTL prune first; if everything is still fresh (fast
            # catch-up), evict oldest-first so the dict stays bounded
            self._recent = {
                h: t for h, t in self._recent.items()
                if now - t < self.RECENT_TTL
            }
            while len(self._recent) > self.RECENT_CAP:
                del self._recent[next(iter(self._recent))]

    def recently_done(self, ledger_hash: bytes) -> bool:
        t = self._recent.get(ledger_hash)
        return t is not None and self.clock() - t < self.RECENT_TTL

    def acquire(
        self, ledger_hash: bytes, callback: Optional[Callable] = None,
        for_lcl: bool = False,
    ) -> InboundLedger:
        """Start (or join) an acquisition. `callback(ledger)` fires for
        THIS request on completion; the global on_complete (the LCL
        adoption hook) fires only for sessions marked ``for_lcl`` —
        repair acquisitions (LedgerCleaner) persist old ledgers without
        ever switching the live chain onto them."""
        il = self.live.get(ledger_hash)
        if callback is not None:
            self._callbacks.setdefault(ledger_hash, []).append(callback)
        if il is None:
            il = InboundLedger(ledger_hash, self.hash_batch,
                               now=self.clock())
            il.for_lcl = for_lcl
            self.live[ledger_hash] = il
            self.trigger(il)
        elif for_lcl:
            il.for_lcl = True
        return il

    def abandon(self, ledger_hash: bytes) -> None:
        """Drop a live acquisition (retargeting): callers' slots are
        released with a None result, late replies are absorbed by the
        recently-done set."""
        il = self.live.pop(ledger_hash, None)
        if il is None:
            return
        self._mark_recent(ledger_hash)
        for cb in self._callbacks.pop(ledger_hash, []):
            cb(None)

    def trigger(self, il: InboundLedger) -> None:
        if self.local_fetch is not None:
            if il.header is None:
                # the header lives in the same store under the ledger
                # hash (HP_LEDGER_MASTER-prefixed); a ledger we already
                # hold on disk must not need a peer at all
                blob = self.local_fetch(il.hash)
                if blob is not None:
                    il.take_header(strip_ledger_prefix(blob))
            if il.header is not None and il.resolve_local(self.local_fetch):
                il.last_progress = self.clock()
            if self._finish(il):
                return
        for req in il.next_requests():
            self.send(req)

    def _finish(self, il: InboundLedger) -> bool:
        """Completion/failure bookkeeping; True when the session ended."""
        if not il.is_complete():
            return False
        h = il.hash
        try:
            ledger = il.build_ledger()
        except (ValueError, KeyError):
            il.failed = True
            del self.live[h]
            self._mark_recent(h)
            for cb in self._callbacks.pop(h, []):
                cb(None)  # failure: callers release their slots
            return True
        del self.live[h]
        self._mark_recent(h)
        for cb in self._callbacks.pop(h, []):
            cb(ledger)
        if self.on_complete is not None and il.for_lcl:
            self.on_complete(ledger)
        return True

    def expire_stale(self, max_age_s: float = 120.0) -> int:
        """Drop acquisitions that made no progress for `max_age_s` —
        unserveable requests (e.g. history no peer holds) must not pin
        sessions and re-broadcast forever (reference: PeerSet failure
        timeouts). Runs on the injected clock (virtual on the simnet).
        Returns the number expired."""
        now = self.clock()
        stale = [
            h
            for h, il in self.live.items()
            if now - il.last_progress > max_age_s
        ]
        for h in stale:
            del self.live[h]
            self._mark_recent(h)
            for cb in self._callbacks.pop(h, []):
                cb(None)  # expiry: callers release their slots
        return len(stale)

    def take_ledger_data(self, msg: LedgerData) -> int:
        """Route a LedgerData reply; returns how much PROGRESS it made
        (0 = ignored/duplicate/unknown — callers use this to score the
        sending peer). Only replies that made progress re-trigger
        requests — a duplicate reply from a second peer must not fan out
        another request wave (the reference throttles the same way via
        PeerSet progress timeouts)."""
        il = self.live.get(msg.ledger_hash)
        if il is None:
            return 0
        progressed = 0
        if msg.what == W_HEADER:
            for _nid, blob in msg.nodes:
                if il.take_header(blob):
                    progressed += 1
        else:
            progressed = il.take_nodes(msg.what, msg.nodes)
        if progressed:
            il.last_progress = self.clock()
        if self._finish(il):
            return max(progressed, 1) if not il.failed else progressed
        if progressed:
            self.trigger(il)
        return progressed


def serve_get_ledger(ledger: Optional[Ledger], msg: GetLedger) -> Optional[LedgerData]:
    """Answer a peer's GetLedger from a closed ledger we hold
    (reference: PeerImp::getLedger → TMLedgerData reply)."""
    if ledger is None:
        return None
    if msg.what == W_HEADER:
        return LedgerData(
            msg.ledger_hash, ledger.seq, W_HEADER, [(b"", ledger.header_bytes())]
        )
    tree = ledger.tx_map if msg.what == W_TX_TREE else ledger.state_map
    nodes: list[tuple[bytes, bytes]] = []
    if not msg.node_ids:
        # no specific request → send the root
        ids = [SHAMapNodeID.root()]
    else:
        ids = []
        for nid_wire in msg.node_ids:
            try:
                ids.append(SHAMapNodeID.decode(nid_wire))
            except ValueError:
                continue
    tree.get_hash()
    from ..state.shamap import serialize_node_prefix

    for nid in ids:
        node = _descend(tree, nid)
        if node is None:
            continue
        # FAT reply (reference: fetch-pack / 'fat' related-node
        # serving): greedy preorder DFS under each requested node,
        # budget-bounded. Preorder guarantees every child lands AFTER
        # its parent in the reply, so the acquirer's frontier matching
        # consumes the whole pack in one pass. Depth-first (not one
        # level) matters: order-book directory keys share 24-byte
        # prefixes, so state trees carry ~48-nibble single-child chain
        # paths — serving one level per round trip made deep-tree
        # catch-up structurally slower than the close cadence (a
        # scenario-fuzzer find: a revived validator could NEVER catch
        # up under an order-book workload).
        stack = [(nid, node)]
        while stack and len(nodes) < MAX_REPLY_NODES:
            cur_id, cur = stack.pop()
            cur = resolve_node(cur)  # lazy serving tree: fault on touch
            nodes.append((cur_id.encode(), serialize_node_prefix(cur)))
            if hasattr(cur, "children"):
                for branch in range(len(cur.children) - 1, -1, -1):
                    child = cur.children[branch]
                    if child is not None:
                        stack.append((cur_id.child(branch), child))
    if not nodes:
        return None
    return LedgerData(msg.ledger_hash, ledger.seq, msg.what, nodes)


def _descend(tree: SHAMap, nid: SHAMapNodeID):
    node = tree.root
    for nb in nid.nibbles():
        node = resolve_node(node)
        if node is None or not hasattr(node, "children"):
            return None
        node = node.children[nb]
    return resolve_node(node)


# -- segment-granular catch-up ---------------------------------------------

# segstore record layout (shared with cpplog, nodestore/segstore.py):
# [u32 body_len LE | u8 flags | 32B key | u8 type | blob]
_SEG_REC_HEADER = 37


def iter_segment_records(data: bytes):
    """Parse one segment's raw bytes into (key, type_byte, blob) records.
    A trailing partial record (snapshot of a growing active segment) is
    ignored; a structurally impossible length raises ValueError so the
    caller can treat the whole transfer as garbage."""
    off, n = 0, len(data)
    while off + _SEG_REC_HEADER <= n:
        body_len, flags = struct.unpack_from("<IB", data, off)
        if body_len < 1 or body_len > (64 << 20):
            raise ValueError(f"segment record length {body_len} at {off}")
        if flags != 0:
            raise ValueError(f"unknown segment record flags {flags}")
        end = off + _SEG_REC_HEADER + body_len
        if end > n:
            break  # torn tail of an active-segment snapshot
        key = data[off + 5: off + 37]
        body = data[off + _SEG_REC_HEADER: end]
        yield key, body[0], body[1:]
        off = end


class SegmentCatchup:
    """Bulk segment transfer into the local NodeStore (see module doc).

    Transport-agnostic and clock-driven: the owner supplies ``send(peer,
    msg)``, ``peers()`` (candidate peer ids, stable order), a monotonic
    ``clock()`` and a ``store(type_byte, key, blob)`` sink; ``tick(now)``
    advances timeouts/retries. On the deterministic simnet the clock is
    virtual, so every timeout, retry and backoff replays bit-identically
    for a given seed.
    """

    # a finished session (done OR fallback) re-arms after this long, so
    # a transient first-episode failure can never disable the bulk path
    # for the node's lifetime
    REARM_S = 60.0
    # a segment transfer may exceed its manifest-advertised size only by
    # this much (the active segment grows between manifest and fetch);
    # anything bigger is a hostile total and condemns the peer
    GROWTH_SLACK = 8 << 20
    # absolute per-segment ceiling, manifest or not
    MAX_SEGMENT_TRANSFER = 512 << 20

    def __init__(
        self,
        send: Callable[[object, object], None],
        peers: Callable[[], list],
        store: Callable[[int, bytes, bytes], None],
        clock: Callable[[], float],
        request_timeout: float = 4.0,
        max_retries: int = 8,
        backoff_base: float = 1.0,
        backoff_max: float = 30.0,
        seed: int = 0,
        note_byzantine: Optional[Callable] = None,
        on_complete: Optional[Callable[[], None]] = None,
        on_condemn: Optional[Callable] = None,
    ):
        import random
        import threading

        from .metrics import AtomicCounters

        # one lock for every public entry point: over TCP, replies land
        # on per-peer reader threads while tick() runs on the timer
        # thread — unsynchronized interleaving could double-charge
        # timeouts for answered requests or abandon a healthy transfer.
        # The simnet is single-threaded; an uncontended lock is free.
        self._lock = threading.RLock()
        self.send = send
        self.peers = peers
        self.store = store
        self.clock = clock
        self.request_timeout = request_timeout
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.rng = random.Random(0xCA7C ^ seed)
        self.note_byzantine = note_byzantine
        self.on_complete = on_complete
        # unified peer scoring seam: a condemned peer also takes a
        # resource charge on its overlay endpoint (the owner wires
        # this to TcpOverlay.charge_peer with FEE_GARBAGE_SEGMENT), so
        # relay, catch-up, and admission privilege degrade together
        self.on_condemn = on_condemn
        self.active = False
        self.state = "idle"  # idle | manifest | fetch | done | fallback
        self._finished_at: Optional[float] = None  # for can_start rearm
        self.counters = AtomicCounters(
            "started", "completed", "requests", "replies", "timeouts",
            "retries", "backoffs", "peer_switches", "garbage_records",
            "garbage_peers", "fallbacks", "segments", "records", "bytes",
            "late_replies", "epoch_restarts",
        )
        self._reset_session()

    def _reset_session(self) -> None:
        self._queue: list[int] = []      # segment ids still to fetch
        self._sizes: dict[int, int] = {}  # manifest-advertised sizes
        self._cur_seg: Optional[int] = None
        self._cur_total = 0
        self._buf = bytearray()
        self._want: Optional[tuple] = None  # ("manifest",) | ("seg", id)
        self._deadline: Optional[float] = None
        self._backoff_until = 0.0
        self._attempts = 0               # for the CURRENT want
        self._peer = None
        self._peer_failures: dict = {}
        self._bad_peers: set = set()
        # snapshot-handoff epoch (doc/follower.md): the serving peer's
        # sealed-set fingerprint from the manifest reply; every chunk
        # fetch is pinned to it and a mid-transfer move restarts the
        # session from a fresh manifest. 0 = pre-epoch peer (don't-care)
        self._snap_epoch = 0
        self._snap_seq = 0

    # -- lifecycle ---------------------------------------------------------

    def can_start(self, now: float) -> bool:
        """A new session may begin: never ran, or the previous one
        (completed or fallen back) finished REARM_S ago."""
        with self._lock:
            if self.active:
                return False
            if self.state == "idle":
                return True
            return (
                self._finished_at is not None
                and now - self._finished_at >= self.REARM_S
            )

    def start(self) -> bool:
        """Begin (or ignore if already running) a catch-up session.
        Returns whether a new session started."""
        with self._lock:
            if self.active:
                return False
            self._reset_session()
            self.active = True
            self.state = "manifest"
            self._want = ("manifest",)
            self.counters.add("started")
            self._send_current(self.clock())
            return True

    def stop(self) -> None:
        with self._lock:
            self.active = False
            self.state = "idle"
            self._want = None

    # -- peer selection ----------------------------------------------------

    def _eligible_peers(self) -> list:
        return [p for p in self.peers() if p not in self._bad_peers]

    def _pick_peer(self):
        """Fewest recorded failures wins; ties break on list order (the
        owner supplies a stable order, so runs replay identically)."""
        cands = self._eligible_peers()
        if not cands:
            return None
        return min(
            cands, key=lambda p: (self._peer_failures.get(p, 0),
                                  cands.index(p))
        )

    def _maybe_switch_peer(self) -> None:
        best = self._pick_peer()
        if best is not None and best != self._peer:
            self._peer = best
            self.counters.add("peer_switches")

    # -- request machinery -------------------------------------------------

    def _send_current(self, now: float) -> None:
        if self._want is None:
            return
        if self._peer is None:
            self._peer = self._pick_peer()
        if self._peer is None:
            self._fallback("no_peers")
            return
        if self._want[0] == "manifest":
            msg = GetSegments(-1, 0)
        else:
            # epoch-pinned snapshot_fetch: the request names the
            # manifest's epoch so the server (and the wire trace) can
            # tell which snapshot the fetcher believes it is reading
            msg = GetSegments(self._want[1], len(self._buf),
                              snap_epoch=self._snap_epoch)
        self.counters.add("requests")
        self._deadline = now + self.request_timeout
        try:
            self.send(self._peer, msg)
        except Exception:  # noqa: BLE001 — a dead transport is a timeout
            pass

    def tick(self, now: float) -> None:
        """Advance timeouts/backoff; the owner calls this from its timer."""
        with self._lock:
            self._tick_locked(now)

    def _tick_locked(self, now: float) -> None:
        if not self.active or self._want is None:
            return
        if self._deadline is not None and now >= self._deadline:
            # request timed out: score the peer, back off exponentially
            # (seeded jitter decorrelates a fleet of cold nodes), rotate
            # to the best-scoring other peer, give up after max_retries
            self._deadline = None
            self.counters.add("timeouts")
            if self._peer is not None:
                self._peer_failures[self._peer] = (
                    self._peer_failures.get(self._peer, 0) + 1
                )
            self._attempts += 1
            if self._attempts > self.max_retries:
                self._fallback("retries_exhausted")
                return
            delay = min(
                self.backoff_max,
                self.backoff_base * (2 ** (self._attempts - 1)),
            )
            delay *= 1.0 + 0.25 * self.rng.random()  # jitter
            self._backoff_until = now + delay
            self.counters.add("backoffs")
            self._maybe_switch_peer()
            return
        if self._deadline is None and now >= self._backoff_until:
            self.counters.add("retries")
            self._send_current(now)

    # -- replies -----------------------------------------------------------

    def on_manifest(self, peer, segments: list, epoch: int = 0,
                    snap_seq: int = 0) -> None:
        with self._lock:
            if not self.active or self._want != ("manifest",):
                self.counters.add("late_replies")
                return
            if peer != self._peer:
                self.counters.add("late_replies")
                return
            self.counters.add("replies")
            self._attempts = 0
            self._deadline = None
            # snapshot_offer accepted: pin this session to the offered
            # epoch; chunk replies from a different epoch restart it
            self._snap_epoch = int(epoch)
            self._snap_seq = int(snap_seq)
            self._sizes = {int(s[0]): int(s[1]) for s in segments}
            self._queue = sorted(self._sizes)
            if not self._queue:
                self._complete()
                return
            self.state = "fetch"
            self._next_segment()

    def _next_segment(self) -> None:
        if not self._queue:
            self._complete()
            return
        self._cur_seg = self._queue.pop(0)
        self._cur_total = 0
        self._buf = bytearray()
        self._want = ("seg", self._cur_seg)
        self._send_current(self.clock())

    def on_data(self, peer, msg: SegmentData) -> None:
        with self._lock:
            if (
                not self.active
                or self._want is None
                or self._want[0] != "seg"
                or msg.seg_id != self._want[1]
                or peer != self._peer
                or msg.offset != len(self._buf)
            ):
                self.counters.add("late_replies")
                return
            self.counters.add("replies")
            self._attempts = 0
            self._deadline = None
            if (
                msg.snap_epoch
                and self._snap_epoch
                and msg.snap_epoch != self._snap_epoch
            ):
                # the source's sealed set moved under us (rotation /
                # compaction / online deletion): the manifest's sizes
                # and this segment's byte range may describe a snapshot
                # that no longer exists. Honest behavior, not garbage —
                # restart from a fresh manifest on the SAME peer instead
                # of splicing records from two different snapshots.
                self.counters.add("epoch_restarts")
                self.state = "manifest"
                self._want = ("manifest",)
                self._queue = []
                self._sizes = {}
                self._buf = bytearray()
                self._cur_seg = None
                self._snap_epoch = 0
                self._send_current(self.clock())
                return
            # transfer-size defense: the claimed total is bounded by the
            # manifest-advertised size (plus active-segment growth
            # slack) and a hard ceiling — a hostile total must never buy
            # unbounded buffering on the very node this path defends
            limit = min(
                self.MAX_SEGMENT_TRANSFER,
                self._sizes.get(msg.seg_id, 0) + self.GROWTH_SLACK,
            )
            if msg.total > limit or len(self._buf) + len(msg.data) > limit:
                self._condemn_peer(peer, "oversized_transfer")
                return
            if len(self._buf) < msg.total and not msg.data:
                # the peer claims more bytes exist but sent none: it
                # cannot serve what it advertised — treating the torn
                # buffer as a complete segment would silently record a
                # partial transfer as success
                self._condemn_peer(peer, "short_transfer")
                return
            self._buf.extend(msg.data)
            self._cur_total = msg.total
            if len(self._buf) < self._cur_total:
                self._send_current(self.clock())  # next chunk
                return
            self._ingest_segment(peer)

    def _condemn_peer(self, peer, why: str) -> None:
        """Per-peer fallback: this peer served garbage (bad records, a
        hostile total, or a short transfer) — condemn it for the session
        and refetch the SAME segment elsewhere; only an out-of-peers
        session falls back to the node-granular walk."""
        self.counters.add("garbage_peers")
        if self.note_byzantine is not None:
            self.note_byzantine("garbage_segment", peer=None,
                                seg=self._cur_seg, why=why)
        if self.on_condemn is not None:
            try:
                self.on_condemn(peer)
            except Exception:  # noqa: BLE001 — the charge is bookkeeping;
                pass           # session fallback below must still run
        self._bad_peers.add(peer)
        self._peer = None
        if not self._eligible_peers():
            self._fallback("all_peers_garbage")
            return
        self._buf = bytearray()
        self._maybe_switch_peer()
        self._send_current(self.clock())

    def _ingest_segment(self, peer) -> None:
        """Verify and store a completed segment transfer. Every record is
        content-addressed, so garbage is detected per record without any
        out-of-band trust; ONE bad record condemns the transfer and the
        serving peer (per-peer fallback), never the whole session."""
        good: list[tuple[bytes, int, bytes]] = []
        bad = 0
        try:
            for key, type_byte, blob in iter_segment_records(bytes(self._buf)):
                if _sha512_half(blob) == key:
                    good.append((key, type_byte, blob))
                else:
                    bad += 1
        except ValueError:
            bad += 1
        if bad:
            self.counters.add("garbage_records", bad)
            self._condemn_peer(peer, "bad_records")
            return
        for key, type_byte, blob in good:
            try:
                self.store(type_byte, key, blob)
            except Exception:  # noqa: BLE001 — a failed local write must
                pass           # not kill the session; the tree walk re-fetches
        self.counters.add_many(
            segments=1, records=len(good), bytes=len(self._buf)
        )
        self._next_segment()

    # -- terminal states ---------------------------------------------------

    def _complete(self) -> None:
        self.active = False
        self.state = "done"
        self._want = None
        self._finished_at = self.clock()
        self.counters.add("completed")
        if self.on_complete is not None:
            try:
                self.on_complete()
            except Exception:  # noqa: BLE001 — completion hook only
                pass

    def _fallback(self, reason: str) -> None:
        """Give up on the bulk path for THIS session: the node-granular
        GetLedger walk (always running underneath) remains the way
        forward, and can_start re-arms a fresh session after REARM_S —
        one bad episode must not disable bulk catch-up forever. Loud in
        the counters, silent in behavior — graceful degradation."""
        self.active = False
        self.state = "fallback"
        self._want = None
        self._finished_at = self.clock()
        self.counters.add("fallbacks")

    def get_json(self) -> dict:
        out = self.counters.snapshot()
        with self._lock:
            out["state"] = self.state
            out["active"] = self.active
            out["snap_epoch"] = self._snap_epoch
            out["snap_seq"] = self._snap_seq
        return out


def _sha512_half(blob: bytes) -> bytes:
    from ..utils.hashes import sha512_half

    return sha512_half(blob)
