"""InboundLedger: network acquisition of a ledger by hash, and the
serving side that answers peers' requests.

Reference: src/ripple_app/ledger/InboundLedger.cpp (state machine: base
header → tx tree → state tree; trigger/takeNodes) and InboundLedgers.cpp
(container with dedup). Used for catch-up: when validations show the
network is on a ledger we don't have, we acquire it and switch
(reference: NetworkOPs::checkLastClosedLedger → switchLastClosedLedger).
"""

from __future__ import annotations

from typing import Callable, Optional

from ..overlay.wire import GetLedger, LedgerData
from ..state.ledger import Ledger, parse_header, strip_ledger_prefix
from ..state.shamap import SHAMap, TNType
from ..state.shamapsync import IncompleteMap, SHAMapNodeID
from ..utils.hashes import HP_LEDGER_MASTER, prefix_hash

__all__ = ["InboundLedger", "InboundLedgers", "serve_get_ledger"]

# GetLedger.what codes
W_HEADER = 0
# reply-size budget for fat GetLedger answers (nodes per LedgerData)
MAX_REPLY_NODES = 512
W_TX_TREE = 1
W_STATE_TREE = 2


class InboundLedger:
    """One acquisition session (reference: InboundLedger.cpp:93-265)."""

    def __init__(self, ledger_hash: bytes, hash_batch: Optional[Callable] = None):
        import time as _time

        self.hash = ledger_hash
        self.hash_batch = hash_batch
        self.header: Optional[bytes] = None
        self.fields: Optional[dict] = None
        self.tx_map: Optional[IncompleteMap] = None
        self.state_map: Optional[IncompleteMap] = None
        self.failed = False
        self.created_at = _time.monotonic()
        self.last_progress = self.created_at
        # True when the LCL catch-up path requested this ledger; repair
        # acquisitions (LedgerCleaner) must NEVER route through LCL
        # adoption (on_complete), only through their own callbacks
        self.for_lcl = False

    # -- progress ---------------------------------------------------------

    def is_complete(self) -> bool:
        return (
            self.header is not None
            and self.tx_map is not None
            and self.state_map is not None
            and self.tx_map.is_complete()
            and self.state_map.is_complete()
        )

    def next_requests(self, per_tree: int = 256) -> list[GetLedger]:
        """What to ask peers for next (reference: trigger)."""
        if self.header is None:
            return [GetLedger(self.hash, 0, W_HEADER, [])]
        out = []
        for what, imap in (
            (W_TX_TREE, self.tx_map),
            (W_STATE_TREE, self.state_map),
        ):
            if imap is not None and not imap.is_complete():
                missing = imap.missing_nodes(per_tree)
                out.append(
                    GetLedger(
                        self.hash, 0, what, [nid.encode() for nid, _h in missing]
                    )
                )
        return out

    def resolve_local(self, fetch: Callable[[bytes], Optional[bytes]]) -> int:
        """Fill missing nodes from a LOCAL (hash -> prefix-blob) source
        before asking the network: near-tip ledgers share almost their
        whole trees with ledgers we already hold, so catch-up only
        fetches the delta over the wire (the reference gets this from
        SHAMap's node cache + fetch packs). Returns nodes resolved."""
        total = 0
        for imap in (self.tx_map, self.state_map):
            if imap is None:
                continue
            while not imap.is_complete():
                found = []
                for _nid, h in imap.missing_nodes(4096):
                    blob = fetch(h)
                    if blob is not None:
                        found.append((h, blob))
                if not found or imap.add_nodes(found) == 0:
                    break
                total += len(found)
        return total

    # -- data intake ------------------------------------------------------

    def take_header(self, blob: bytes) -> bool:
        """Verify and accept the ledger header (the 'base' in the
        reference). The header IS the hashed content: LWR-prefixed
        SHA-512-half must equal the ledger hash we're acquiring."""
        if self.header is not None:
            return False  # duplicate — no progress
        if prefix_hash(HP_LEDGER_MASTER, blob) != self.hash:
            return False
        self.header = blob
        f = parse_header(blob)
        self.fields = f
        self.tx_map = IncompleteMap(f["tx_hash"], TNType.TX_MD)
        self.state_map = IncompleteMap(f["account_hash"], TNType.ACCOUNT_STATE)
        return True

    def take_nodes(self, what: int, pairs: list[tuple[bytes, bytes]]) -> int:
        """Accept LedgerData nodes: (node_id_wire, blob) pairs. Node
        position ids route the request; integrity comes from the
        hash-verified attach inside IncompleteMap (reference: takeNodes →
        SHAMapSync::addKnownNode)."""
        imap = self.tx_map if what == W_TX_TREE else self.state_map
        if imap is None:
            return 0
        by_id: dict[SHAMapNodeID, bytes] = {}
        for nid_wire, blob in pairs:
            try:
                by_id[SHAMapNodeID.decode(nid_wire)] = blob
            except ValueError:
                continue
        # a reply can contain several tree levels; every accepted level
        # exposes new positions, so keep matching until nothing new lands
        n = 0
        progressed = True
        while progressed and by_id:
            progressed = False
            want = {
                nid: h
                for nid, h in imap.missing_nodes(limit=4 * len(by_id) + 16)
            }
            batch = [
                (h, by_id[nid])
                for nid, h in want.items()
                if nid in by_id and not imap.have_node(h)
            ]
            if batch:
                got = imap.add_nodes(batch)
                n += got
                progressed = got > 0
        return n

    # -- completion -------------------------------------------------------

    def build_ledger(self) -> Ledger:
        assert self.is_complete()
        f = self.fields
        led = Ledger(
            seq=f["seq"],
            parent_hash=f["parent_hash"],
            tot_coins=f["tot_coins"],
            fee_pool=f["fee_pool"],
            inflation_seq=f["inflation_seq"],
            close_time=f["close_time"],
            parent_close_time=f["parent_close_time"],
            close_resolution=f["close_resolution"],
            close_flags=f["close_flags"],
            tx_map=self.tx_map.to_shamap(self.hash_batch),
            state_map=self.state_map.to_shamap(self.hash_batch),
        )
        led.closed = True
        led.accepted = True
        if led.hash() != self.hash:
            raise ValueError("acquired ledger does not hash to target")
        return led


class InboundLedgers:
    """Dedup container of running acquisitions
    (reference: InboundLedgers.cpp)."""

    def __init__(self, send: Callable[[GetLedger], None],
                 hash_batch: Optional[Callable] = None,
                 local_fetch: Optional[Callable[[bytes], Optional[bytes]]] = None):
        self.send = send  # broadcast/anycast a GetLedger to peers
        self.hash_batch = hash_batch
        # optional hash -> prefix-blob lookup into local storage so
        # acquisitions only fetch the DELTA over the wire
        self.local_fetch = local_fetch
        self.live: dict[bytes, InboundLedger] = {}
        self.on_complete: Optional[Callable[[Ledger], None]] = None
        # per-acquisition completion callbacks (repair path)
        self._callbacks: dict[bytes, list[Callable]] = {}
        # hashes of acquisitions that recently left `live` (completed,
        # failed, or expired) -> monotonic time of departure. Late
        # replies from peers we legitimately asked (timer re-anycasts
        # rotate targets) must be neither charged nor scored.
        self._recent: dict[bytes, float] = {}

    RECENT_TTL = 60.0

    RECENT_CAP = 256

    def _mark_recent(self, ledger_hash: bytes) -> None:
        import time as _time

        now = _time.monotonic()
        self._recent.pop(ledger_hash, None)  # re-insert at newest position
        self._recent[ledger_hash] = now
        if len(self._recent) > self.RECENT_CAP:
            # TTL prune first; if everything is still fresh (fast
            # catch-up), evict oldest-first so the dict stays bounded
            self._recent = {
                h: t for h, t in self._recent.items()
                if now - t < self.RECENT_TTL
            }
            while len(self._recent) > self.RECENT_CAP:
                del self._recent[next(iter(self._recent))]

    def recently_done(self, ledger_hash: bytes) -> bool:
        import time as _time

        t = self._recent.get(ledger_hash)
        return t is not None and _time.monotonic() - t < self.RECENT_TTL

    def acquire(
        self, ledger_hash: bytes, callback: Optional[Callable] = None,
        for_lcl: bool = False,
    ) -> InboundLedger:
        """Start (or join) an acquisition. `callback(ledger)` fires for
        THIS request on completion; the global on_complete (the LCL
        adoption hook) fires only for sessions marked ``for_lcl`` —
        repair acquisitions (LedgerCleaner) persist old ledgers without
        ever switching the live chain onto them."""
        il = self.live.get(ledger_hash)
        if callback is not None:
            self._callbacks.setdefault(ledger_hash, []).append(callback)
        if il is None:
            il = InboundLedger(ledger_hash, self.hash_batch)
            il.for_lcl = for_lcl
            self.live[ledger_hash] = il
            self.trigger(il)
        elif for_lcl:
            il.for_lcl = True
        return il

    def abandon(self, ledger_hash: bytes) -> None:
        """Drop a live acquisition (retargeting): callers' slots are
        released with a None result, late replies are absorbed by the
        recently-done set."""
        il = self.live.pop(ledger_hash, None)
        if il is None:
            return
        self._mark_recent(ledger_hash)
        for cb in self._callbacks.pop(ledger_hash, []):
            cb(None)

    def trigger(self, il: InboundLedger) -> None:
        if self.local_fetch is not None:
            if il.header is None:
                # the header lives in the same store under the ledger
                # hash (HP_LEDGER_MASTER-prefixed); a ledger we already
                # hold on disk must not need a peer at all
                blob = self.local_fetch(il.hash)
                if blob is not None:
                    il.take_header(strip_ledger_prefix(blob))
            if il.header is not None and il.resolve_local(self.local_fetch):
                import time as _time

                il.last_progress = _time.monotonic()
            if self._finish(il):
                return
        for req in il.next_requests():
            self.send(req)

    def _finish(self, il: InboundLedger) -> bool:
        """Completion/failure bookkeeping; True when the session ended."""
        if not il.is_complete():
            return False
        h = il.hash
        try:
            ledger = il.build_ledger()
        except (ValueError, KeyError):
            il.failed = True
            del self.live[h]
            self._mark_recent(h)
            for cb in self._callbacks.pop(h, []):
                cb(None)  # failure: callers release their slots
            return True
        del self.live[h]
        self._mark_recent(h)
        for cb in self._callbacks.pop(h, []):
            cb(ledger)
        if self.on_complete is not None and il.for_lcl:
            self.on_complete(ledger)
        return True

    def expire_stale(self, max_age_s: float = 120.0) -> int:
        """Drop acquisitions that made no progress for `max_age_s` —
        unserveable requests (e.g. history no peer holds) must not pin
        sessions and re-broadcast forever (reference: PeerSet failure
        timeouts). Returns the number expired."""
        import time as _time

        now = _time.monotonic()
        stale = [
            h
            for h, il in self.live.items()
            if now - il.last_progress > max_age_s
        ]
        for h in stale:
            del self.live[h]
            self._mark_recent(h)
            for cb in self._callbacks.pop(h, []):
                cb(None)  # expiry: callers release their slots
        return len(stale)

    def take_ledger_data(self, msg: LedgerData) -> int:
        """Route a LedgerData reply; returns how much PROGRESS it made
        (0 = ignored/duplicate/unknown — callers use this to score the
        sending peer). Only replies that made progress re-trigger
        requests — a duplicate reply from a second peer must not fan out
        another request wave (the reference throttles the same way via
        PeerSet progress timeouts)."""
        il = self.live.get(msg.ledger_hash)
        if il is None:
            return 0
        progressed = 0
        if msg.what == W_HEADER:
            for _nid, blob in msg.nodes:
                if il.take_header(blob):
                    progressed += 1
        else:
            progressed = il.take_nodes(msg.what, msg.nodes)
        if progressed:
            import time as _time

            il.last_progress = _time.monotonic()
        if self._finish(il):
            return max(progressed, 1) if not il.failed else progressed
        if progressed:
            self.trigger(il)
        return progressed


def serve_get_ledger(ledger: Optional[Ledger], msg: GetLedger) -> Optional[LedgerData]:
    """Answer a peer's GetLedger from a closed ledger we hold
    (reference: PeerImp::getLedger → TMLedgerData reply)."""
    if ledger is None:
        return None
    if msg.what == W_HEADER:
        return LedgerData(
            msg.ledger_hash, ledger.seq, W_HEADER, [(b"", ledger.header_bytes())]
        )
    tree = ledger.tx_map if msg.what == W_TX_TREE else ledger.state_map
    nodes: list[tuple[bytes, bytes]] = []
    if not msg.node_ids:
        # no specific request → send the root
        ids = [SHAMapNodeID.root()]
    else:
        ids = []
        for nid_wire in msg.node_ids:
            try:
                ids.append(SHAMapNodeID.decode(nid_wire))
            except ValueError:
                continue
    tree.get_hash()
    from ..state.shamap import serialize_node_prefix

    for nid in ids:
        node = _descend(tree, nid)
        if node is None:
            continue
        nodes.append((nid.encode(), serialize_node_prefix(node)))
        # FAT reply (reference: fetch-pack / 'fat' related-node serving):
        # include one extra level under each served inner node, budget-
        # bounded — the acquirer's frontier matching consumes multi-level
        # replies, so each round trip moves the sync two levels
        if hasattr(node, "children") and len(nodes) < MAX_REPLY_NODES:
            for branch, child in enumerate(node.children):
                if child is None:
                    continue
                if len(nodes) >= MAX_REPLY_NODES:
                    break
                nodes.append(
                    (nid.child(branch).encode(), serialize_node_prefix(child))
                )
    if not nodes:
        return None
    return LedgerData(msg.ledger_hash, ledger.seq, msg.what, nodes)


def _descend(tree: SHAMap, nid: SHAMapNodeID):
    node = tree.root
    for nb in nid.nibbles():
        if node is None or not hasattr(node, "children"):
            return None
        node = node.children[nb]
    return node
