"""JobQueue: typed, priority-scheduled thread pool.

Reference: src/ripple_core/functional/JobQueue.{h,cpp} over
beast::Workers — jobs carry a JobType with priority, per-type concurrency
limit and skip-on-overload flag (JobTypes.h:39-167); workers always pull
the highest-priority runnable job; per-type latency is sampled for load
shedding (LoadMonitor).

The job-type table is the batching seam (SURVEY §2.9): same-type jobs
(jtTRANSACTION, jtVALIDATION_*) form the natural batch dimension for the
device verify plane, which coalesces across jobs via VerifyPlane rather
than per-job synchronous verification.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Callable, Optional

__all__ = ["JobType", "JobQueue", "Job", "JOB_LIMITS"]


class JobType(IntEnum):
    """Priority-ordered job types (higher value = higher priority),
    following the reference table JobTypes.h:39-167 / Job.h:38-73."""

    jtPACK = 10  # make fetch pack
    jtPUBOLDLEDGER = 15
    jtVALIDATION_ut = 20  # untrusted validation
    jtPROOFWORK = 23
    jtTRANSACTION_l = 25  # local transaction
    jtPROPOSAL_ut = 30
    jtLEDGER_DATA = 40
    jtCLIENT = 45  # websocket command
    jtRPC = 50
    jtUPDATE_PF = 55
    jtTRANSACTION = 60  # network transaction
    jtADVANCE = 65
    jtPUBLEDGER = 70
    jtTXN_DATA = 75
    jtWAL = 80
    jtVALIDATION_t = 85  # trusted validation
    jtWRITE = 90
    jtACCEPT = 92
    jtPROPOSAL_t = 95
    jtSWEEP = 100
    jtNETOP_CLUSTER = 105
    jtNETOP_TIMER = 110
    jtADMIN = 115


@dataclass
class _Limits:
    limit: int = 0  # max concurrent (0 = unlimited)
    skip: bool = False  # skip-on-overload
    avg_ms: int = 0  # latency targets (load shedding signal)
    peak_ms: int = 0


# reference: JobTypes.h:47-128 (limit, skip, avg, peak)
JOB_LIMITS: dict[JobType, _Limits] = {
    JobType.jtPACK: _Limits(1, True, 0, 0),
    JobType.jtPUBOLDLEDGER: _Limits(2, False, 10000, 15000),
    JobType.jtVALIDATION_ut: _Limits(0, True, 2000, 5000),
    JobType.jtPROOFWORK: _Limits(0, True, 2000, 5000),
    JobType.jtTRANSACTION_l: _Limits(0, False, 100, 500),
    JobType.jtPROPOSAL_ut: _Limits(0, True, 500, 1250),
    JobType.jtLEDGER_DATA: _Limits(2, True, 0, 0),
    JobType.jtCLIENT: _Limits(0, True, 2000, 5000),
    JobType.jtRPC: _Limits(0, False, 0, 0),
    JobType.jtUPDATE_PF: _Limits(1, False, 0, 0),
    JobType.jtTRANSACTION: _Limits(0, False, 250, 1000),
    JobType.jtADVANCE: _Limits(0, False, 0, 0),
    JobType.jtPUBLEDGER: _Limits(0, False, 3000, 4500),
    JobType.jtTXN_DATA: _Limits(1, False, 0, 0),
    JobType.jtWAL: _Limits(0, False, 1000, 2500),
    JobType.jtVALIDATION_t: _Limits(0, False, 500, 1500),
    JobType.jtWRITE: _Limits(0, False, 1750, 2500),
    JobType.jtACCEPT: _Limits(0, False, 0, 0),
    JobType.jtPROPOSAL_t: _Limits(0, False, 100, 500),
    JobType.jtSWEEP: _Limits(0, True, 0, 0),
    JobType.jtNETOP_CLUSTER: _Limits(0, True, 9999, 9999),
    JobType.jtNETOP_TIMER: _Limits(0, True, 999, 999),
    JobType.jtADMIN: _Limits(0, False, 0, 0),
}


@dataclass(order=True)
class Job:
    sort_key: tuple = field(init=False)
    type: JobType = field(compare=False)
    seq: int = field(compare=False)
    name: str = field(compare=False, default="")
    work: Optional[Callable[[], None]] = field(compare=False, default=None)
    queued_at: float = field(compare=False, default=0.0)

    def __post_init__(self):
        # min-heap: invert priority; FIFO within a type
        self.sort_key = (-int(self.type), self.seq)


class _TypeStats:
    __slots__ = (
        "queued", "running", "finished", "dropped", "total_ms", "peak_ms",
        "ewma_ms",
    )

    def __init__(self):
        self.queued = 0
        self.running = 0
        self.finished = 0
        self.dropped = 0
        self.total_ms = 0.0
        self.peak_ms = 0.0
        # recent latency incl. queue wait (LoadMonitor role: the load
        # signal must react to the present, not the lifetime average)
        self.ewma_ms = 0.0


class JobQueue:
    """Priority thread pool with per-type concurrency limits."""

    def __init__(self, threads: int = 4, name: str = "jobq", tracer=None):
        from .tracer import get_tracer

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._heap: list[Job] = []
        self._seq = itertools.count()
        self._stats: dict[JobType, _TypeStats] = {t: _TypeStats() for t in JobType}
        self._stopping = False
        self._threads: list[threading.Thread] = []
        self._name = name
        self.tracer = tracer if tracer is not None else get_tracer()
        self.set_thread_count(threads)

    # -- submission -------------------------------------------------------

    def add_job(self, jtype: JobType, name: str, work: Callable[[], None]) -> bool:
        """Queue a job; returns False when shed by the skip-on-overload
        rule (reference: JobQueue::addJob + PeerImp backlog shed)."""
        lim = JOB_LIMITS[jtype]
        with self._lock:
            if self._stopping:
                return False
            st = self._stats[jtype]
            # skip-on-overload: shed when the per-type backlog is deep
            # (limit-bounded types shed at 2× their concurrency; unlimited
            # skip types at a fixed backlog, the reference's >100-queued
            # PeerImp shed writ large)
            if lim.skip:
                threshold = 2 * lim.limit if lim.limit else 256
                if st.queued >= threshold:
                    st.dropped += 1
                    return False
            st.queued += 1
            heapq.heappush(
                self._heap,
                Job(type=jtype, seq=next(self._seq), name=name, work=work,
                    queued_at=time.monotonic()),
            )
            self._cv.notify()
        return True

    def get_job_count(self, jtype: Optional[JobType] = None) -> int:
        with self._lock:
            if jtype is None:
                return sum(s.queued + s.running for s in self._stats.values())
            s = self._stats[jtype]
            return s.queued + s.running

    def is_overloaded(self) -> bool:
        """Any latency-targeted job type running over its average target
        (reference: JobQueue::isOverloaded → LoadMonitor::isOver). The
        EWMA includes queue wait, so a deep backlog trips this even while
        individual jobs are fast."""
        with self._lock:
            for t, s in self._stats.items():
                target = JOB_LIMITS[t].avg_ms
                if target and s.ewma_ms > target and (s.queued or s.running):
                    return True
        return False

    # -- worker loop ------------------------------------------------------

    def _next_runnable(self) -> Optional[Job]:
        """Pop the highest-priority job whose type is under its concurrency
        limit (reference: JobQueue::getNextJob skips over-limit types)."""
        deferred: list[Job] = []
        job = None
        while self._heap:
            cand = heapq.heappop(self._heap)
            lim = JOB_LIMITS[cand.type]
            if lim.limit and self._stats[cand.type].running >= lim.limit:
                deferred.append(cand)
                continue
            job = cand
            break
        for d in deferred:
            heapq.heappush(self._heap, d)
        return job

    def _worker(self) -> None:
        while True:
            with self._lock:
                job = self._next_runnable()
                while job is None and not self._stopping:
                    self._cv.wait(timeout=0.1)
                    job = self._next_runnable()
                if job is None and self._stopping:
                    return
                st = self._stats[job.type]
                st.queued -= 1
                st.running += 1
            t0 = time.monotonic()
            p0 = time.perf_counter()
            try:
                job.work()
            except Exception:  # noqa: BLE001 — a job must never kill a worker
                import traceback

                traceback.print_exc()
            now = time.monotonic()
            p1 = time.perf_counter()
            ms = (now - t0) * 1000
            # load signal includes the time spent waiting in the queue
            # (reference: LoadMonitor::addSamples measures from queue entry)
            wait_ms = (now - job.queued_at) * 1000
            # queue-wait vs run time per JobType for the tracing plane
            # (the wait interval is re-anchored onto the tracer's clock:
            # queued_at is monotonic, spans are perf_counter)
            tr = self.tracer
            if tr.enabled:
                wait_s = max(0.0, t0 - job.queued_at)
                jt = job.type.name
                tr.complete(f"jobq.{jt}.wait", "jobq", p0 - wait_s, p0,
                            job=job.name)
                tr.complete(f"jobq.{jt}.run", "jobq", p0, p1,
                            job=job.name)
            with self._lock:
                st.running -= 1
                st.finished += 1
                st.total_ms += ms
                st.peak_ms = max(st.peak_ms, ms)
                st.ewma_ms += 0.25 * (wait_ms - st.ewma_ms)
                # a slot freed for a limited type may unblock a deferred job
                self._cv.notify()

    # -- lifecycle --------------------------------------------------------

    def set_thread_count(self, n: int) -> None:
        while len(self._threads) < n:
            t = threading.Thread(
                target=self._worker, name=f"{self._name}-{len(self._threads)}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        """Drain: workers finish queued jobs then exit
        (reference: Stoppable onStop → Workers::pauseAllThreadsAndWait)."""
        with self._lock:
            self._stopping = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=10)

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until no jobs are queued or running (test/standalone aid)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.get_job_count() == 0:
                return True
            time.sleep(0.002)
        return False

    # -- introspection (reference: JobQueue::getJson via get_counts) ------

    def get_json(self) -> dict:
        out = {}
        with self._lock:
            for t, s in self._stats.items():
                if s.finished or s.queued or s.running or s.dropped:
                    out[t.name] = {
                        "queued": s.queued,
                        "running": s.running,
                        "finished": s.finished,
                        "dropped": s.dropped,
                        "avg_ms": s.total_ms / s.finished if s.finished else 0.0,
                        "peak_ms": s.peak_ms,
                    }
        return out
