"""LedgerCleaner: background integrity checker over stored ledgers.

Role parity with /root/reference/src/ripple_app/ledger/LedgerCleaner.cpp
(448 LoC): walk a range of persisted ledgers, verify each loads from the
NodeStore with its recorded hash (Ledger.load recomputes and compares),
verify parent-hash chain linkage against the header index, and count /
report what is broken so the operator (or the acquisition plane) can
repair. Driven by the `ledger_cleaner` admin RPC like the reference.
"""

from __future__ import annotations

import threading
from typing import Optional

__all__ = ["LedgerCleaner"]


class LedgerCleaner:
    def __init__(self, node):
        self.node = node
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.state = "idle"
        self.checked = 0
        self.failed: list[dict] = []
        self.range: tuple[int, int] = (0, 0)
        self.repairs_requested = 0
        self.repaired = 0
        self.repairs_failed = 0

    def start(self, min_seq: Optional[int] = None,
              max_seq: Optional[int] = None) -> dict:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return {"status": "already_running", **self.get_json()}
            seqs = self.node.txdb.ledger_seqs()
            if not seqs:
                return {"status": "no_ledgers"}
            lo = min_seq if min_seq is not None else seqs[0]
            hi = max_seq if max_seq is not None else seqs[-1]
            self.range = (lo, hi)
            self.state = "running"
            self.checked = 0
            self.failed = []
            self.repairs_requested = 0
            self.repaired = 0
            self.repairs_failed = 0
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="ledger-cleaner", daemon=True
            )
            self._thread.start()
        return {"status": "started", "min_ledger": lo, "max_ledger": hi}

    def _run(self) -> None:
        from ..state.ledger import Ledger

        lo, hi = self.range
        prev_hash: Optional[bytes] = None
        for seq in range(hi, lo - 1, -1):  # newest-first like the reference
            if self._stop.is_set():
                with self._lock:
                    self.state = "stopped"
                return
            hdr = self.node.txdb.get_ledger_header(seq=seq)
            if hdr is None:
                self.failed.append({"seq": seq, "problem": "missing header"})
                # walking newest-first, the ledger above already told us
                # this ledger's hash (its parent_hash) — acquirable
                if prev_hash is not None:
                    self._request_repair(seq, prev_hash)
                prev_hash = None  # linkage unknown across the gap
                continue
            try:
                led = Ledger.load(
                    self.node.nodestore, hdr["hash"],
                    hash_batch=self.node.hasher,
                )
            except (KeyError, ValueError) as e:
                self.failed.append({"seq": seq, "problem": f"load: {e}"})
                self._request_repair(seq, hdr["hash"])
                prev_hash = None
                self.checked += 1
                continue
            if prev_hash is not None and prev_hash != hdr["hash"]:
                self.failed.append({"seq": seq, "problem": "chain break"})
            prev_hash = led.parent_hash
            self.checked += 1
        with self._lock:
            self.state = "done"

    # outstanding-repair cap per scan: a large corrupted range must not
    # open thousands of live acquisition sessions at once
    MAX_INFLIGHT_REPAIRS = 32

    def _request_repair(self, seq: int, ledger_hash: bytes) -> None:
        """Ask the acquisition plane to re-fetch a broken/missing stored
        ledger from peers and re-persist it (reference: LedgerCleaner's
        acquire path). No-op without an overlay; capped in flight (the
        stale-acquisition expiry reclaims unserveable requests)."""
        overlay = getattr(self.node, "overlay", None)
        if overlay is None:
            return
        with self._lock:
            in_flight = (
                self.repairs_requested - self.repaired - self.repairs_failed
            )
            if in_flight >= self.MAX_INFLIGHT_REPAIRS:
                return
            self.repairs_requested += 1
        vn = overlay.node

        def on_persisted():
            with self._lock:
                self.repaired += 1

        def on_persist_failed():
            # release the in-flight slot on a failed disk write, or the
            # cleaner's 32-slot repair budget leaks one slot per failure
            with self._lock:
                self.repairs_failed += 1

        def persist(led):
            # led is None when the acquisition expired or failed to
            # build — release the in-flight slot so later repairs in the
            # scan are not starved by unserveable requests
            if led is None:
                on_persist_failed()
                return
            # fires on the overlay message thread UNDER the master lock —
            # hand the disk work to the close pipeline's ordered drain
            # (concurrent TxDatabase batches are not safe, and disk time
            # must not stall consensus); a "repair" entry persists data
            # only, never the CLF resume pointer. Inline fallback for
            # embedders that stubbed the pipeline out.
            pipeline = getattr(self.node, "close_pipeline", None)
            if pipeline is not None:
                pipeline.submit_repair(
                    led,
                    done=lambda _results: on_persisted(),
                    on_failed=on_persist_failed,
                )
                return
            from .node import _results_from_meta

            try:
                self.node.persist_ledger_data(led, _results_from_meta(led))
                on_persisted()
            except Exception:  # noqa: BLE001 — log, keep the cleaner alive
                import logging

                logging.getLogger("stellard.cleaner").exception(
                    "repair persist failed for seq %d", seq
                )
                on_persist_failed()

        with vn.lock:
            vn.inbound.acquire(ledger_hash, callback=persist)

    def stop(self) -> dict:
        """Abort a running scan (reference: the handler's stop verb)."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        return self.get_json()

    def get_json(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "min_ledger": self.range[0],
                "max_ledger": self.range[1],
                "checked": self.checked,
                "failures": list(self.failed[:16]),
                "failure_count": len(self.failed),
                "repairs_requested": self.repairs_requested,
                "repaired": self.repaired,
                "repairs_failed": self.repairs_failed,
            }
