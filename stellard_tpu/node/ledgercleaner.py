"""LedgerCleaner: background integrity checker over stored ledgers, and
OnlineDeleter: rippled-style storage rotation.

LedgerCleaner role parity with
/root/reference/src/ripple_app/ledger/LedgerCleaner.cpp (448 LoC): walk
a range of persisted ledgers, verify each loads from the NodeStore with
its recorded hash (Ledger.load recomputes and compares), verify
parent-hash chain linkage against the header index, and count / report
what is broken so the operator (or the acquisition plane) can repair.
Driven by the `ledger_cleaner` admin RPC like the reference.

OnlineDeleter fills production rippled's ``SHAMapStore`` online_delete
role (``src/ripple/app/misc/SHAMapStoreImp.cpp``): retain the last N
validated ledgers, mark every node reachable from their roots, sweep
the rest out of the store, and let the segstore compactor reclaim the
dead segments — a validator's disk stays bounded near the live set
under an arbitrarily long flood. Where rippled rotates whole backend
instances (copy live into the writable store, archive the old one),
the segmented backend deletes in place: same policy, no double-write
of the live set. The sweep's apply step runs ON the close pipeline's
drain worker (ClosePipeline.submit_task) so no NodeStore flush can be
mid-flight when entries are removed — the flush known-set race
(a flush skipping a node the sweep is about to delete) is closed by
ordering, and the segstore's own in-sweep guards (dedup off +
recent-key protection) cover every writer that isn't the drain worker.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

__all__ = ["LedgerCleaner", "OnlineDeleter"]


class LedgerCleaner:
    def __init__(self, node):
        self.node = node
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.state = "idle"
        self.checked = 0
        self.failed: list[dict] = []
        self.range: tuple[int, int] = (0, 0)
        self.repairs_requested = 0
        self.repaired = 0
        self.repairs_failed = 0

    def start(self, min_seq: Optional[int] = None,
              max_seq: Optional[int] = None) -> dict:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return {"status": "already_running", **self.get_json()}
            seqs = self.node.txdb.ledger_seqs()
            if not seqs:
                return {"status": "no_ledgers"}
            lo = min_seq if min_seq is not None else seqs[0]
            hi = max_seq if max_seq is not None else seqs[-1]
            self.range = (lo, hi)
            self.state = "running"
            self.checked = 0
            self.failed = []
            self.repairs_requested = 0
            self.repaired = 0
            self.repairs_failed = 0
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="ledger-cleaner", daemon=True
            )
            self._thread.start()
        return {"status": "started", "min_ledger": lo, "max_ledger": hi}

    def _run(self) -> None:
        from ..state.ledger import Ledger

        lo, hi = self.range
        prev_hash: Optional[bytes] = None
        for seq in range(hi, lo - 1, -1):  # newest-first like the reference
            if self._stop.is_set():
                with self._lock:
                    self.state = "stopped"
                return
            hdr = self.node.txdb.get_ledger_header(seq=seq)
            if hdr is None:
                self.failed.append({"seq": seq, "problem": "missing header"})
                # walking newest-first, the ledger above already told us
                # this ledger's hash (its parent_hash) — acquirable
                if prev_hash is not None:
                    self._request_repair(seq, prev_hash)
                prev_hash = None  # linkage unknown across the gap
                continue
            try:
                led = Ledger.load(
                    self.node.nodestore, hdr["hash"],
                    hash_batch=self.node.hasher,
                )
            except (KeyError, ValueError) as e:
                self.failed.append({"seq": seq, "problem": f"load: {e}"})
                self._request_repair(seq, hdr["hash"])
                prev_hash = None
                self.checked += 1
                continue
            if prev_hash is not None and prev_hash != hdr["hash"]:
                self.failed.append({"seq": seq, "problem": "chain break"})
            prev_hash = led.parent_hash
            self.checked += 1
        with self._lock:
            self.state = "done"

    # outstanding-repair cap per scan: a large corrupted range must not
    # open thousands of live acquisition sessions at once
    MAX_INFLIGHT_REPAIRS = 32

    def _request_repair(self, seq: int, ledger_hash: bytes) -> None:
        """Ask the acquisition plane to re-fetch a broken/missing stored
        ledger from peers and re-persist it (reference: LedgerCleaner's
        acquire path). No-op without an overlay; capped in flight (the
        stale-acquisition expiry reclaims unserveable requests)."""
        overlay = getattr(self.node, "overlay", None)
        if overlay is None:
            return
        with self._lock:
            in_flight = (
                self.repairs_requested - self.repaired - self.repairs_failed
            )
            if in_flight >= self.MAX_INFLIGHT_REPAIRS:
                return
            self.repairs_requested += 1
        vn = overlay.node

        def on_persisted():
            with self._lock:
                self.repaired += 1

        def on_persist_failed():
            # release the in-flight slot on a failed disk write, or the
            # cleaner's 32-slot repair budget leaks one slot per failure
            with self._lock:
                self.repairs_failed += 1

        def persist(led):
            # led is None when the acquisition expired or failed to
            # build — release the in-flight slot so later repairs in the
            # scan are not starved by unserveable requests
            if led is None:
                on_persist_failed()
                return
            # fires on the overlay message thread UNDER the master lock —
            # hand the disk work to the close pipeline's ordered drain
            # (concurrent TxDatabase batches are not safe, and disk time
            # must not stall consensus); a "repair" entry persists data
            # only, never the CLF resume pointer. Inline fallback for
            # embedders that stubbed the pipeline out.
            pipeline = getattr(self.node, "close_pipeline", None)
            if pipeline is not None:
                pipeline.submit_repair(
                    led,
                    done=lambda _results: on_persisted(),
                    on_failed=on_persist_failed,
                )
                return
            from .node import _results_from_meta

            try:
                self.node.persist_ledger_data(led, _results_from_meta(led))
                on_persisted()
            except Exception:  # noqa: BLE001 — log, keep the cleaner alive
                import logging

                logging.getLogger("stellard.cleaner").exception(
                    "repair persist failed for seq %d", seq
                )
                on_persist_failed()

        with vn.lock:
            vn.inbound.acquire(ledger_hash, callback=persist)

    def stop(self) -> dict:
        """Abort a running scan (reference: the handler's stop verb)."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        return self.get_json()

    def get_json(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "min_ledger": self.range[0],
                "max_ledger": self.range[1],
                "checked": self.checked,
                "failures": list(self.failed[:16]),
                "failure_count": len(self.failed),
                "repairs_requested": self.repairs_requested,
                "repaired": self.repaired,
                "repairs_failed": self.repairs_failed,
            }


class OnlineDeleter:
    """Rotation-driven online deletion (see module docstring).

    Lifecycle per sweep:

    1. ``on_validated(seq)`` — called from the drain worker after each
       CLF commit — starts a background mark thread every ``interval``
       validated ledgers;
    2. the mark thread arms the store's sweep guards
       (``Database.begin_sweep``) and walks every node reachable from
       the retained ledgers' roots ([seq-retain+1, seq]): header blob,
       state tree, tx tree — shared subtrees walk once via the live
       set itself;
    3. the apply step is submitted to the close pipeline
       (``submit_task``): ON the drain worker it catch-up-marks any
       ledger persisted since the mark started (their headers are in
       txdb by drain order), then ``Database.apply_sweep`` removes
       everything else, purges the façade's cache/known-set, and the
       segstore compactor + checkpoint make the deletion durable and
       reclaim the bytes.
    """

    def __init__(self, node, retain: int, interval: int = 0,
                 sql_trim: bool = True, shardstore=None):
        self.node = node
        # history tiering ([node_db] shards=): the retired range is
        # sealed into an offline-verifiable shard BEFORE the sweep
        # deletes it and before trim_below drops its SQL rows — with a
        # shard store configured, rotation tiers history to cold
        # storage instead of discarding it (doc/storage.md)
        self.shardstore = shardstore
        self.retain = max(1, int(retain))
        self.interval = int(interval) if interval > 0 else max(
            1, self.retain // 2
        )
        # also trim the txdb SQL mirror (tx rows, account index, ledger
        # headers, validations) below the same horizon, on the same
        # drain worker — nodestore-only rotation leaves SQLite growing
        # without bound ([node_db] sql_trim=0 opts out)
        self.sql_trim = bool(sql_trim)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_sweep_seq = 0
        # one sweep generation at a time: the backend's sweep guards
        # (_recent_keys / dedup-off) are single-generation state, so a
        # new begin_sweep must not fire while a previous generation's
        # apply task is still queued on the drain worker
        self._apply_pending = False
        # counters (node_store observability block)
        self.sweeps_started = 0
        self.sweeps_completed = 0
        self.nodes_removed = 0
        self.last_marked = 0
        self.last_removed = 0
        self.last_sweep_ms = 0.0
        self.last_retain_floor = 0
        self.sql_rows_trimmed = 0
        self.last_sql_trimmed = 0
        self.shards_sealed = 0
        self.seal_failures = 0

    # -- hooks -------------------------------------------------------------

    def on_validated(self, seq: int) -> None:
        """Drain-worker hook (after a durable CLF commit): start a sweep
        every `interval` validated ledgers. Cheap when idle."""
        with self._lock:
            if self._stop.is_set():
                return
            if self._thread is not None and self._thread.is_alive():
                return
            if self._apply_pending:
                return  # previous generation's apply not yet landed
            if seq - self._last_sweep_seq < self.interval:
                return
            self._last_sweep_seq = seq
            self.sweeps_started += 1
            self._thread = threading.Thread(
                target=self._run, args=(seq,), daemon=True,
                name="online-delete",
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10)

    # -- sweep -------------------------------------------------------------

    def _run(self, validated_seq: int) -> None:
        db = self.node.nodestore
        t0 = time.perf_counter()
        try:
            db.begin_sweep()
            live: set[bytes] = set()
            lo = max(1, validated_seq - self.retain + 1)
            self.last_retain_floor = lo
            for seq in range(lo, validated_seq + 1):
                if self._stop.is_set():
                    db.cancel_sweep()
                    return
                self._mark_seq(seq, live)
        except Exception:  # noqa: BLE001 — a failed mark must disarm
            db.cancel_sweep()
            logging.getLogger("stellard.cleaner").exception(
                "online-delete mark failed (sweep skipped)"
            )
            return

        def apply_task():
            # ON the drain worker: no save_stage can be concurrent
            try:
                if self._stop.is_set():
                    db.cancel_sweep()
                    return
                try:
                    # catch-up mark: ledgers persisted since the mark
                    # began — contiguous from validated_seq+1, walked by
                    # direct header lookup (a full ledger_seqs() scan
                    # here would stall the drain worker, and before SQL
                    # trimming existed it also grew without bound)
                    seq = validated_seq + 1
                    while True:
                        hdr = self.node.txdb.get_ledger_header(seq=seq)
                        if hdr is None:
                            break
                        self._mark_seq(seq, live)
                        seq += 1
                    if self.shardstore is not None:
                        # tiering contract: history leaves the live
                        # store only AFTER its shard sealed — a failed
                        # seal skips this whole sweep generation (disk
                        # keeps growing, loudly) rather than deleting
                        # unsealed history
                        if not self._seal_retired(lo, live):
                            db.cancel_sweep()
                            return
                    removed = db.apply_sweep(live)
                except Exception:  # noqa: BLE001
                    db.cancel_sweep()
                    logging.getLogger("stellard.cleaner").exception(
                        "online-delete apply failed (sweep skipped)"
                    )
                    return
                trimmed = 0
                if self.sql_trim:
                    # SQL mirror rotation, ON the drain worker (it owns
                    # every txdb write, so no batch can be concurrent):
                    # the horizon is the same retain floor the mark used
                    try:
                        trimmed = sum(
                            self.node.txdb.trim_below(lo).values()
                        )
                    except Exception:  # noqa: BLE001 — trimming is an
                        # optimization over intact history; never fail
                        # the sweep for it
                        logging.getLogger("stellard.cleaner").exception(
                            "online-delete SQL trim failed (skipped)"
                        )
                with self._lock:
                    self.sql_rows_trimmed += trimmed
                    self.last_sql_trimmed = trimmed
                    self.sweeps_completed += 1
                    self.nodes_removed += removed
                    self.last_marked = len(live)
                    self.last_removed = removed
                    self.last_sweep_ms = round(
                        (time.perf_counter() - t0) * 1000.0, 2
                    )
            finally:
                with self._lock:
                    self._apply_pending = False

        def apply_failed():
            db.cancel_sweep()
            with self._lock:
                self._apply_pending = False

        with self._lock:
            self._apply_pending = True
        self.node.close_pipeline.submit_task(
            apply_task, on_failed=apply_failed
        )

    def _seal_retired(self, floor: int, live: set) -> bool:
        """Seal every stored-but-retiring ledger (seq < floor, above the
        last sealed shard) into history shards, one shard per contiguous
        header run. Runs ON the drain worker right before apply_sweep —
        by drain order no flush is concurrent, so the walked blobs are
        exactly what the sweep would delete. Returns False when a seal
        failed (the caller must then skip the sweep)."""
        from ..nodestore.shards import collect_retired

        txdb = self.node.txdb
        db = self.node.nodestore
        sealed_range = self.shardstore.range()
        start = sealed_range[1] + 1 if sealed_range else 1
        start = max(start, getattr(txdb, "retain_floor", 0) or 1)
        runs: list[list[dict]] = []
        cur: list[dict] = []
        for seq in range(start, floor):
            hdr = txdb.get_ledger_header(seq=seq)
            if hdr is None:
                if cur:
                    runs.append(cur)
                    cur = []
                continue
            cur.append(hdr)
        if cur:
            runs.append(cur)

        def fetch(h: bytes):
            obj = db.fetch(h, populate_cache=False)
            return obj.data if obj is not None else None

        for run in runs:
            lo_s, hi_s = run[0]["seq"], run[-1]["seq"]
            try:
                records = collect_retired(fetch, run, live)
                acct_rows = txdb.account_tx_index(lo_s, hi_s)
                self.shardstore.seal(
                    lo_s, hi_s, records, acct_rows,
                    first_hash=run[0]["hash"], last_hash=run[-1]["hash"],
                )
                with self._lock:
                    self.shards_sealed += 1
            except Exception:  # noqa: BLE001 — never delete unsealed
                with self._lock:
                    self.seal_failures += 1
                logging.getLogger("stellard.cleaner").exception(
                    "history-shard seal failed for [%d, %d] "
                    "(sweep skipped; disk keeps history)", lo_s, hi_s,
                )
                return False
        return True

    def _mark_seq(self, seq: int, live: set) -> None:
        hdr = self.node.txdb.get_ledger_header(seq=seq)
        if hdr is None:
            return
        live.add(hdr["hash"])  # the stored header object itself
        self._mark_tree(hdr["account_hash"], live)
        self._mark_tree(hdr["tx_hash"], live)

    def _mark_tree(self, root_hash: bytes, live: set) -> None:
        """Mark every reachable node by walking stored blobs directly
        (prefix-format: an inner node is HP_INNER_NODE + 16 child
        hashes) — no SHAMap materialization, and the live set itself
        memoizes shared subtrees across retained ledgers."""
        from ..state.shamap import ZERO256
        from ..utils.hashes import HP_INNER_NODE

        inner_prefix = HP_INNER_NODE.to_bytes(4, "big")
        db = self.node.nodestore
        stack = [root_hash]
        while stack:
            h = stack.pop()
            if h == ZERO256 or h in live:
                continue
            # facade fetch (pending writes must be visible) but without
            # cache insertion: an O(live-set) walk would otherwise
            # evict every hot close-path entry each sweep
            obj = db.fetch(h, populate_cache=False)
            if obj is None:
                continue  # history gap: nothing below it to retain
            live.add(h)
            blob = obj.data
            if blob[:4] == inner_prefix:
                for i in range(16):
                    stack.append(blob[4 + 32 * i: 36 + 32 * i])

    def get_json(self) -> dict:
        with self._lock:
            return {
                "retain": self.retain,
                "interval": self.interval,
                "running": (
                    self._thread is not None and self._thread.is_alive()
                ),
                "sweeps_started": self.sweeps_started,
                "sweeps_completed": self.sweeps_completed,
                "nodes_removed": self.nodes_removed,
                "last_marked": self.last_marked,
                "last_removed": self.last_removed,
                "last_sweep_ms": self.last_sweep_ms,
                "last_retain_floor": self.last_retain_floor,
                "sql_trim": self.sql_trim,
                "sql_rows_trimmed": self.sql_rows_trimmed,
                "last_sql_trimmed": self.last_sql_trimmed,
                "shards_enabled": self.shardstore is not None,
                "shards_sealed": self.shards_sealed,
                "seal_failures": self.seal_failures,
            }
