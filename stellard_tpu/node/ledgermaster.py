"""LedgerMaster: the ledger-chain state machine.

Reference: src/ripple_app/ledger/LedgerMaster.cpp (1469 LoC) — tracks the
current open ledger, last closed ledger and last validated ledger
(LedgerHolder triples), holds transactions that can't apply yet
(terPRE_SEQ et al.) for retry on the next ledger, and accepts a ledger as
validated once a quorum of trusted validations arrives (checkAccept,
:705-750). Also CanonicalTXSet (misc/CanonicalTXSet.cpp): the salted
canonical application order used when a closed ledger's tx set is
applied.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..engine.engine import TransactionEngine, TxParams
from ..node.hashrouter import SF_SIGGOOD
from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from ..state.ledger import Ledger
from .metrics import AtomicCounters, LatencyHist
from .tracer import STAGE_BOUNDS, get_tracer

__all__ = ["LedgerMaster", "CanonicalTXSet", "LEDGER_TOTAL_PASSES"]

# reference: applyTransactions retry sizing (LedgerConsensus.cpp:1935-2070)
LEDGER_TOTAL_PASSES = 4

# held-pile bounds (reference: mHeldTransactions is unbounded — a
# single-account sequence-gap flood pinned memory forever): entries
# expire after this many closes, and the pile itself is capped with
# FIFO eviction. With the TxQ enabled the pile is absorbed into the
# fee-ordered queue instead and these bounds are the fallback path.
HELD_EXPIRE_LEDGERS = 16
HELD_CAP = 1024


class CanonicalTXSet:
    """Salted canonical ordering (reference: misc/CanonicalTXSet.{h,cpp}):
    sort key = (account XOR salt, sequence, txid); the salt is the parent
    ledger hash so the order is unpredictable to submitters but identical
    on every node."""

    def __init__(self, salt: bytes):
        self.salt = salt
        self._map: dict[tuple, SerializedTransaction] = {}

    def insert(self, tx: SerializedTransaction) -> None:
        acct = int.from_bytes(tx.account, "big")
        salt = int.from_bytes(self.salt[:20], "big")
        self._map[(acct ^ salt, tx.sequence, tx.txid())] = tx

    def erase(self, key: tuple) -> None:
        self._map.pop(key, None)

    def values(self):
        return self._map.values()

    def __len__(self):
        return len(self._map)

    def items_sorted(self) -> list[tuple[tuple, SerializedTransaction]]:
        return sorted(self._map.items())


class LedgerMaster:
    """Holds the chain: validated ←closed ←current(open)."""

    def __init__(
        self, hash_batch: Optional[Callable] = None, router=None,
        tracer=None,
    ):
        self._lock = threading.RLock()
        self.hash_batch = hash_batch
        # tracing plane: close-stage spans + per-tx splice/fallback marks
        # (consensus rounds built over this chain trace through it too)
        self.tracer = tracer if tracer is not None else get_tracer()
        # HashRouter: close-time re-application consults SF_SIGGOOD so
        # txs verified at submit are not host-re-verified per close
        # (reference: LedgerConsensus::applyTransaction skips checkSign
        # via SF_SIGGOOD, LedgerConsensus.cpp:2101-2106)
        self.router = router
        self.current: Optional[Ledger] = None  # open
        self.closed: Optional[Ledger] = None  # last closed (LCL)
        self.validated: Optional[Ledger] = None
        self.ledger_history: dict[int, bytes] = {}  # seq -> hash
        # closed-ledger cache: bounded + aged so a long-running node's
        # memory does not grow with chain length (reference: LedgerHistory
        # TaggedCache, tuned at Application.cpp:723-727)
        from ..utils.taggedcache import TaggedCache

        self.ledgers_by_hash: TaggedCache = TaggedCache(
            "ledger_history", target_size=512, expiration_s=600.0
        )
        # optional loader for cache misses (Node wires the NodeStore in;
        # overlay validators are memory-resident and leave it unset)
        self.fetch_fallback: Optional[Callable[[bytes], Optional[Ledger]]] = None
        # optional LIGHT resolver: ledger hash -> (seq, parent_hash)
        # from the stored header alone (no tree loads) — used by the
        # LCL-switch reindex walk
        self.header_fetch: Optional[
            Callable[[bytes], Optional[tuple[int, bytes]]]
        ] = None
        # txns held for a future ledger (reference: mHeldTransactions)
        # value is (tx, expire_seq): bounded + expired by ledger seq so
        # a sequence-gap flood cannot pin memory forever
        self.held: dict[tuple[bytes, int], tuple[SerializedTransaction, int]] = {}
        self.held_stats = {"evicted": 0, "expired": 0}
        # admission-control plane ([txq]): wired by Node; promotion of
        # queued txs into each new open ledger happens at _open_next
        self.txq = None
        self.min_validations = 0  # quorum for checkAccept
        self.on_validated: Optional[Callable[[Ledger], None]] = None
        # optional persist-row materializer (Node wires build_tx_rows):
        # when set, the close overlaps this Python tail with the seal
        # tree-hash, whose native/device batches release the GIL
        self.persist_prep: Optional[Callable[[Ledger, dict], list]] = None
        # speculative delta-replay close ([close] delta_replay): the
        # open-ledger accept also runs the tx once in close mode against
        # a SpecView, and the close splices the recorded delta when the
        # read set still validates (engine/deltareplay.py)
        self.delta_replay = True
        # close-info counters live in one AtomicCounters bundle: the
        # close path, the TxQ's deferred promotion job, and the parallel
        # executor's commit thread all feed close-adjacent counters from
        # their own threads, and bare `dict +=` would lose updates
        self.delta_stats = AtomicCounters(
            "closes", "spliced", "fallback", "invalidated",
        )
        self.last_close: dict = {}
        # parallel speculative executor ([spec] workers=N, engine/
        # specexec.py): when active, _speculate_open dispatches to the
        # worker pool instead of executing inline, and the close drains
        # the window before consuming the records. None/inactive keeps
        # the serial inline path byte-for-byte.
        self.spec_executor = None
        # incremental O(dirty) seal ([tree] incremental, default on):
        # speculated writes fold into a pre-seal "building" tree on the
        # SpecState, and a background drainer hashes its dirty subtrees
        # through the routed hash plane between closes — the in-close
        # seal then adopts the pre-hashed root and hashes only the
        # residual (engine/deltareplay.py maybe_adopt_prehashed). The
        # full serial seal remains the per-close fallback, never forked.
        self.incremental_seal = True
        self.seal_drain_batch = 256  # writes folded before a drain fires
        self.tree_stats = {
            "drains": 0, "drained_nodes": 0, "seal_adopted": 0,
            "seal_rejected": 0, "seal_residual_keys": 0,
            "bulk_merges": 0, "bulk_merged_keys": 0,
        }
        self._drain_hist = LatencyHist(bounds=STAGE_BOUNDS, interpolate=True)
        self._drain_cv = threading.Condition()
        self._drain_pending = 0
        self._drain_kick = False
        self._drain_busy = False
        self._drainer: Optional[threading.Thread] = None
        self._drain_stop = False
        # per-close stage latency histograms (ms): apply pass, seal
        # overlap, total — the shared metrics.LatencyHist (fine-grained
        # bounds: closes live in the 1-500 ms band)
        self.close_stage_hist: dict[str, LatencyHist] = {
            "apply": LatencyHist(bounds=STAGE_BOUNDS, interpolate=True),
            "seal": LatencyHist(bounds=STAGE_BOUNDS, interpolate=True),
            "total": LatencyHist(bounds=STAGE_BOUNDS, interpolate=True),
        }

    # -- bootstrap --------------------------------------------------------

    def start_new_ledger(self, root_account_id: bytes, close_time: int = 0) -> None:
        """Fresh genesis chain (reference: Application::startNewLedger —
        builds the seq-1 genesis, closes it, opens seq 2 on top)."""
        with self._lock:
            genesis = Ledger.genesis(root_account_id, close_time=close_time,
                                     hash_batch=self.hash_batch)
            genesis.close(close_time, genesis.close_resolution)
            genesis.accepted = True
            self._push_closed(genesis)
            self.validated = genesis
            self.current = genesis.open_successor()

    def load_ledger(self, ledger: Ledger) -> None:
        """Resume from a stored closed ledger (reference: loadOldLedger)."""
        with self._lock:
            ledger.accepted = True
            self._push_closed(ledger)
            self.validated = ledger
            self.current = ledger.open_successor()

    def _push_closed(self, ledger: Ledger) -> None:
        self.closed = ledger
        h = ledger.hash()
        # the validated chain is AUTHORITATIVE for its index slots: a
        # stale round churning out a late close at an already-validated
        # seq (fork-repair flapping) must not clobber the validated
        # entry — its validation is already refused by can_sign, and
        # the history index must stay the validated truth (scenario-
        # fuzzer find: honest histories permanently disagreed after a
        # partition healed through competing branches)
        floor = self.validated.seq if self.validated is not None else 0
        if ledger.seq > floor or self.ledger_history.get(ledger.seq) is None:
            self.ledger_history[ledger.seq] = h
        if len(self.ledger_history) > 8192:
            # bound the seq index too; full history stays in txdb/nodestore
            del self.ledger_history[min(self.ledger_history)]
        self.ledgers_by_hash.put(h, ledger)

    # -- accessors --------------------------------------------------------

    def current_ledger(self) -> Ledger:
        with self._lock:
            assert self.current is not None, "LedgerMaster not started"
            return self.current

    def closed_ledger(self) -> Ledger:
        with self._lock:
            assert self.closed is not None, "LedgerMaster not started"
            return self.closed

    def get_ledger_by_seq(self, seq: int) -> Optional[Ledger]:
        with self._lock:
            h = self.ledger_history.get(seq)
            return self.ledgers_by_hash.get(h) if h else None

    def get_ledger_by_hash(self, h: bytes) -> Optional[Ledger]:
        with self._lock:
            led = self.ledgers_by_hash.get(h)
            if led is None and self.fetch_fallback is not None:
                led = self.fetch_fallback(h)
                if led is not None:
                    self.ledgers_by_hash.put(h, led)
            return led

    # -- held transactions (reference: addHeldTransaction) ----------------

    def add_held_transaction(self, tx: SerializedTransaction) -> None:
        with self._lock:
            now = self.closed.seq if self.closed is not None else 0
            self._hold(tx, now + HELD_EXPIRE_LEDGERS)

    def _hold(self, tx: SerializedTransaction, expire_seq: int) -> None:
        """Insert with the pile's cap: a full pile evicts its OLDEST
        entry (insertion order) rather than growing without bound."""
        key = (tx.account, tx.sequence)
        if key in self.held:
            # re-hold after a retry keeps the ORIGINAL horizon — a
            # never-applicable tx must not refresh itself forever
            expire_seq = min(expire_seq, self.held[key][1])
        elif len(self.held) >= HELD_CAP:
            self.held.pop(next(iter(self.held)))
            self.held_stats["evicted"] += 1
        self.held[key] = (tx, expire_seq)

    def _drain_held(self) -> list[tuple[SerializedTransaction, int]]:
        """Take every live (tx, expire_seq) pair, dropping expired
        entries. Caller holds the lock."""
        now = self.closed.seq if self.closed is not None else 0
        entries = list(self.held.values())
        self.held.clear()
        live = []
        for tx, expire in entries:
            if expire < now:
                self.held_stats["expired"] += 1
            else:
                live.append((tx, expire))
        return live

    def take_held_transactions(self) -> list[SerializedTransaction]:
        with self._lock:
            return [tx for tx, _expire in self._drain_held()]

    # -- apply to the open ledger (reference: doTransaction) --------------

    def do_transaction(self, tx: SerializedTransaction, params: TxParams) -> tuple[TER, bool]:
        with self._lock:
            return self._open_apply(tx, params)

    def _open_apply(self, tx: SerializedTransaction, params: TxParams,
                    speculate: bool = True) -> tuple[TER, bool]:
        """Apply to the open ledger; on accept, seed the parsed-tx memo
        and run the speculative close-mode execution. Caller holds the
        lock. `speculate=False` defers the close-mode dry run — the TxQ
        promotion path uses it to keep the (expensive) speculation OFF
        the close window and re-runs it on a deferred job
        (TxQ._drain_deferred_spec -> _speculate_open)."""
        open_ledger = self.current_ledger()
        engine = TransactionEngine(open_ledger)
        with self.tracer.span("open.apply", "apply", txid=tx.txid(),
                              ledger_seq=open_ledger.seq):
            ter, applied = engine.apply_transaction(tx, params)
        if applied:
            # seed the OPEN ledger's parsed-tx memo so the close path
            # reuses this exact object instead of re-parsing the blob
            # (txid is the blob's content hash). Ownership contract: a
            # submitted tx belongs to the node FOREVER — the object
            # escapes into the closed ledger's parsed_txs and is served
            # from history caches — so callers must never mutate it.
            open_ledger.parsed_txs[tx.txid()] = tx
            # speculate only for OPEN-mode accepts: the open window
            # never mutates ledger state, which is the invariant that
            # makes the SpecView's parent reads equal to the state the
            # close will start from (a close-mode apply through this
            # path would break it)
            if speculate and (int(params) & int(TxParams.OPEN_LEDGER)):
                self._speculate_open(open_ledger, tx)
        return ter, applied

    def _speculate_open(self, open_ledger: Ledger,
                        tx: SerializedTransaction,
                        origin: str = "submit") -> None:
        """Close-mode dry run of an open-accepted tx against the open
        window's speculative overlay (engine/deltareplay.py), creating
        the SpecState on first use. `origin` tags the record so the
        queue's promotion counters can tell spliced-promoted txs apart
        from submit-time speculation."""
        if not self.delta_replay:
            return
        spec = getattr(open_ledger, "_spec_state", None)
        if spec is None:
            from ..engine.deltareplay import SpecState

            spec = open_ledger._spec_state = SpecState(open_ledger)
            if self.incremental_seal:
                # the open window never mutates the state map, so
                # its root IS the parent state the close starts
                # from — the building tree folds speculated
                # writes onto it and pre-hashes between closes
                spec.attach_building(
                    open_ledger.state_map.root, self.hash_batch
                )
        if tx.txid() in spec.records:
            return
        ex = self.spec_executor
        if ex is not None and ex.active:
            # parallel plane: dispatch to the worker pool (O(1) under
            # the chain lock — the execution itself runs on workers and
            # commits in index order off this thread). Folding into the
            # building tree rides the commit step via _note_fold.
            session = getattr(spec, "_exec_session", None)
            if session is None and ex.can_accept:
                session = spec._exec_session = ex.begin_window(
                    spec, open_ledger, on_fold=self._note_fold,
                )
            if session is not None:
                if ex.dispatch(session, tx, origin):
                    return
                # executor refused (stopping / pool dead): seal the
                # window so no late commit races the serial path, then
                # fall through
                ex.end_window(session, timeout=ex.drain_timeout_s)
                spec._exec_session = None
        with self.tracer.span("open.speculate", "apply",
                              txid=tx.txid(), origin=origin):
            spec.speculate(tx, origin=origin)
        rec = spec.records.get(tx.txid())
        if rec is not None and spec.building is not None:
            folded = spec.fold_building(rec)
            if folded:
                self._note_fold(folded)

    # -- incremental-seal background drain --------------------------------

    def _ensure_drainer_locked(self) -> None:
        """Lazily start the seal-drain thread; caller holds _drain_cv."""
        if self._drainer is None and not self._drain_stop:
            self._drainer = threading.Thread(
                target=self._drain_loop, name="seal-drain",
                daemon=True,
            )
            self._drainer.start()

    def _note_fold(self, n_ops: int) -> None:
        """Count folded writes; past the drain batch, wake the drainer to
        pre-hash the building tree's dirty subtrees off this thread.
        drain_batch < 1 disables background drains entirely (folding and
        root adoption still run; the seal just hashes at close time)."""
        if self.seal_drain_batch < 1:
            return
        with self._drain_cv:
            self._drain_pending += n_ops
            if self._drain_pending >= self.seal_drain_batch:
                self._ensure_drainer_locked()
                self._drain_cv.notify()

    def kick_seal_drain(self, wait_s: float = 0.0) -> None:
        """Flush the sub-batch fold residual to the background pre-hash
        thread NOW (the parallel executor's pre-close advisory drain
        lands folds in a burst right before the close — without a kick
        they would sit below the drain-batch threshold and get hashed
        inside the close's lock window instead of outside it). With
        ``wait_s``, block up to that long for the drainer to go idle so
        a caller about to close sees the pre-hash actually finished —
        still outside any lock, and bounded."""
        if self.seal_drain_batch < 1:
            return
        with self._drain_cv:
            if self._drain_pending > 0:
                self._ensure_drainer_locked()
                self._drain_kick = True
                self._drain_cv.notify()
            if wait_s > 0:
                deadline = time.perf_counter() + wait_s
                while (self._drain_pending > 0 or self._drain_kick
                       or self._drain_busy) and not self._drain_stop:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._drain_cv.wait(min(remaining, 0.05))

    def _drain_loop(self) -> None:
        from ..state.shamap import compute_hashes

        # the hasher is fixed per LedgerMaster: probe its hash_tree
        # hint capability once, not one inspect.signature per drain
        supports_hint: Optional[bool] = None
        while True:
            with self._drain_cv:
                # max(1, batch): a runtime knob change to <1 must idle
                # the thread (pending only grows via _note_fold, which
                # gates on the same knob), never spin it
                while (self._drain_pending < max(1, self.seal_drain_batch)
                       and not self._drain_kick
                       and not self._drain_stop):
                    self._drain_cv.wait(timeout=1.0)
                if self._drain_stop:
                    return
                todo = self._drain_pending
                self._drain_pending = 0
                self._drain_kick = False
                self._drain_busy = True
            # snapshot the building tree UNDER the chain lock, hash it
            # OUTSIDE: the tree is persistent, so hashing a snapshot
            # root only fills write-once _hash slots on nodes the
            # foreground shares — concurrent folds build new paths and
            # never touch fields this walk writes
            with self._lock:
                cur = self.current
                spec = getattr(cur, "_spec_state", None) if cur else None
                building = spec.building if spec is not None else None
                root = building.root if building is not None else None
                hasher = building.hash_batch if building is not None else None
            if root is None:
                with self._drain_cv:
                    self._drain_busy = False
                    self._drain_cv.notify_all()
                continue
            t0 = time.perf_counter()
            try:
                tree_fn = getattr(hasher, "hash_tree", None)
                if tree_fn is not None \
                        and not getattr(hasher, "fused_enabled", True):
                    tree_fn = None  # [tree] fused=0: staged per-level
                if tree_fn is not None:
                    if supports_hint is None:
                        import inspect

                        supports_hint = (
                            "hint_nodes"
                            in inspect.signature(tree_fn).parameters
                        )
                    if supports_hint:
                        n = tree_fn(root, hint_nodes=todo)
                    else:
                        n = tree_fn(root)
                else:
                    n = compute_hashes(root, hasher)
            except Exception:  # noqa: BLE001 — pre-hashing is advisory;
                # the close's full seal recomputes whatever is missing
                with self._drain_cv:
                    self._drain_busy = False
                    self._drain_cv.notify_all()
                continue
            t1 = time.perf_counter()
            with self._drain_cv:
                self.tree_stats["drains"] += 1
                self.tree_stats["drained_nodes"] += n
                self._drain_busy = False
                self._drain_cv.notify_all()
            self._drain_hist.record((t1 - t0) * 1000.0)
            self.tracer.complete("seal.incremental", "seal", t0, t1,
                                 nodes=n)

    def stop_seal_drainer(self) -> None:
        """Stop the background pre-hash thread (Node.stop). Idempotent;
        a stopped LedgerMaster never restarts it."""
        with self._drain_cv:
            self._drain_stop = True
            self._drain_cv.notify_all()
        t = self._drainer
        if t is not None:
            t.join(timeout=5)

    def tree_json(self) -> dict:
        """Batched-commit-plane counters for get_counts/server_state."""
        with self._drain_cv:
            out = dict(self.tree_stats)
        out["incremental_seal"] = self.incremental_seal
        out["drain_batch"] = self.seal_drain_batch
        if self._drain_hist.count:
            out["drain_p50_ms"] = self._drain_hist.quantile(0.5)
            out["drain_p90_ms"] = self._drain_hist.quantile(0.9)
        return out

    # -- close (standalone / consensus-accept share this tail) ------------

    def _parse_with_verdict(self, open_ledger: Ledger, txid: bytes, blob: bytes):
        """Parse an open-ledger blob — or reuse the submit-time parsed
        object from the ledger's own memo (txid is content-addressed,
        so a hit is byte-equal) — carrying over the submit-time
        SF_SIGGOOD verdict so close/re-apply never host-re-verifies
        (reference: LedgerConsensus::applyTransaction skips checkSign
        via SF_SIGGOOD, LedgerConsensus.cpp:2101-2106)."""
        tx = open_ledger.parse_tx(txid, blob)
        if self.router is not None and (
            self.router.get_flags(txid) & SF_SIGGOOD
        ):
            tx.set_sig_verdict(True)
        return tx

    def _seal(self, new_lcl: Ledger, results: dict[bytes, TER]) -> None:
        """Shared seal tail of both close paths: compute the two tree
        hashes while the persist-row materialization runs.

        The tree hashes are the close's crypto block — their batches run
        in the GIL-releasing native/device hashers when configured — so
        the tx map and the state map each hash on their OWN helper
        thread (the two trees are disjoint, and the device hasher's
        routing model is thread-safe, so the two fused chains overlap on
        the mesh) while THIS thread does the pure-Python persist tail
        (meta parse, affected-account walk, row build). The SHAMap is
        persistent: hashing only fills node._hash slots, and the row
        walk reads item data/children, so the traversals never write the
        same fields. A hashing failure on a helper thread is absorbed —
        _push_closed recomputes serially.

        Emits the transfer-honesty spans: ``close.device.fused`` (the
        overlapped hash window + whether the fused whole-tree pipeline
        was eligible) and ``close.device.transfer`` (per-close deltas of
        the hash plane's TransferMeter — the device-residency proof)."""
        if self.persist_prep is None:
            return
        t0 = time.perf_counter()
        tj = getattr(self.hash_batch, "transfer_json", None)
        before = tj() if tj is not None else None

        done = threading.Event()
        pending = [2]
        plock = threading.Lock()

        def _arm(get_hash):
            def run():
                try:
                    get_hash()
                except Exception:  # noqa: BLE001 — recomputed on push
                    pass
                finally:
                    with plock:
                        pending[0] -= 1
                        if pending[0] == 0:
                            done.set()
            return run

        threads = [
            threading.Thread(target=_arm(new_lcl.tx_map.get_hash),
                             name="seal-hash-tx"),
            threading.Thread(target=_arm(new_lcl.state_map.get_hash),
                             name="seal-hash-state"),
        ]
        for t in threads:
            t.start()
        try:
            new_lcl.persist_rows = self.persist_prep(new_lcl, results)
        except Exception:  # noqa: BLE001 — the persist stage rebuilds rows
            pass
        finally:
            done.wait()
            for t in threads:
                t.join()
        t1 = time.perf_counter()
        self.tracer.complete(
            "close.device.fused", "seal", t0, t1,
            fused=bool(getattr(self.hash_batch, "fused_enabled", True)),
            seq=new_lcl.seq,
        )
        if before is not None:
            after = tj()
            if after is not None:
                self.tracer.complete(
                    "close.device.transfer", "seal", t0, t1,
                    seq=new_lcl.seq,
                    uploads=after["uploads"] - before["uploads"],
                    readbacks=after["readbacks"] - before["readbacks"],
                    transfers=after["transfers"] - before["transfers"],
                    bytes_moved=(after["bytes_moved"]
                                 - before["bytes_moved"]),
                )

    def close_and_advance(
        self,
        close_time: int,
        close_resolution: int,
        correct_close_time: bool = True,
        extra_txs: Optional[list[SerializedTransaction]] = None,
    ) -> tuple[Ledger, dict[bytes, TER]]:
        """Build the next closed ledger from the open ledger's tx set and
        advance the chain. This is the shared tail of the reference's
        LedgerConsensus::accept (:931-1127) and the standalone
        `ledger_accept` path (NetworkOPs::acceptLedger):

        1. collect the open ledger's txns (+ any consensus extras) into a
           CanonicalTXSet salted by the parent hash,
        2. re-apply them to a successor of the LCL with retry passes
           (applyTransactions, LedgerConsensus.cpp:1935-2070),
        3. seal it, open the next ledger, re-apply held txns.

        Returns (new closed ledger, per-txid results).
        """
        with self._lock:
            t0 = time.perf_counter()
            prev = self.closed_ledger()
            open_ledger = self.current_ledger()

            # 1. canonical set from the open ledger's recorded blobs;
            # SF_SIGGOOD verdicts memoized at submit time carry over to
            # the freshly-parsed copies (the reference's close path
            # skips checkSign the same way)
            txset = CanonicalTXSet(prev.hash())
            for txid, blob, _meta in open_ledger.tx_entries():
                txset.insert(self._parse_with_verdict(open_ledger, txid, blob))
            for tx in extra_txs or []:
                txset.insert(tx)

            # 2. successor of the LCL; apply with retry passes, splicing
            # speculative deltas where the open pass's records validate
            new_lcl = prev.open_successor()
            spec = (
                getattr(open_ledger, "_spec_state", None)
                if self.delta_replay else None
            )
            self._drain_spec(spec)
            results = self._apply_transactions(new_lcl, txset, spec=spec)
            t_apply = time.perf_counter()

            # 3. seal + advance
            new_lcl.close(close_time, close_resolution, correct_close_time)
            new_lcl.accepted = True
            # seed the parsed-tx memo so persist/publish reuse these
            # exact objects instead of re-parsing every blob
            for tx in txset.values():
                new_lcl.parsed_txs[tx.txid()] = tx
            # overlap: tree-hash (GIL-releasing crypto batches) on a
            # helper thread while the persist rows materialize here
            self._seal(new_lcl, results)
            t_seal = time.perf_counter()
            self._push_closed(new_lcl)
            self._open_next(new_lcl, (t_apply - t0) * 1000.0)

            # standalone trusts its own closes (reference: standalone mode
            # skips validations; checkAccept quorum handles the net case)
            if self.min_validations == 0:
                self.validated = new_lcl
                if self.on_validated:
                    self.on_validated(new_lcl)

            self._note_close_stages(t0, t_apply, t_seal, new_lcl.seq)
            return new_lcl, results

    def close_with_txset(
        self,
        txs: list[SerializedTransaction],
        close_time: int,
        close_resolution: int,
        correct_close_time: bool = True,
    ) -> tuple[Ledger, dict[bytes, TER]]:
        """Consensus-accept path (reference: LedgerConsensus::accept,
        :931-1127): close the chain with the *agreed* tx set — which may
        differ from our open ledger's — then re-apply to the new open
        ledger anything we had locally that didn't make the consensus set
        (reference: reapply of local/disputed txns :1050-1127)."""
        with self._lock:
            t0 = time.perf_counter()
            prev = self.closed_ledger()
            open_ledger = self.current_ledger()

            txset = CanonicalTXSet(prev.hash())
            for tx in txs:
                txset.insert(tx)

            new_lcl = prev.open_successor()
            spec = (
                getattr(open_ledger, "_spec_state", None)
                if self.delta_replay else None
            )
            self._drain_spec(spec)
            results = self._apply_transactions(new_lcl, txset, spec=spec)
            t_apply = time.perf_counter()

            new_lcl.close(close_time, close_resolution, correct_close_time)
            new_lcl.accepted = True
            for tx in txset.values():
                new_lcl.parsed_txs[tx.txid()] = tx
            self._seal(new_lcl, results)
            t_seal = time.perf_counter()
            self._push_closed(new_lcl)

            # re-apply: our open-ledger txns that missed consensus first
            # (they are the lower sequences), then held/queued;
            # SF_SIGGOOD verdicts from submit time carry over so the
            # re-apply never host-re-verifies
            consensus_ids = {tx.txid() for tx in txs}
            leftovers = [
                self._parse_with_verdict(open_ledger, txid, blob)
                for txid, blob, _meta in open_ledger.tx_entries()
                if txid not in consensus_ids
            ]
            self._open_next(new_lcl, (t_apply - t0) * 1000.0,
                            leftovers=leftovers)
            self._note_close_stages(t0, t_apply, t_seal, new_lcl.seq)
            return new_lcl, results

    def _open_next(self, new_lcl: Ledger, apply_ms: float,
                   leftovers: list = ()) -> None:
        """Open the successor ledger and replenish it: consensus
        leftovers first, then the held pile / admission queue. With the
        TxQ enabled this is the promotion site — held terPRE_SEQ txs are
        absorbed into the fee-ordered queue and the best-paying eligible
        queued txs fill the new open ledger up to the soft cap (the
        [txq] enabled=0 kill-switch keeps the legacy held re-apply path
        byte-for-byte). Caller holds the lock."""
        self.current = new_lcl.open_successor()
        for tx in leftovers:
            ter, _applied = self._open_apply(
                tx, TxParams.OPEN_LEDGER | TxParams.RETRY
            )
            if ter == TER.terPRE_SEQ:
                self._hold_or_queue(tx)
        txq = self.txq
        if txq is not None and txq.enabled:
            # fold any held entries (validator/networked submit path
            # still feeds the pile directly) into the queue, then
            # promote; capacity model feeds from this close's apply pass
            for tx, expire in self._drain_held():
                txq.absorb_held(tx, self, expire)
            txq.after_close(self, new_lcl, apply_ms)
        else:
            for tx, expire in self._drain_held():
                ter, _applied = self._open_apply(
                    tx, TxParams.OPEN_LEDGER | TxParams.RETRY
                )
                if ter == TER.terPRE_SEQ:
                    self._hold(tx, expire)

    def _drain_spec(self, spec) -> None:
        """Seal the open window's parallel-speculation session before
        the close consumes its records: every dispatched task commits
        (in-flight work finishes through the pool; a wedged pool's
        remainder is executed serially in index order on this thread —
        the close-side fallback batch also drains through the executor).
        No-op on the serial path. Caller holds the chain lock; the
        commit machinery never takes it, so waiting here cannot
        deadlock."""
        ex = self.spec_executor
        session = getattr(spec, "_exec_session", None) if spec else None
        if ex is None or session is None:
            return
        t0 = time.perf_counter()
        ex.end_window(session)
        spec._exec_session = None
        self.tracer.complete("spec.drain", "close", t0,
                             time.perf_counter(),
                             dispatched=len(session.tasks))

    def _hold_or_queue(self, tx: SerializedTransaction) -> None:
        """terPRE_SEQ disposition: the fee-ordered queue when the TxQ is
        enabled, the (bounded) held pile otherwise."""
        if self.txq is not None and self.txq.enabled:
            self.txq.absorb_held(tx, self)
        else:
            self.add_held_transaction(tx)

    def switch_lcl(self, ledger: Ledger) -> None:
        """Adopt a different (acquired) last-closed ledger — the network
        moved on without us (reference: switchLastClosedLedger,
        NetworkOPs.cpp:930). Our open-ledger txns are NOT carried over;
        anything still valid will be re-relayed by peers."""
        with self._lock:
            ledger.accepted = True
            self._push_closed(ledger)
            self.current = ledger.open_successor()
            self._reindex_chain(ledger)

    def _reindex_chain(self, ledger: Ledger) -> None:
        """Repoint the seq->hash index at the adopted chain's ancestry.
        Closes we made ourselves before the switch are ORPHANS: leaving
        them indexed would make get_ledger_by_seq (and the `ledger` RPC)
        serve a ledger the network never validated at that index — the
        mismatch the reference's LedgerHistory::handleMismatch repairs.
        Repoints every resolvable ancestor; index entries between the
        last VALIDATED seq and the deepest confirmed ancestor that
        cannot be confirmed are DROPPED — after a switch they are
        orphan-branch closes, and serving nothing (the caller falls
        back to stored history, whose own divergence is LedgerCleaner
        repair territory) beats serving a ledger the network never
        validated. The tip itself was just indexed by _push_closed; the
        walk starts at its parent. Ancestry resolves from the in-memory
        cache or the LIGHT header fetch (seq + parent only) — never a
        full two-tree Ledger.load under the master lock — and stops at
        the validated floor, which no switch may rewrite."""
        floor = self.validated.seq if self.validated is not None else 0
        resolve = self._resolve_header
        cur_hash = ledger.parent_hash
        confirmed_down_to = ledger.seq
        while True:
            info = resolve(cur_hash)
            if info is None:
                break
            seq, parent_hash = info
            if seq <= floor:
                break  # never rewrite the validated chain's entries
            if self.ledger_history.get(seq) == cur_hash:
                confirmed_down_to = seq
                break
            self.ledger_history[seq] = cur_hash
            confirmed_down_to = seq
            cur_hash = parent_hash
        # one pass: (a) unconfirmable entries between the floor and the
        # deepest confirmed ancestor are orphan-branch closes; (b)
        # entries ABOVE the adopted tip are our own solo closes on an
        # abandoned fork (backward adoption repairs a runaway node) —
        # the network validated neither
        for seq in [
            s for s in self.ledger_history
            if floor < s < confirmed_down_to or s > ledger.seq
        ]:
            del self.ledger_history[seq]
        while len(self.ledger_history) > 8192:
            del self.ledger_history[min(self.ledger_history)]

    def _resolve_header(self, h: bytes) -> Optional[tuple[int, bytes]]:
        """(seq, parent_hash) for a ledger hash, from the in-memory
        cache or the LIGHT header fetch — never a full two-tree load
        under the master lock."""
        led = self.ledgers_by_hash.get(h)
        if led is not None:
            return led.seq, led.parent_hash
        if self.header_fetch is not None:
            return self.header_fetch(h)
        return None

    def set_validated(self, ledger: Ledger) -> None:
        """A quorum of trusted validations arrived for this ledger
        (reference: LedgerMaster::checkAccept tail, :705-750)."""
        with self._lock:
            if self.validated is not None and ledger.seq <= self.validated.seq:
                return
            prev_floor = (
                self.validated.seq if self.validated is not None else 0
            )
            self.validated = ledger
            # a quorum-validated ledger is the strongest possible signal
            # for its index slot: repair any orphan entry left by a fork
            # healed without an LCL switch (LedgerHistory mismatch role)
            self.ledger_history[ledger.seq] = ledger.hash()
            self.ledgers_by_hash.put(ledger.hash(), ledger)
            # and for every slot it SKIPPED: when validation jumps a
            # seq range (contested rounds, a revived node), the new
            # tip's ancestry is authoritative for the gap — without
            # this, a node that closed an orphan inside the gap served
            # that orphan from its history forever (scenario-fuzzer
            # find: honest histories permanently disagreed at a seq
            # below the validated floor)
            # bounded: never walk (or grow the index) past the 8192
            # history bound — a cold node whose first validation lands
            # at a high seq must not do seq-many header reads under
            # the master lock
            prev_floor = max(prev_floor, ledger.seq - 256)
            cur_hash = ledger.parent_hash
            seq = ledger.seq - 1
            while seq > prev_floor:
                self.ledger_history[seq] = cur_hash
                info = self._resolve_header(cur_hash)
                if info is None:
                    # deeper ancestry unresolvable from memory/headers:
                    # any remaining gap entries are unconfirmable —
                    # probably this node's own orphan-branch closes from
                    # before the jump. Same policy as the switch_lcl
                    # repair: serving NOTHING beats serving a hash the
                    # network never validated (re-resolvable later via
                    # stored history / LedgerCleaner).
                    for s in range(prev_floor + 1, seq):
                        self.ledger_history.pop(s, None)
                    break
                _seq, cur_hash = info
                seq -= 1
            while len(self.ledger_history) > 8192:
                del self.ledger_history[min(self.ledger_history)]
        if self.on_validated:
            self.on_validated(ledger)

    def check_accept(self, ledger_hash: bytes, trusted_count: int) -> bool:
        """Quorum test for a closed ledger we know about (reference:
        checkAccept) — promotes it to validated when `trusted_count`
        meets `min_validations`."""
        if trusted_count < max(self.min_validations, 1):
            return False
        ledger = self.get_ledger_by_hash(ledger_hash)
        if ledger is None:
            return False
        self.set_validated(ledger)
        return True

    def _apply_transactions(
        self, ledger: Ledger, txset: CanonicalTXSet, spec=None
    ) -> dict[bytes, TER]:
        """reference: LedgerConsensus::applyTransactions — passes over the
        canonical set, retrying ter* failures (which may succeed once an
        earlier tx lands), claiming fees on tec*.

        With a SpecState from the open pass, each tx first consults the
        delta-replay context: a record whose read set validates against
        the close's writer map is spliced (recorded delta + meta, no
        transactor run); everything else runs the full serial apply and
        poisons its written keys (engine/deltareplay.py)."""
        results: dict[bytes, TER] = {}
        engine = TransactionEngine(ledger)
        tracer = self.tracer
        replay = None
        if spec is not None and self.delta_replay:
            from ..engine.deltareplay import CloseReplay

            replay = CloseReplay(spec, ledger, tracer=tracer)

        def apply_one(key_tx, final: bool):
            tx = key_tx[1]
            if replay is not None:
                hit = replay.try_splice(engine, tx, final)
                if hit is not None:
                    return hit
                # the serial transactor reads the real trees: queued
                # spliced writes must land first
                replay.flush_pending()
            ter, did_apply = engine.apply_transaction(
                tx, TxParams.NONE if final else TxParams.RETRY
            )
            if replay is not None:
                replay.note_fallback(tx, engine, did_apply)
            elif tracer.enabled and tracer.sampled(tx.txid()):
                # serial close path (delta replay off / no spec): the
                # per-tx close mark still lands in the causal tree
                tracer.instant("close.tx", "close", txid=tx.txid(),
                               mode="serial", ledger_seq=ledger.seq,
                               ter=int(ter))
            return ter, did_apply

        remaining = txset.items_sorted()
        for pass_no in range(LEDGER_TOTAL_PASSES):
            final_pass = pass_no == LEDGER_TOTAL_PASSES - 1
            retry: list = []
            changes = 0
            for key, tx in remaining:
                ter, did_apply = apply_one((key, tx), final_pass)
                results[tx.txid()] = ter
                if did_apply or ter == TER.tesSUCCESS:
                    changes += 1
                elif -99 <= int(ter) < 0 and not final_pass:  # ter* retry band
                    retry.append((key, tx))
                elif 100 <= int(ter) < 200 and not did_apply and not final_pass:
                    retry.append((key, tx))  # tec w/o fee claim under RETRY
            remaining = retry
            if not remaining or changes == 0:
                # no progress → another pass can't help (final pass already
                # recorded non-retry results)
                if remaining and not final_pass:
                    for key, tx in remaining:
                        ter, _ = apply_one((key, tx), True)
                        results[tx.txid()] = ter
                break
        if replay is not None:
            replay.flush_pending()
            if self.incremental_seal:
                # adopt the pre-hashed building root where it matches the
                # close's final write set — the seal then hashes only the
                # residual (full seal stays the automatic fallback)
                replay.maybe_adopt_prehashed()
            self._note_delta_stats(replay)
        return results

    # -- delta-replay / close-stage observability -------------------------

    def _note_delta_stats(self, replay) -> None:
        c = replay.counts()
        if self.txq is not None and self.txq.enabled:
            # queue-aware speculation honesty: which of the txs the
            # queue promoted into this window spliced vs fell back
            self.txq.note_close_classes(replay.classes())
        # one atomic multi-key bump: concurrent readers (RPC threads,
        # the metrics collector) never see a torn closes/spliced pair
        self.delta_stats.add_many(
            closes=1, spliced=c["spliced"], fallback=c["fallback"],
            invalidated=c["invalidated"],
        )
        with self._drain_cv:
            self.tree_stats["bulk_merges"] += c.get("bulk_merges", 0)
            self.tree_stats["bulk_merged_keys"] += c.get(
                "bulk_merged_keys", 0
            )
            adopt = c.get("seal_adopt")
            if adopt == "adopted":
                self.tree_stats["seal_adopted"] += 1
                self.tree_stats["seal_residual_keys"] += c.get(
                    "seal_residual", 0
                )
            elif adopt in ("rejected", "error"):
                self.tree_stats["seal_rejected"] += 1
        self.last_close.update(c)

    def _note_close_stages(self, t0: float, t_apply: float,
                           t_seal: float, seq: int) -> None:
        now = time.perf_counter()
        stages = {
            "apply_ms": round((t_apply - t0) * 1000.0, 3),
            "seal_ms": round((t_seal - t_apply) * 1000.0, 3),
            "total_ms": round((now - t0) * 1000.0, 3),
        }
        self.close_stage_hist["apply"].record(stages["apply_ms"])
        self.close_stage_hist["seal"].record(stages["seal_ms"])
        self.close_stage_hist["total"].record(stages["total_ms"])
        self.last_close.update(stages)
        tr = self.tracer
        tr.complete("close.apply", "close", t0, t_apply, seq=seq)
        tr.complete("close.seal", "close", t_apply, t_seal, seq=seq)
        tr.complete("close.total", "close", t0, now, seq=seq)

    def delta_replay_json(self) -> dict:
        """spliced/fallback/invalidation counters + close-stage latency
        percentiles, for server_state / get_counts. Snapshots under the
        chain lock: RPC worker threads call this while the close thread
        records stages / merges last_close."""
        with self._lock:
            out = {
                "enabled": self.delta_replay,
                **self.delta_stats.snapshot(),
                "last_close": dict(self.last_close),
            }
            if self.close_stage_hist["total"].count:
                for stage, hist in self.close_stage_hist.items():
                    out[f"{stage}_p50_ms"] = hist.quantile(0.5)
                    out[f"{stage}_p90_ms"] = hist.quantile(0.9)
        if self.spec_executor is not None:
            out["spec"] = self.spec_executor.get_json()
        return out
