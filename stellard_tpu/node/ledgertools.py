"""Offline ledger tooling: dump, transaction streams, and replay.

Reference: src/ripple_app/main/LedgerDump.cpp — `--dump_ledger` (:68),
`--dump_transactions` (:86), `--load_transactions` (:267) — plus the
`--ledger N --replay` path (Main.cpp:325-332): load a stored ledger and
re-close it from its parent, verifying the rebuilt hash bit-for-bit.

Replay is BASELINE config #5's harness: it re-runs the full pipeline —
canonical apply, metadata, level-batched tree re-hash — against known
good output, and times it.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Iterator, Optional, TextIO

from ..nodestore.core import Database
from ..protocol.sttx import SerializedTransaction
from ..protocol.stobject import STObject
from ..protocol.ter import TER
from ..state.ledger import Ledger
from .ledgermaster import CanonicalTXSet, LedgerMaster

__all__ = [
    "dump_ledger",
    "dump_transactions",
    "load_transactions",
    "replay_ledger",
    "replay_range",
]


def dump_ledger(ledger: Ledger) -> dict:
    """Full JSON image of one closed ledger (reference: dumpLedger,
    LedgerDump.cpp:68 — header, state entries, transactions)."""
    out = {
        "ledger_index": ledger.seq,
        "ledger_hash": ledger.hash().hex().upper(),
        "parent_hash": ledger.parent_hash.hex().upper(),
        "close_time": ledger.close_time,
        "close_time_resolution": ledger.close_resolution,
        "close_flags": ledger.close_flags,
        "total_coins": str(ledger.tot_coins),
        "fee_pool": str(ledger.fee_pool),
        "inflation_seq": ledger.inflation_seq,
        "account_hash": ledger.state_map.get_hash().hex().upper(),
        "transaction_hash": ledger.tx_map.get_hash().hex().upper(),
        "accountState": [],
        "transactions": [],
    }
    for item in ledger.state_map.items():
        sle = STObject.from_bytes(item.data)
        j = sle.to_json()
        j["index"] = item.tag.hex().upper()
        out["accountState"].append(j)
    for txid, blob, meta in ledger.tx_entries():
        tx = SerializedTransaction.from_bytes(blob)
        j = tx.obj.to_json()
        j["hash"] = txid.hex().upper()
        out["transactions"].append(j)
    return out


def dump_transactions(
    ledgers: Iterator[Ledger], fh: TextIO
) -> int:
    """Stream every transaction of a ledger range as JSON lines
    (reference: dumpTransactions, LedgerDump.cpp:86). Format per line:
    {"ledger": seq, "close_time": t, "blob": hex}."""
    n = 0
    for ledger in ledgers:
        for txid, blob, _meta in ledger.tx_entries():
            fh.write(
                json.dumps(
                    {
                        "ledger": ledger.seq,
                        "close_time": ledger.close_time,
                        "hash": txid.hex(),
                        "blob": blob.hex(),
                    }
                )
                + "\n"
            )
            n += 1
    return n


def load_transactions(
    fh: TextIO,
    lm: LedgerMaster,
    close_every: Optional[int] = None,
) -> tuple[int, int]:
    """Re-drive dumped transactions through a fresh chain (reference:
    loadTransactions, LedgerDump.cpp:267 — the bulk-import harness).
    Closes the open ledger whenever the source ledger seq changes (or
    every `close_every` txns). Returns (applied, failed)."""
    from ..engine.engine import TxParams

    applied = failed = 0
    last_src_ledger: Optional[int] = None
    last_close_time = 0
    pending = 0
    for line in fh:
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        if last_src_ledger is not None and (
            rec["ledger"] != last_src_ledger
            or (close_every and pending >= close_every)
        ):
            # close with the batch's OWN close time (the previous
            # record's), not the next ledger's — time-dependent txns must
            # see the same clock they saw in the source chain
            lm.close_and_advance(last_close_time, 30)
            pending = 0
        last_src_ledger = rec["ledger"]
        last_close_time = rec["close_time"]
        tx = SerializedTransaction.from_bytes(bytes.fromhex(rec["blob"]))
        ter, ok = lm.do_transaction(tx, TxParams.OPEN_LEDGER | TxParams.RETRY)
        if ok or int(ter) == 0:
            applied += 1
        else:
            failed += 1
        pending += 1
    if pending:
        lm.close_and_advance(last_close_time, 30)
    return applied, failed


def _reverify_memoized(txs: list, verify_many: Callable) -> None:
    """Re-verify a tx list's signatures in ONE batched call and memoize
    the verdicts (the HashRouter SF_SIGGOOD seam) — the single shape of
    the catch-up trust model, shared by per-ledger replay and bulk
    replay_range."""
    if not txs:
        return
    from ..crypto.backend import VerifyRequest

    flags = verify_many([
        VerifyRequest(tx.signing_pub_key, tx.signing_hash(), tx.signature)
        for tx in txs
    ])
    for tx, good in zip(txs, flags):
        tx.set_sig_verdict(bool(good))


def replay_ledger(
    db: Database,
    ledger_hash: bytes,
    hash_batch: Optional[Callable] = None,
    verify_many: Optional[Callable] = None,
    _txs: Optional[list] = None,
    _target: Optional[Ledger] = None,
) -> dict:
    """Re-close a stored ledger from its parent and verify the result
    hashes identically (reference: --ledger N --replay, Main.cpp:325-332).

    Loads ledger L and parent P from the NodeStore, re-applies L's tx
    set to P in canonical order through the full engine, re-hashes both
    trees through the (device) BatchHasher, and compares against L's
    recorded hashes. Returns timing/throughput stats.

    With `verify_many` (a VerifyPlane-style batched verifier), every tx
    signature in the ledger is re-verified in ONE batch up front and the
    verdicts memoized into the txs — the HashRouter SF_SIGGOOD seam — so
    the per-tx engine path skips its inline host verify. This is the
    catch-up trust model: replayed history is re-verified, batched."""
    kw = {"hash_batch": hash_batch} if hash_batch else {}
    target = _target if _target is not None else Ledger.load(
        db, ledger_hash, **kw
    )
    parent = Ledger.load(db, target.parent_hash, **kw)

    txs = _txs if _txs is not None else [
        SerializedTransaction.from_bytes(blob)
        for _txid, blob, _meta in target.tx_entries()
    ]
    t0 = time.perf_counter()
    if verify_many is not None:
        _reverify_memoized(txs, verify_many)
    replay = parent.open_successor()
    txset = CanonicalTXSet(parent.hash())
    for tx in txs:
        txset.insert(tx)
    lm = LedgerMaster(**kw)
    results = lm._apply_transactions(replay, txset)
    replay.close(
        target.close_time,
        target.close_resolution,
        correct_close_time=(target.close_flags & 1) == 0,
    )
    replay.close_flags = target.close_flags
    replay_hash = replay.hash()
    elapsed = time.perf_counter() - t0

    ok = replay_hash == ledger_hash
    return {
        "ok": ok,
        "ledger_seq": target.seq,
        "tx_count": len(txs),
        "elapsed_s": elapsed,
        "tx_per_s": len(txs) / elapsed if elapsed > 0 else 0.0,
        "expected_hash": ledger_hash.hex(),
        "replayed_hash": replay_hash.hex(),
        "state_hash_ok": replay.state_map.get_hash()
        == target.state_map.get_hash(),
        "tx_hash_ok": replay.tx_map.get_hash() == target.tx_map.get_hash(),
        "results": {k.hex(): int(v) for k, v in results.items()},
    }


def replay_range(
    db: Database,
    ledger_hashes: list[bytes],
    hash_batch: Optional[Callable] = None,
    verify_many: Optional[Callable] = None,
) -> dict:
    """Bulk catch-up over a chain of stored ledgers.

    The reference re-verifies acquired history per ledger because its
    verify is a per-call host library (LedgerMaster/LedgerCleaner checks,
    libsodium); on a latency-flat batch device the TPU-native formulation
    verifies EVERY transaction signature across the whole range in ONE
    kernel invocation up front, then re-applies ledger by ledger with the
    verdicts memoized (the SF_SIGGOOD seam) — the bigger the catch-up
    span, the further the batch rides up the device's throughput curve.
    Verdict semantics are identical to per-ledger replay: a bad historic
    signature still fails its own ledger's hash check, no other's."""
    kw = {"hash_batch": hash_batch} if hash_batch else {}
    t0 = time.perf_counter()
    targets = [Ledger.load(db, h, **kw) for h in ledger_hashes]
    per_ledger: list[list[SerializedTransaction]] = [
        [
            SerializedTransaction.from_bytes(blob)
            for _txid, blob, _meta in target.tx_entries()
        ]
        for target in targets
    ]
    if verify_many is not None:
        _reverify_memoized(
            [tx for txs in per_ledger for tx in txs], verify_many
        )
    stats = [
        replay_ledger(db, h, hash_batch=hash_batch, _txs=txs,
                      _target=target)
        for h, txs, target in zip(ledger_hashes, per_ledger, targets)
    ]
    elapsed = time.perf_counter() - t0
    total = sum(s["tx_count"] for s in stats)
    return {
        "ok": all(s["ok"] for s in stats),
        "ledger_count": len(stats),
        "tx_count": total,
        "elapsed_s": elapsed,
        "tx_per_s": total / elapsed if elapsed > 0 else 0.0,
        "ledgers": stats,
    }
