"""Load-scaled fees + the load/deadlock watchdog.

Role parity with the reference's three-piece load plane:
- LoadFeeTrack (/root/reference/src/ripple_core/functional/LoadFeeTrack.h:51,
  LoadFeeTrackImp.cpp): a fee multiplier in 1/256 units that rises while
  the node is overloaded and decays back to normal, applied to the
  open-ledger required fee (telINSUF_FEE_P when a tx pays less);
- LoadManager (/root/reference/src/ripple_app/main/LoadManager.cpp:81-223):
  a watchdog thread that samples the job queue each second, raising or
  lowering the local fee, plus the deadlock canary — if the heartbeat
  fails to reset it for ``deadlock_timeout`` seconds the node is wedged
  and ``on_deadlock`` fires (the reference aborts after 500s);
- the peer-transaction backlog shed (reference PeerImp.cpp:64-66): relay
  transaction intake is dropped outright while more than
  ``TX_BACKLOG_SHED`` jtTRANSACTION jobs are queued.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

__all__ = ["LoadFeeTrack", "LoadManager", "TX_BACKLOG_SHED"]

NORMAL_FEE = 256  # lftNormalFee: multiplier denominator ("no escalation")
MAX_FEE = 256 * 1_000_000  # safety ceiling on escalation
TX_BACKLOG_SHED = 100  # reference: drop peer txs at >100 queued jobs


class LoadFeeTrack:
    """Local + remote load-fee multipliers, 1/256 units.

    raise/lower follow the reference's quarter-step dynamics: each raise
    adds ~25%, each lower removes ~25% of the distance toward normal, so
    sustained overload escalates geometrically and recovery is smooth.
    """

    REMOTE_TTL = 30.0  # a cluster report is stale after this many seconds

    def __init__(self):
        self._lock = threading.Lock()
        self._local = NORMAL_FEE
        # admission-queue component ([txq]): the escalated open-ledger
        # requirement fed back by TxQ.after_close — folded into
        # load_factor so server_info / the `server` stream / fee RPC
        # all see the admission price, but EXCLUDED from network_floor
        # (it is local open-ledger state other nodes do not share)
        self._queue = NORMAL_FEE
        # overlay abuse-pressure component: the resource plane's
        # aggregate peer pressure mapped onto the fee scale
        # (set_network_pressure). Included in network_floor — it is
        # genuine local load, exactly like the job-queue component —
        # so relay gating and payFee both see it
        self._overlay = NORMAL_FEE
        # source -> (fee, report_time, expiry): per-reporter so one
        # healthy cluster member cannot overwrite another's elevated
        # report (reference keeps per-node ClusterNodeStatus entries,
        # each carrying the ORIGINAL reportTime so receivers keep only
        # the newest report and stale relays age out)
        self._remote: dict[bytes, tuple[int, int, float]] = {}
        self.raise_count = 0
        # change hooks (the `server` stream publishes serverStatus on
        # load-factor movement — reference: NetworkOPs::pubServer)
        self.on_change: list = []

    def _fire_change(self) -> None:
        for cb in list(self.on_change):
            try:
                cb()
            except Exception:  # noqa: BLE001 — observers must not break fee tracking
                pass

    def raise_local_fee(self) -> None:
        with self._lock:
            before = self._local
            self._local = min(MAX_FEE, self._local + max(1, self._local // 4))
            self.raise_count += 1
            changed = self._local != before
        if changed:
            self._fire_change()

    def lower_local_fee(self) -> None:
        changed = False
        with self._lock:
            if self._local > NORMAL_FEE:
                before = self._local
                self._local = max(NORMAL_FEE, self._local - max(1, self._local // 4))
                changed = self._local != before
        if changed:
            self._fire_change()

    def set_remote_fee(
        self, fee: int, source: bytes = b"", report_time: int = 0
    ) -> None:
        """From cluster/peer load reports (sfLoadFee in validations),
        keyed by reporter. Reports expire: a peer that stops reporting
        (or whose load subsides) must not ratchet our fee up forever.

        A report that is not NEWER (by the reporter's own report_time)
        than the stored one is dropped, so relayed copies of an entry we
        already hold can neither refresh its TTL nor overwrite a fresher
        direct report — a crashed member's last report ages out
        cluster-wide after REMOTE_TTL even while members keep relaying
        it."""
        with self._lock:
            prev = self._remote.get(source)
            # drop unless strictly newer; a report with NO timing info
            # (report_time 0, e.g. a malformed/legacy wire entry) may
            # never displace or refresh a timestamped one, but two
            # untimestamped direct reports keep the old replace behavior
            if (
                prev is not None
                and max(prev[1], report_time) > 0
                and prev[1] >= report_time
            ):
                return
            self._remote[source] = (
                max(NORMAL_FEE, min(MAX_FEE, int(fee))),
                int(report_time),
                time.monotonic() + self.REMOTE_TTL,
            )

    @property
    def local_fee(self) -> int:
        """Our OWN load fee — what cluster reports must carry (sending
        the max(local, remote) would echo a peer's fee back and ratchet
        the whole cluster permanently)."""
        with self._lock:
            return self._local

    def remote_reports(self) -> list[tuple[bytes, int, int]]:
        """Unexpired (source, fee, report_time) cluster reports — relayed
        onward in TMCluster so every member learns every member's load
        (reference: each ClusterNodeStatus entry carries its ORIGINAL
        reporter AND reportTime, so relaying cannot ratchet: receivers
        key by reporter and keep only the newest report)."""
        now = time.monotonic()
        with self._lock:
            return [
                (src, fee, rtime)
                for src, (fee, rtime, expiry) in self._remote.items()
                if expiry > now and src
            ]

    def _live_remote(self) -> int:
        now = time.monotonic()
        best = NORMAL_FEE
        for source in list(self._remote):
            fee, _rtime, expiry = self._remote[source]
            if now >= expiry:
                del self._remote[source]
            else:
                best = max(best, fee)
        return best

    def set_queue_fee(self, fee: int) -> None:
        """Queue-pressure feedback from the admission plane (TxQ): the
        current escalated open-ledger fee level, 1/256 units."""
        fee = max(NORMAL_FEE, min(MAX_FEE, int(fee)))
        with self._lock:
            changed = fee != self._queue
            self._queue = fee
        if changed:
            self._fire_change()

    @property
    def queue_fee(self) -> int:
        with self._lock:
            return self._queue

    def set_network_pressure(self, fee: int) -> None:
        """Abuse-pressure feedback from the overlay's resource plane:
        the aggregate peer charge pressure expressed on the 1/256 fee
        scale (NORMAL_FEE = no abuse). Rises while the peer set as a
        whole is paying charges, decays back with the balances."""
        fee = max(NORMAL_FEE, min(MAX_FEE, int(fee)))
        with self._lock:
            changed = fee != self._overlay
            self._overlay = fee
        if changed:
            self._fire_change()

    @property
    def overlay_fee(self) -> int:
        with self._lock:
            return self._overlay

    @property
    def network_floor(self) -> int:
        """The fee floor peers would apply (local + remote + overlay
        abuse pressure — never our queue escalation): the relay gate
        for queued txs."""
        with self._lock:
            return max(self._local, self._live_remote(), self._overlay)

    @property
    def load_factor(self) -> int:
        with self._lock:
            return max(
                self._local, self._live_remote(), self._queue, self._overlay
            )

    @property
    def is_loaded(self) -> bool:
        return self.load_factor > NORMAL_FEE

    def get_json(self) -> dict:
        with self._lock:
            remote = self._live_remote()
            return {
                "load_factor": max(
                    self._local, remote, self._queue, self._overlay
                ),
                "load_base": NORMAL_FEE,
                "local_fee": self._local,
                "remote_fee": remote,
                "queue_fee": self._queue,
                "overlay_fee": self._overlay,
            }


class LoadManager:
    """Watchdog thread: job-queue load → fee escalation; deadlock canary.

    The heartbeat (NetworkOPs timer / Node.run loop) must call
    ``reset_deadlock_detector()`` regularly; if it stops for
    ``deadlock_timeout`` seconds, ``on_deadlock`` fires once (reference
    LoadManager.cpp:81-204 aborts the process; embedders decide here).
    """

    def __init__(
        self,
        job_queue,
        fee_track: LoadFeeTrack,
        clock: Optional[Callable[[], float]] = None,
        interval: float = 1.0,
        deadlock_timeout: float = 500.0,
        on_deadlock: Optional[Callable[[], None]] = None,
    ):
        self.jq = job_queue
        self.fee_track = fee_track
        self.clock = clock or time.monotonic
        self.interval = interval
        self.deadlock_timeout = deadlock_timeout
        self.on_deadlock = on_deadlock
        self._armed = False
        self._canary = self.clock()
        self._deadlock_fired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- deadlock canary --------------------------------------------------

    def reset_deadlock_detector(self) -> None:
        """Called from the heartbeat (reference: resetDeadlockDetector)."""
        self._canary = self.clock()

    def arm(self) -> None:
        """Start watching for deadlock (reference: activateDeadlockDetector,
        armed only once the application is fully up)."""
        self._canary = self.clock()
        self._armed = True

    # -- periodic work ----------------------------------------------------

    def tick(self) -> None:
        """One watchdog pass — called by the background thread, or directly
        by tests with a fake clock."""
        now = self.clock()
        if (
            self._armed
            and not self._deadlock_fired
            and now - self._canary > self.deadlock_timeout
        ):
            self._deadlock_fired = True
            if self.on_deadlock is not None:
                self.on_deadlock()
        if self.jq is not None and self.jq.is_overloaded():
            self.fee_track.raise_local_fee()
        else:
            self.fee_track.lower_local_fee()

    def start(self) -> "LoadManager":
        self._thread = threading.Thread(
            target=self._run, name="load-manager", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.tick()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def get_json(self) -> dict:
        return {
            "armed": self._armed,
            "deadlock_fired": self._deadlock_fired,
            "seconds_since_heartbeat": round(self.clock() - self._canary, 1),
            **self.fee_track.get_json(),
        }
