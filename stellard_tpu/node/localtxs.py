"""LocalTxs: locally-submitted transactions re-applied across ledgers.

Role parity with /root/reference/src/ripple_app/tx/LocalTxs.cpp: a
transaction a client handed to THIS node must not vanish just because
one consensus round left it out — it re-applies to every successive open
ledger until it lands in a validated ledger, permanently fails, or
expires (a bounded number of ledgers past submission, the reference's
holdLedgers role).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER

__all__ = ["LocalTxs"]

HOLD_LEDGERS = 5  # retry horizon past the submission ledger


class _LocalTx:
    __slots__ = ("tx", "submit_seq", "failed")

    def __init__(self, tx: SerializedTransaction, submit_seq: int):
        self.tx = tx
        self.submit_seq = submit_seq
        self.failed = False

    def expired(self, ledger_seq: int) -> bool:
        return ledger_seq > self.submit_seq + HOLD_LEDGERS


class LocalTxs:
    def __init__(self):
        self._lock = threading.Lock()
        self._txns: dict[bytes, _LocalTx] = {}
        self.reapplied = 0

    def push_back(self, ledger_seq: int, tx: SerializedTransaction) -> None:
        """Track a locally-submitted tx (reference push_back). A
        RE-submission of a known txid revives the entry — it must not be
        shadowed by a stale `failed` mark or an old retry horizon (a tx
        queued by the admission plane and later evicted is resubmitted
        by the client with the same txid; the old setdefault left the
        original entry in place, permanently un-retriable once failed)."""
        with self._lock:
            cur = self._txns.get(tx.txid())
            if cur is None:
                self._txns[tx.txid()] = _LocalTx(tx, ledger_seq)
            else:
                cur.failed = False
                cur.submit_seq = max(cur.submit_seq, ledger_seq)

    def rebase(self, ledger_seq: int) -> int:
        """Fresh retry horizon for every tracked tx, used at fork repair
        (LCL switch): the expiry horizon counts ledgers on the chain a
        tx could have been INCLUDED in — after adopting the network's
        chain (whose seq may be far past submit_seq + HOLD_LEDGERS), a
        client tx submitted to the losing side must get its HOLD_LEDGERS
        retries against the authoritative chain, not be silently expired
        by a seq jump it never saw. Returns entries rebased."""
        with self._lock:
            for item in self._txns.values():
                item.submit_seq = max(item.submit_seq, ledger_seq)
            return len(self._txns)

    def remove(self, txid: bytes) -> bool:
        """Stop tracking a tx (wired as TxQ.on_drop: admission-queue
        eviction / expiry / promote-drop): the queue's drop decision
        must also stop the cross-round re-apply, and the next client
        resubmission starts a fresh retry horizon."""
        with self._lock:
            return self._txns.pop(txid, None) is not None

    def __contains__(self, txid: bytes) -> bool:
        with self._lock:
            return txid in self._txns

    def __len__(self) -> int:
        with self._lock:
            return len(self._txns)

    def apply_to_open(self, ledger_master, engine_params) -> int:
        """Re-apply survivors to the current open ledger (reference
        LocalTxsImp::apply, driven after each consensus accept). Returns
        the number re-applied."""
        with self._lock:
            items = [t for t in self._txns.values() if not t.failed]
        n = 0
        for item in items:
            ter, _applied = ledger_master.do_transaction(
                item.tx, engine_params
            )
            if ter.is_tem or ter.is_tec:
                # malformed or claimed-fee failure: no future retry
                with self._lock:
                    cur = self._txns.get(item.tx.txid())
                    if cur is not None:
                        cur.failed = True
            else:
                n += 1
        self.reapplied += n
        return n

    def sweep(self, validated_ledger) -> int:
        """Drop txns that made a validated ledger or expired (reference
        sweep with mSweepLedgers). Returns the number dropped."""
        dropped = 0
        in_ledger = set()
        for txid, _blob, _meta in validated_ledger.tx_entries():
            in_ledger.add(txid)
        with self._lock:
            for txid in list(self._txns):
                item = self._txns[txid]
                if txid in in_ledger or item.expired(validated_ledger.seq):
                    del self._txns[txid]
                    dropped += 1
        return dropped

    def get_json(self) -> dict:
        with self._lock:
            return {"count": len(self._txns), "reapplied": self.reapplied}
