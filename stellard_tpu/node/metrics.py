"""Metrics plane: insight-style instruments + statsd export + history.

Role parity with the reference's beast::insight + CollectorManager
(/root/reference/src/ripple_app/main/CollectorManager.cpp:22-60,
beast insight {Counter,Gauge,Event,Meter,Hook}): subsystems register
named instruments against a collector; the `[insight]` config selects a
NullCollector (default) or a StatsDCollector that ships deltas over UDP.

Hooks are pull-gauges: a callable sampled at flush time, which is how
the JobQueue per-type gauges and the verify plane's rates export without
the hot paths touching the collector.

Beyond the reference: a Monarch-style embedded history (MetricsHistory —
bounded ring of periodic instrument snapshots, queryable in-process via
the `metrics_history` admin RPC) and a Prometheus text-exposition
renderer (text format 0.0.4, the `GET /metrics` door). Snapshots feed
the SLO health watchdog (node/health.py) through the manager's on_sample
callbacks.
"""

from __future__ import annotations

import socket
import threading
import time
from collections import deque
from typing import Callable, Optional

__all__ = [
    "Counter",
    "Gauge",
    "Meter",
    "LatencyHist",
    "AtomicCounters",
    "CollectorManager",
    "MetricsHistory",
    "NullCollector",
    "StatsDCollector",
    "prometheus_escape_help",
    "prometheus_escape_label",
    "prometheus_name",
]


class AtomicCounters:
    """A named-counter bundle under ONE lock.

    The close-info counters (spliced/fallback/invalidated) and the
    parallel-speculation counters are incremented from several threads —
    the close path, the TxQ's deferred promotion job, and the executor's
    commit thread — so per-dict `+=` on a plain dict would lose updates.
    One shared lock for the whole bundle keeps multi-key updates (e.g. a
    commit bumping committed AND retries) atomic as a group, which a
    per-counter lock could not."""

    __slots__ = ("_lock", "_vals")

    def __init__(self, *names, **initial):
        self._lock = threading.Lock()
        self._vals: dict = {name: 0 for name in names}
        self._vals.update(initial)

    def add(self, name: str, n=1) -> None:
        with self._lock:
            self._vals[name] = self._vals.get(name, 0) + n

    def add_many(self, **deltas) -> None:
        """Atomically apply several deltas (one lock hold)."""
        with self._lock:
            for name, n in deltas.items():
                self._vals[name] = self._vals.get(name, 0) + n

    def set(self, name: str, value) -> None:
        with self._lock:
            self._vals[name] = value

    def get(self, name: str):
        with self._lock:
            return self._vals.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._vals)

    def __getitem__(self, name: str):
        return self.get(name)

    def keys(self):
        """Mapping protocol (with __getitem__): ``dict(counters)`` and
        ``**counters`` both work, so an AtomicCounters can drop in where
        a plain stats dict used to live."""
        with self._lock:
            return list(self._vals)


class LatencyHist:
    """Fixed-bucket latency histogram (ms): tiny, lock-free enough for a
    single-writer stage, read-mostly for metrics. The ONE percentile
    implementation for the whole node — the close pipeline's stage
    timers, the ledger master's close stages, the verify plane's batch
    latencies, and the tracer's span-derived stage histograms all share
    it (they used to carry three divergent ad-hoc copies).

    Quantiles report the upper bound of the bucket holding the target
    rank (0 when empty); `interpolate=True` refines that to a linear
    estimate inside the holding bucket (used where the value feeds
    round-over-round comparisons — bench provenance, close stages —
    so a drifting p50 moves continuously instead of jumping a whole
    bucket). `bounds` tunes resolution per instrument; the default
    decade ladder matches the original close-pipeline buckets.
    """

    BOUNDS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 250.0, 500.0,
              1000.0, 5000.0)

    def __init__(self, bounds: Optional[tuple] = None,
                 interpolate: bool = False):
        self.bounds = tuple(bounds) if bounds is not None else self.BOUNDS
        self.interpolate = interpolate
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total_ms = 0.0
        self.max_ms = 0.0

    def record(self, ms: float) -> None:
        i = 0
        for i, b in enumerate(self.bounds):  # noqa: B007
            if ms <= b:
                break
        else:
            i = len(self.bounds)
        self.counts[i] += 1
        self.count += 1
        self.total_ms += ms
        self.max_ms = max(self.max_ms, ms)

    def quantile(self, q: float) -> float:
        """Upper bucket bound holding the q-quantile (0 when empty);
        with `interpolate`, the linear estimate inside that bucket."""
        if not self.count:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                hi = (self.bounds[i] if i < len(self.bounds)
                      else self.bounds[-1] * 2)
                if not self.interpolate or not c:
                    return hi
                lo = self.bounds[i - 1] if i > 0 else 0.0
                frac = (target - (seen - c)) / c
                return round(lo + frac * (hi - lo), 3)
        return self.bounds[-1] * 2

    def get_json(self) -> dict:
        return {
            "count": self.count,
            "mean_ms": round(self.total_ms / self.count, 3) if self.count else 0.0,
            "p50_ms": self.quantile(0.5),
            "p90_ms": self.quantile(0.9),
            "p99_ms": self.quantile(0.99),
            "max_ms": round(self.max_ms, 3),
        }


class Counter:
    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v


class Meter:
    """Events per flush interval (plus a never-reset cumulative total so
    history snapshots and Prometheus exposition stay monotone across the
    statsd flusher's drains)."""

    __slots__ = ("name", "count", "total", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self.count += n
            self.total += n

    def drain(self) -> int:
        with self._lock:
            n = self.count
            self.count = 0
            return n


class MetricsHistory:
    """Bounded ring of periodic instrument snapshots (Monarch's core
    move: keep queryable metric history INSIDE the monitored system).

    One snapshot per `interval` seconds, kept for `window` seconds —
    capacity is fixed at construction, so memory is bounded no matter
    how long the node runs. Snapshots are immutable once appended;
    reads copy the row list under the lock (copy-on-read), so a reader
    holding a result is never affected by concurrent appends."""

    def __init__(self, interval: float = 5.0, window: float = 300.0):
        self.interval = max(0.1, float(interval))
        self.window = max(self.interval, float(window))
        self.capacity = max(2, int(round(self.window / self.interval)))
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self.appended = 0  # lifetime count (evictions = appended - len)

    def append(self, snap: dict) -> None:
        with self._lock:
            self._ring.append(snap)
            self.appended += 1

    def rows(self, since: float = 0.0, limit: int = 0) -> list[dict]:
        """Chronological snapshots (copy-on-read). `since` filters by
        snapshot timestamp; `limit` keeps only the newest N."""
        with self._lock:
            out = list(self._ring)
        if since:
            out = [r for r in out if r.get("ts", 0.0) >= since]
        if limit and len(out) > limit:
            out = out[-limit:]
        return out

    def last(self) -> Optional[dict]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def get_json(self) -> dict:
        with self._lock:
            n = len(self._ring)
        return {
            "interval": self.interval,
            "window": self.window,
            "capacity": self.capacity,
            "rows": n,
            "appended": self.appended,
        }


# -- Prometheus text exposition (format 0.0.4) ------------------------------


def prometheus_name(name: str) -> str:
    """Map an insight instrument name to a legal Prometheus metric name:
    [a-zA-Z_:][a-zA-Z0-9_:]* — dots and dashes become underscores."""
    out = []
    for i, ch in enumerate(name):
        if ch.isalnum() or ch in "_:":
            out.append(ch)
        else:
            out.append("_")
        if i == 0 and ch.isdigit():
            out[0] = "_"
    return "".join(out) or "_"


def prometheus_escape_help(text: str) -> str:
    """HELP line escaping: backslash and newline only (format 0.0.4)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def prometheus_escape_label(value: str) -> str:
    """Label value escaping: backslash, newline, and double quote."""
    return (
        value.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')
    )


def _prom_num(v) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


class NullCollector:
    """Discards everything (the default when [insight] is unset)."""

    def flush(self, lines: list[str]) -> None:  # pragma: no cover - trivial
        pass

    def close(self) -> None:  # pragma: no cover - trivial
        pass


class StatsDCollector:
    """Ships statsd datagrams over UDP (reference StatsDCollector)."""

    def __init__(self, host: str, port: int, prefix: str = "stellard"):
        self.addr = (host, port)
        self.prefix = prefix
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sent = 0

    def flush(self, lines: list[str]) -> None:
        # batch into ~1400-byte datagrams (statsd multi-metric packets)
        buf = b""
        for line in lines:
            data = f"{self.prefix}.{line}\n".encode()
            if buf and len(buf) + len(data) > 1400:
                self._send(buf)
                buf = b""
            buf += data
        if buf:
            self._send(buf)

    def _send(self, buf: bytes) -> None:
        try:
            self.sock.sendto(buf, self.addr)
            self.sent += 1
        except OSError:
            pass

    def close(self) -> None:
        self.sock.close()


class CollectorManager:
    """Instrument registry + periodic flusher (CollectorManager role)."""

    def __init__(self, collector=None, flush_interval: float = 1.0):
        self.collector = collector or NullCollector()
        self.flush_interval = flush_interval
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._meters: dict[str, Meter] = {}
        self._hooks: dict[str, Callable[[], dict]] = {}
        self._hists: dict[str, LatencyHist] = {}
        self._last_counter_vals: dict[str, int] = {}
        # embedded history ([insight] history_interval/history_window):
        # None disables sampling entirely (the kill switch)
        self.history: Optional[MetricsHistory] = None
        self._last_sample = 0.0
        # observers of each history snapshot (the health watchdog seam);
        # called OFF the registry lock with the immutable snapshot dict
        self._on_sample: list[Callable[[dict], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_config(cls, insight: str) -> "CollectorManager":
        """[insight] value: '' / 'null' -> null; 'statsd:host:port[:prefix]'
        -> statsd (reference CollectorManager.cpp config parse)."""
        if insight.startswith("statsd:"):
            parts = insight.split(":")
            try:
                host, port = parts[1], int(parts[2])
            except (IndexError, ValueError):
                return cls(NullCollector())  # malformed: metrics off
            prefix = parts[3] if len(parts) > 3 else "stellard"
            return cls(StatsDCollector(host, port, prefix))
        return cls(NullCollector())

    # -- registry ----------------------------------------------------------

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter(name))

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge(name))

    def meter(self, name: str) -> Meter:
        with self._lock:
            return self._meters.setdefault(name, Meter(name))

    def hook(self, name: str, fn: Callable[[], dict]) -> None:
        """fn() -> {metric_suffix: value} sampled at flush time (the
        insight::Hook shape; how JobQueue gauges export pull-style)."""
        with self._lock:
            self._hooks[name] = fn

    def register_hist(self, name: str, hist: LatencyHist) -> None:
        """Expose a subsystem's LatencyHist through history snapshots
        and the /metrics histogram exposition (pull-style — the owner
        keeps recording into it; we only read)."""
        with self._lock:
            self._hists[name] = hist

    def on_sample(self, fn: Callable[[dict], None]) -> None:
        """Subscribe to history snapshots (the health watchdog seam)."""
        self._on_sample.append(fn)

    # -- history ------------------------------------------------------------

    def enable_history(self, interval: float, window: float) -> MetricsHistory:
        self.history = MetricsHistory(interval, window)
        return self.history

    def instruments_snapshot(self) -> dict:
        """Point-in-time view of every registered instrument: cumulative
        counter/meter values (monotone across flushes — flush drains a
        meter's interval count, never its total), gauge values, hook
        samples, and histogram quantiles."""
        with self._lock:
            counters = {c.name: c.value for c in self._counters.values()}
            for m in self._meters.values():
                counters.setdefault(m.name, m.total)
            gauges = {g.name: g.value for g in self._gauges.values()}
            hooks = list(self._hooks.items())
            hists = list(self._hists.items())
        hook_vals: dict[str, float] = {}
        for name, fn in hooks:
            try:
                for suffix, value in fn().items():
                    hook_vals[f"{name}.{suffix}"] = value
            except Exception:  # noqa: BLE001 — a hook must not kill sampling
                pass
        hist_vals: dict[str, dict] = {}
        for name, h in hists:
            hist_vals[name] = {
                "count": h.count,
                "mean_ms": round(h.total_ms / h.count, 3) if h.count else 0.0,
                "p50_ms": h.quantile(0.5),
                "p99_ms": h.quantile(0.99),
                "max_ms": round(h.max_ms, 3),
            }
        return {
            "counters": counters,
            "gauges": gauges,
            "hooks": hook_vals,
            "hists": hist_vals,
        }

    def sample_history(self, now: Optional[float] = None) -> Optional[dict]:
        """Take one history snapshot and notify on_sample observers.
        Driven by the flusher thread at history cadence; tests and the
        scenario runner call it directly with a virtual clock."""
        if self.history is None:
            return None
        snap = self.instruments_snapshot()
        snap["ts"] = time.time() if now is None else float(now)
        self.history.append(snap)
        for fn in list(self._on_sample):
            try:
                fn(snap)
            except Exception:  # noqa: BLE001 — observers never kill sampling
                pass
        return snap

    # -- flushing ----------------------------------------------------------

    def flush_once(self) -> list[str]:
        lines: list[str] = []
        with self._lock:
            gauges = list(self._gauges.values())
            meters = list(self._meters.values())
            hooks = list(self._hooks.items())
            # counter deltas (and the last-seen map they depend on) are
            # computed UNDER the registry lock: two concurrent flushes
            # racing _last_counter_vals could double-report a delta
            for c in list(self._counters.values()):
                prev = self._last_counter_vals.get(c.name, 0)
                delta = c.value - prev
                self._last_counter_vals[c.name] = c.value
                if delta:
                    lines.append(f"{c.name}:{delta}|c")
        for g in gauges:
            lines.append(f"{g.name}:{g.value:g}|g")
        for m in meters:
            n = m.drain()
            if n:
                # meters drain per-interval event counts; statsd has no
                # "|m" type (real daemons drop unknown types on the
                # floor), so they ship as counters — same delta
                # semantics, a type the server actually aggregates
                lines.append(f"{m.name}:{n}|c")
        for name, fn in hooks:
            try:
                for suffix, value in fn().items():
                    lines.append(f"{name}.{suffix}:{value:g}|g")
            except Exception:  # noqa: BLE001 — a hook must not kill the flusher
                pass
        self.collector.flush(lines)
        return lines

    def start(self) -> "CollectorManager":
        self._thread = threading.Thread(
            target=self._run, name="insight", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.flush_interval):
            self.flush_once()
            if self.history is not None:
                mono = time.monotonic()
                if mono - self._last_sample >= self.history.interval:
                    self._last_sample = mono
                    self.sample_history()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.collector.close()

    # -- Prometheus exposition ----------------------------------------------

    def prometheus_text(self, prefix: str = "stellard",
                        extra_gauges: Optional[dict] = None) -> str:
        """Text exposition format 0.0.4 (the `GET /metrics` door):
        counters/meters as `counter`, gauges and hook samples as `gauge`,
        registered LatencyHists as `histogram` with CUMULATIVE `le`
        buckets, a `+Inf` bucket, and `_count`/`_sum` series.
        `extra_gauges` lets the serving layer fold in computed values
        (health status, ledger seq) without registering instruments."""
        snap = self.instruments_snapshot()
        with self._lock:
            hists = list(self._hists.items())
        out: list[str] = []

        def emit(name: str, mtype: str, value, help_text: str = "") -> None:
            pname = prometheus_name(f"{prefix}_{name}")
            if help_text:
                out.append(f"# HELP {pname} {prometheus_escape_help(help_text)}")
            out.append(f"# TYPE {pname} {mtype}")
            out.append(f"{pname} {_prom_num(value)}")

        for name in sorted(snap["counters"]):
            emit(name, "counter", snap["counters"][name])
        for name in sorted(snap["gauges"]):
            emit(name, "gauge", snap["gauges"][name])
        for name in sorted(snap["hooks"]):
            emit(name, "gauge", snap["hooks"][name])
        for name, value in sorted((extra_gauges or {}).items()):
            emit(name, "gauge", value)
        for name, h in sorted(hists):
            pname = prometheus_name(f"{prefix}_{name}")
            out.append(f"# TYPE {pname} histogram")
            # snapshot the bucket counts once: the owner thread keeps
            # recording, and Prometheus requires cumulative monotone
            # buckets within one scrape
            counts = list(h.counts)
            cum = 0
            for i, b in enumerate(h.bounds):
                cum += counts[i]
                out.append(
                    f'{pname}_bucket{{le="{_prom_num(float(b))}"}} {cum}'
                )
            cum += counts[len(h.bounds)]
            out.append(f'{pname}_bucket{{le="+Inf"}} {cum}')
            out.append(f"{pname}_count {cum}")
            out.append(f"{pname}_sum {_prom_num(round(h.total_ms, 3))}")
        return "\n".join(out) + "\n"

    def history_json(self, since: float = 0.0, limit: int = 0) -> dict:
        """`metrics_history` admin RPC payload."""
        if self.history is None:
            return {"enabled": False, "rows": []}
        return {
            "enabled": True,
            **self.history.get_json(),
            "series": self.history.rows(since=since, limit=limit),
        }
