"""SNTP-disciplined network clock.

Role parity with /root/reference/src/ripple_net/basics/SNTPClient.cpp
(wired at Application.cpp:698-699, consumed as getNetworkTimeNC): the
node queries configured SNTP servers over UDP, keeps a smoothed offset
between the local clock and network time, and the consensus plane reads
close times through it. Close-time agreement must not depend on every
host's wall clock being right.

The client speaks standard SNTPv4 (RFC 4330) so it works against real
NTP servers; tests drive it against an in-process UDP responder.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Optional

__all__ = ["SntpClient", "NTP_EPOCH_DELTA"]

NTP_EPOCH_DELTA = 2208988800  # 1900-01-01 -> 1970-01-01
MAX_PLAUSIBLE_OFFSET = 600.0  # ignore insane replies (reference sanity)


class SntpClient:
    """Polls SNTP servers; exposes a smoothed offset and network_time()."""

    def __init__(
        self,
        servers: list[tuple[str, int]],
        poll_interval: float = 64.0,
        timeout: float = 2.0,
    ):
        self.servers = list(servers)
        self.poll_interval = poll_interval
        self.timeout = timeout
        self._offset = 0.0  # network - local, seconds
        self._have_sample = False
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.queries = 0
        self.replies = 0

    # -- wire --------------------------------------------------------------

    @staticmethod
    def _build_request() -> bytes:
        # LI=0 VN=4 Mode=3 (client); transmit timestamp = local now
        pkt = bytearray(48)
        pkt[0] = (4 << 3) | 3
        tx = time.time() + NTP_EPOCH_DELTA
        sec = int(tx)
        frac = int((tx - sec) * (1 << 32))
        struct.pack_into(">II", pkt, 40, sec, frac)
        return bytes(pkt)

    @staticmethod
    def _parse_reply(data: bytes) -> Optional[float]:
        """-> server transmit time (unix seconds) or None."""
        if len(data) < 48:
            return None
        mode = data[0] & 0x7
        if mode != 4:  # server reply
            return None
        sec, frac = struct.unpack_from(">II", data, 40)
        if sec == 0:
            return None
        return sec - NTP_EPOCH_DELTA + frac / (1 << 32)

    def query_once(self) -> bool:
        """One round against all servers; keeps the best (first) reply."""
        for host, port in self.servers:
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.settimeout(self.timeout)
            try:
                t0 = time.time()
                sock.sendto(self._build_request(), (host, port))
                self.queries += 1
                data, _addr = sock.recvfrom(512)
                t1 = time.time()
            except OSError:
                continue
            finally:
                sock.close()
            server_time = self._parse_reply(data)
            if server_time is None:
                continue
            # midpoint correction: assume symmetric path delay
            local_mid = (t0 + t1) / 2.0
            offset = server_time - local_mid
            if abs(offset) > MAX_PLAUSIBLE_OFFSET:
                continue
            with self._lock:
                self.replies += 1
                if not self._have_sample:
                    self._offset = offset
                    self._have_sample = True
                else:
                    # smooth: clock discipline without step jumps
                    self._offset += 0.25 * (offset - self._offset)
            return True
        return False

    # -- service -----------------------------------------------------------

    def start(self) -> "SntpClient":
        self._thread = threading.Thread(
            target=self._run, name="sntp", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        self.query_once()
        while not self._stop.wait(self.poll_interval):
            self.query_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- readings ----------------------------------------------------------

    @property
    def offset(self) -> float:
        with self._lock:
            return self._offset

    @property
    def synced(self) -> bool:
        with self._lock:
            return self._have_sample

    def network_unix_time(self) -> float:
        """Local clock corrected by the disciplined offset
        (reference getNetworkTimeNC semantics)."""
        return time.time() + self.offset

    def get_json(self) -> dict:
        with self._lock:
            return {
                "synced": self._have_sample,
                "offset_s": round(self._offset, 6),
                "queries": self.queries,
                "replies": self.replies,
            }
