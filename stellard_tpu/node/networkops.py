"""NetworkOPs: the application brain.

Reference: src/ripple_app/misc/NetworkOPs.cpp (2923 LoC) — operating-mode
state machine (NetworkOPs.h:76-84), transaction submission/processing
(:274-558), standalone ledger close (acceptLedger), and the pub/sub
fan-out (pubLedger / pubProposedTransaction / pubAcceptedTransaction).

TPU shape: signature checks route through the VerifyPlane (coalesced
device batches) with HashRouter SF_SIGGOOD/SF_BAD memoization, so the
apply path under the master lock never re-verifies.
"""

from __future__ import annotations

import logging
import threading
import time
from enum import IntEnum
from typing import Callable, Optional

from ..crypto.backend import VerifyRequest
from ..engine.engine import TxParams
from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from ..state.ledger import Ledger
from .hashrouter import SF_BAD, SF_RELAYED, SF_SIGGOOD, HashRouter
from .jobqueue import JobQueue, JobType

log = logging.getLogger("stellard.netops")
from .ledgermaster import LedgerMaster
from .verifyplane import VerifyPlane

__all__ = ["NetworkOPs", "OperatingMode", "TxStatus"]

# seconds between 1970-01-01 and 2000-01-01 (reference: iToSeconds /
# NetClock epoch) — ledger close times are seconds since 2000.
EPOCH_OFFSET = 946_684_800


class OperatingMode(IntEnum):
    """reference: NetworkOPs.h:76-84"""

    DISCONNECTED = 0
    CONNECTED = 1
    SYNCING = 2
    TRACKING = 3
    FULL = 4


class TxStatus(IntEnum):
    """reference: Transaction.h TransStatus"""

    NEW = 0
    INVALID = 1
    INCLUDED = 2
    CONFLICTED = 3
    COMMITTED = 4
    HELD = 5
    REMOVED = 6
    OBSOLETE = 7
    INCOMPLETE = 8


class NetworkOPs:
    def __init__(
        self,
        ledger_master: LedgerMaster,
        job_queue: JobQueue,
        verify_plane: VerifyPlane,
        hash_router: HashRouter,
        standalone: bool = True,
        fee_track=None,
        tracer=None,
        txq=None,
    ):
        from .tracer import get_tracer

        self.lm = ledger_master
        self.jq = job_queue
        self.vp = verify_plane
        self.router = hash_router
        self.tracer = tracer if tracer is not None else get_tracer()
        self.fee_track = fee_track  # loadmgr.LoadFeeTrack or None
        # admission-control plane ([txq], node/txq.py): post-verify
        # intake routes through TxQ.admit when enabled — soft open-
        # ledger cap, escalating fee, fee-priority queue (terQUEUED);
        # enabled=0 (or None) is the legacy direct-apply path
        self.txq = txq
        self.standalone = standalone
        self.mode = OperatingMode.FULL if standalone else OperatingMode.DISCONNECTED
        self.master_lock = threading.RLock()  # reference: getApp().getMasterLock()
        self.net_time_offset = 0
        # networked-mode seams (wired by Node when an overlay exists):
        # relay an applied client tx to peers (excluding the suppression
        # peer-id set it arrived from) / track it for re-apply across
        # rounds (reference: processTransaction relay step + LocalTxs
        # client-submit tracking)
        # read plane (rpc/readplane.py, wired by Node): the serving
        # side's immutable validated-snapshot pointer — publish hands it
        # each closed ledger so read RPCs resolve "validated" without
        # ever taking the chain lock
        self.read_plane = None
        self.relay_tx: Optional[
            Callable[[SerializedTransaction, set[int]], None]
        ] = None
        self.local_push: Optional[Callable[[int, SerializedTransaction], None]] = None
        # pub/sub sinks (wired by InfoSub manager; reference NetworkOPsImp
        # mSubLedger / mSubTransactions / ...)
        self.on_ledger_closed: list[Callable[[Ledger, dict], None]] = []
        self.on_proposed_tx: list[Callable[[SerializedTransaction, TER], None]] = []
        # bounded status map (insertion-ordered; oldest evicted) — the
        # HashRouter equivalent of this sweeps on a hold timer
        self.on_tx_result: dict[bytes, TxStatus] = {}
        self.max_tx_results = 100_000
        self.stats = {"processed": 0, "bad_sig": 0, "held": 0}
        # ordered intake (see _enqueue_intake)
        self._intake: list = []
        self._intake_lock = threading.Lock()
        self._intake_scheduled = False

    # -- time (reference: getNetworkTimeNC via SNTP offset) ---------------

    def network_time(self) -> int:
        return int(time.time()) - EPOCH_OFFSET + self.net_time_offset

    # -- transaction intake ----------------------------------------------

    def submit_transaction(
        self, tx: SerializedTransaction, cb: Optional[Callable] = None
    ) -> None:
        """Async submission: verify (coalesced) off the master lock, then
        process on a jtTRANSACTION job (reference:
        NetworkOPs::submitTransaction :274-321)."""
        # relay backlog shed (reference: PeerImp.cpp:64-66 — drop new
        # network transactions outright past a 100-job backlog). A caller
        # that asked for a result still gets one (telINSUF_FEE_P: transient
        # local overload, resubmittable) so local clients never hang.
        from .loadmgr import TX_BACKLOG_SHED

        # intake backlog counts toward the shed gate: batching collapses
        # the queue to at most one jtTRANSACTION job, so the job count
        # alone no longer reflects a flood (the drain queue does)
        if (self.jq.get_job_count(JobType.jtTRANSACTION)
                + len(self._intake)) > TX_BACKLOG_SHED:
            self.stats["shed"] = self.stats.get("shed", 0) + 1
            if cb:
                cb(tx, TER.telINSUF_FEE_P, False)
            return
        txid = tx.txid()
        tr = self.tracer
        # root of the transaction's causal span tree (trace id = txid):
        # every later stage — verify wait, intake process, open apply,
        # close splice/fallback, persist — links back to this span
        sub = tr.begin("submit", "submit", txid=txid)
        flags = self.router.get_flags(txid)
        if flags & SF_BAD:
            tr.end(sub, outcome="known_bad")
            if cb:
                cb(tx, TER.temINVALID, False)
            return
        if flags & SF_SIGGOOD:
            tx.set_sig_verdict(True)
            tr.end(sub, outcome="cached_sig")
            self._enqueue_intake(tx, cb, parent=sub)
            return
        # cross-thread span: begins here, ends on the verify plane's
        # flusher thread when the coalesced batch completes the future
        vtok = tr.begin("verify.wait", "verify", txid=txid, parent=sub)
        tr.end(sub, outcome="verify_queued")
        fut = self.vp.submit(
            VerifyRequest(tx.signing_pub_key, tx.signing_hash(), tx.signature)
        )

        def when_done(f):
            good = bool(f.result()) if not f.exception() else False
            tr.end(vtok, good=good)
            tx.set_sig_verdict(good)
            self.router.set_flag(txid, SF_SIGGOOD if good else SF_BAD)
            if not good:
                self.stats["bad_sig"] += 1
                if cb:
                    cb(tx, TER.temINVALID, False)
                return
            self._enqueue_intake(tx, cb, parent=vtok)

        fut.add_done_callback(when_done)

    def _enqueue_intake(self, tx, cb, parent=None) -> None:
        """Ordered intake: verified txs drain FIFO under ONE
        jtTRANSACTION job at a time. One job per tx let the worker pool
        race same-account bursts out of sequence order — a 3000-tx
        single-account flood scrambled ~80% of itself into terPRE_SEQ
        holds (and each close then re-walked the held pile). The verify
        plane completes futures in submission order, so a FIFO drain
        preserves the client's order end-to-end; it also amortizes job
        dispatch across the batch. (reference: per-tx jtTRANSACTION
        jobs work there because holds are rare on real traffic; the
        coalescing verify plane makes bursts the NORM here.)"""
        with self._intake_lock:
            self._intake.append((tx, cb, parent))
            if self._intake_scheduled:
                return
            self._intake_scheduled = True
        if not self.jq.add_job(
            JobType.jtTRANSACTION, "processTxBatch", self._drain_intake
        ):
            # queue refused (stopping): never strand the flag set with no
            # drain coming — fail the queued callers resubmittably
            with self._intake_lock:
                stranded = list(self._intake)
                self._intake.clear()
                self._intake_scheduled = False
            for s_tx, s_cb, _par in stranded:
                if s_cb:
                    s_cb(s_tx, TER.telINSUF_FEE_P, False)

    def _drain_intake(self) -> None:
        try:
            while True:
                with self._intake_lock:
                    if not self._intake:
                        return
                    batch = list(self._intake)
                    self._intake.clear()
                for tx, cb, parent in batch:
                    try:
                        self._process_cb(tx, cb, parent)
                    except Exception:  # noqa: BLE001 — one bad tx must not
                        # drop the rest of the batch (the per-tx-job design
                        # this replaces lost only the failing tx)
                        log.exception("intake: processing failed for %s",
                                      tx.txid().hex()[:16])
        finally:
            # ALWAYS release the schedule flag — an exception escaping the
            # loop (or the jobqueue killing the job) must not wedge intake
            # forever; reschedule if arrivals raced the drain's exit
            resched = False
            with self._intake_lock:
                self._intake_scheduled = False
                if self._intake:
                    self._intake_scheduled = True
                    resched = True
            if resched and not self.jq.add_job(
                JobType.jtTRANSACTION, "processTxBatch", self._drain_intake
            ):
                # queue refused (stopping): fail the stranded callers
                # resubmittably instead of hanging them (same contract
                # as _enqueue_intake's refusal path)
                with self._intake_lock:
                    stranded = list(self._intake)
                    self._intake.clear()
                    self._intake_scheduled = False
                for s_tx, s_cb, _par in stranded:
                    if s_cb:
                        s_cb(s_tx, TER.telINSUF_FEE_P, False)

    def _process_cb(self, tx, cb, parent=None):
        # the process span parents the open-apply/speculation spans
        # recorded inside do_transaction (same thread, tls stack)
        with self.tracer.span("process", "submit", txid=tx.txid(),
                              parent=parent):
            ter, applied = self.process_transaction(tx)
        if cb:
            cb(tx, ter, applied)

    def _plane_check_sign(self, tx: SerializedTransaction) -> bool:
        """Synchronous single-tx verification THROUGH the routed verify
        plane (the RPC submit path). Before this, process_transaction
        verified inline via tx.check_sign(), bypassing the plane
        entirely — a mesh-enabled node could serve a whole RPC flood
        with device_sigs frozen at 0 and no routing/latency evidence.
        The plane's cost model sends a 1-sig batch to the host arm
        (same verify_signature underneath), so the common case costs
        what check_sign did; forced-device mode and big resubmit
        sweeps ride the configured kernel."""
        ok = bool(self.vp.verify_many(
            [VerifyRequest(tx.signing_pub_key, tx.signing_hash(),
                           tx.signature)]
        )[0])
        tx.set_sig_verdict(ok)
        return ok

    def process_transaction(
        self, tx: SerializedTransaction, admin: bool = False
    ) -> tuple[TER, bool]:
        """Synchronous path (reference: NetworkOPs::processTransaction
        :444-558): router flags → checkSign (memoized / pre-batched) →
        apply to open ledger under the master lock → status bookkeeping
        → relay."""
        txid = tx.txid()
        flags = self.router.get_flags(txid)
        if flags & SF_BAD:
            self._record_status(txid, TxStatus.INVALID)
            return TER.temINVALID, False
        if flags & SF_SIGGOOD:
            tx.set_sig_verdict(True)
        elif not self._plane_check_sign(tx):
            self.router.set_flag(txid, SF_BAD)
            self.stats["bad_sig"] += 1
            self._record_status(txid, TxStatus.INVALID)
            return TER.temINVALID, False
        else:
            self.router.set_flag(txid, SF_SIGGOOD)

        params = TxParams.OPEN_LEDGER
        if admin:
            params |= TxParams.ADMIN
        txq = self.txq
        use_txq = txq is not None and txq.enabled
        with self.master_lock:
            if self.fee_track is not None:
                # load-scaled open-ledger fee: Transactor::payFee reads the
                # ledger's load_factor (reference: scaleFeeLoad via
                # LoadFeeTrack) and rejects under-payers with telINSUF_FEE_P.
                # The NETWORK floor only (local + remote) — never the queue
                # escalation component: TxQ.admit already prices admission,
                # and folding it here would double-gate — the stamped value
                # rides open_successor into the next window, where payFee
                # would reject the very txs the queue is promoting
                # (telINSUF_FEE_P -> retriable -> promotion starves).
                self.lm.current_ledger().load_factor = self.fee_track.network_floor
            if use_txq:
                # admission control: soft open-ledger cap + escalating
                # fee; under-payers above the cap queue (terQUEUED) or
                # shed, terPRE_SEQ holds fold into the queue fee-ordered
                ter, did_apply = txq.admit(tx, self.lm, params)
            else:
                ter, did_apply = self.lm.do_transaction(tx, params)
        self.stats["processed"] += 1

        # status bookkeeping (reference :500-533). Only tem (malformed) is
        # permanently bad — tel (transient local, e.g. telINSUF_FEE_P under
        # load) and tef must stay resubmittable.
        if ter == TER.tesSUCCESS or did_apply:
            status = TxStatus.INCLUDED
        elif ter.is_tem:
            status = TxStatus.INVALID
            self.router.set_flag(txid, SF_BAD)
        elif ter == TER.terQUEUED:
            # waiting in the admission queue for a later ledger
            status = TxStatus.HELD
            self.stats["queued"] = self.stats.get("queued", 0) + 1
        elif ter == TER.terPRE_SEQ:
            # future sequence: hold for the next ledger (reference
            # :516-524). With the TxQ enabled admit() already queued or
            # shed it and never returns terPRE_SEQ from this path.
            if not use_txq:
                self.lm.add_held_transaction(tx)
            status = TxStatus.HELD
            self.stats["held"] += 1
        else:
            status = TxStatus.INVALID if int(ter) < 0 else TxStatus.INCLUDED
        self._record_status(txid, status)

        for sink in self.on_proposed_tx:
            sink(tx, ter)

        # relay seam (overlay broadcast; no-op in standalone). The
        # SF_RELAYED flag is only CONSUMED when the tx actually applied:
        # a transiently-failing submission (e.g. telINSUF_FEE_P under
        # load) must still relay on its later successful resubmit, while
        # a successful one must not become a per-resubmit broadcast
        # amplifier (swap_set returns newly-set exactly for this gate).
        # A QUEUED tx relays only once it meets the current NETWORK fee
        # floor (other nodes would drop an under-payer anyway); a queued
        # tx below the floor relays when promotion applies it
        # (publish_closed_ledger drains TxQ.drain_relay).
        if not ter.is_tem and (did_apply or ter == TER.terPRE_SEQ):
            self.relay_applied(tx)
        elif ter == TER.terQUEUED and txq is not None and (
            txq.meets_network_floor(tx, self.lm.current_ledger())
        ):
            # a queued tx at the network floor relays, but is NOT
            # LocalTxs-tracked yet: the queue owns its retry, and the
            # validator's LocalTxs re-apply would bypass admission
            # (tracking starts when promotion applies it — see
            # publish_closed_ledger's drain)
            self.relay_applied(tx, track=False)
        return ter, did_apply

    def relay_applied(self, tx: SerializedTransaction,
                      track: bool = True) -> bool:
        """Relay (+ optional local-retry tracking) for a tx this node
        accepted — shared by the submit path and the TxQ promotion
        drain. The SF_RELAYED swap_set gate makes the broadcast
        exactly-once per txid; returns whether THIS call won it."""
        prev_peers, newly = self.router.swap_set(
            tx.txid(), set(), SF_RELAYED
        )
        if newly:
            if self.relay_tx is not None:
                # prev_peers = peers this tx already arrived from;
                # they are excluded from the fan-out
                self.relay_tx(tx, prev_peers)
            if track and self.local_push is not None:
                self.local_push(self.lm.closed_ledger().seq, tx)
        return newly

    # -- standalone close (reference: NetworkOPs::acceptLedger) ------------

    def accept_ledger(self) -> tuple[Ledger, dict[bytes, TER]]:
        """Close the open ledger immediately (standalone `ledger_accept`
        admin RPC; the JS integration tests drive closes this way,
        SURVEY §4.3)."""
        ex = getattr(self.lm, "spec_executor", None)
        if ex is not None and ex.active:
            # advisory pre-drain OUTSIDE the close lock: let in-flight
            # worker speculation commit while submissions can still
            # interleave, so the in-lock drain inside close_and_advance
            # is (usually) a no-op and the lock hold stays at splice
            # cost. Never forces — the close-side drain owns that.
            spec = getattr(self.lm.current, "_spec_state", None)
            session = getattr(spec, "_exec_session", None) if spec else None
            if session is not None:
                ex.drain(session, timeout=1.0, force=False)
                # the drain just landed a burst of building-tree folds;
                # hash them on the background drainer BEFORE the close
                # takes the lock, not inside its seal window (bounded
                # wait — still outside every lock)
                self.lm.kick_seal_drain(wait_s=0.25)
        with self.master_lock:
            if self.fee_track is not None:
                # refresh before close: held-tx retries inside
                # close_and_advance must see the CURRENT load, not the
                # factor stamped by the last submission. NETWORK floor
                # only, same as the submit path: the queue-escalation
                # component must never reach a window payFee gates, or
                # promotion double-prices the txs the queue admits
                self.lm.current_ledger().load_factor = self.fee_track.network_floor
            closed, results = self.lm.close_and_advance(
                close_time=self.network_time(),
                close_resolution=self.lm.closed_ledger().close_resolution,
            )
        self.publish_closed_ledger(closed, results)
        return closed, results

    def publish_closed_ledger(
        self, closed: Ledger, results: dict[bytes, TER]
    ) -> None:
        """Status promotion + ledger-closed sinks, shared by the
        standalone close above and the networked consensus path (the
        WS ledger/transactions streams hang off on_ledger_closed)."""
        if self.txq is not None:
            # promoted txs whose relay waited out the chain lock (and
            # the fee floor) broadcast here, outside the close path —
            # BEFORE the COMMITTED promotion below: a deferred-promoted
            # tx commits in the very close being published, and its
            # HELD->INCLUDED transition must land first or it would
            # stay INCLUDED forever. Promotion applied it, so it always
            # (re-)enters LocalTxs tracking even when the fee floor
            # already relayed it at queue time.
            for tx in self.txq.drain_relay():
                self._record_status(tx.txid(), TxStatus.INCLUDED)
                if not self.relay_applied(tx) and self.local_push is not None:
                    self.local_push(self.lm.closed_ledger().seq, tx)
        for txid, _ter in results.items():
            if self.on_tx_result.get(txid) == TxStatus.INCLUDED:
                self._record_status(txid, TxStatus.COMMITTED)
        for sink in self.on_ledger_closed:
            sink(closed, results)
        if self.read_plane is not None:
            # hand the serving side its persisted-tip floor — AFTER the
            # sinks, so by the time the validated-seq cache opens this
            # epoch the persistence pipeline already holds the ledger's
            # entry and the SQL-index RPCs' read-your-writes wait
            # (_await_history) covers it; in networked mode this whole
            # method runs post-persist on the drain worker. The read
            # plane publishes min(persisted, validated): a degraded
            # solo close never masquerades as validated state, and on a
            # quorum net the epoch opens when the validation floor
            # catches up (LedgerMaster.on_validated -> note_validated).
            self.read_plane.note_persisted(closed)

    def _record_status(self, txid: bytes, status: TxStatus) -> None:
        m = self.on_tx_result
        m.pop(txid, None)
        m[txid] = status
        while len(m) > self.max_tx_results:
            m.pop(next(iter(m)))

    # -- introspection ----------------------------------------------------

    def server_state(self) -> str:
        return {
            OperatingMode.DISCONNECTED: "disconnected",
            OperatingMode.CONNECTED: "connected",
            OperatingMode.SYNCING: "syncing",
            OperatingMode.TRACKING: "tracking",
            OperatingMode.FULL: "full",
        }[self.mode]
